"""Host peak-RSS proof for ``shard_residency=device`` (run per-mode in
a fresh subprocess by test_sharding.py; one construct+train per
process so the comparison is a difference of lifetime VmHWM peaks with
the interpreter baseline cancelling — the test_two_round.py pattern).

The dataset streams in through a generator source (the dense float
matrix never exists, docs/DATA.md), so the host-side footprints in
play are the binned matrix and the training buffers:

- ``host`` residency keeps the host numpy bins AND a device copy alive
  through training — peak carries both plus the training buffers;
- ``device`` residency frees the host copy right after the mesh upload
  (parallel/placement.py), so training buffers grow from a floor one
  binned matrix lower.

Reports one JSON line: ``vmhwm_kb`` (null when /proc omits VmHWM —
the test skips there), ``bins_mb``, and ``host_binned_bytes`` after
training (0 under device residency: the measured "no host holds the
binned matrix" claim).

Usage: python sharding_mem_worker.py <host|device>
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.data import GeneratorChunkSource  # noqa: E402

MODE = sys.argv[1]
N = 1 << 20
F = 24
CHUNK = 1 << 15


def chunks():
    start = 0
    while start < N:
        c = min(CHUNK, N - start)
        rs = np.random.RandomState(start % (2 ** 31 - 1))
        Xc = rs.randn(c, F).astype(np.float32)
        yc = (Xc[:, 0] + 0.3 * Xc[:, 1] > 0).astype(np.float64)
        yield Xc, yc
        start += c


def vmhwm_kb():
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return None


def main():
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "bin_construct_sample_cnt": 20000,
              "ingest_chunk_rows": CHUNK, "min_data_in_leaf": 20,
              "shard_residency": MODE, "verbosity": -1}
    src = GeneratorChunkSource(chunks, num_rows=N, num_features=F)
    ds = lgb.Dataset(src, params=params)
    ds.construct()
    bins_mb = ds.host_bins().nbytes / 2 ** 20
    lgb.train(params, ds, num_boost_round=3)
    resident = 0 if ds._bins is None else int(ds._bins.nbytes)
    print(json.dumps({
        "mode": MODE,
        "vmhwm_kb": vmhwm_kb(),
        "bins_mb": round(bins_mb, 1),
        "host_binned_bytes": resident,
    }))


if __name__ == "__main__":
    main()
