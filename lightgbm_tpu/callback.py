"""Training callbacks.

Re-design of /root/reference/python-package/lightgbm/callback.py:
``log_evaluation`` (:109), ``record_evaluation`` (:183),
``reset_parameter`` (:254), ``early_stopping`` (:454 /
``_EarlyStoppingCallback`` :278). The callback protocol (CallbackEnv,
before/after ordering, EarlyStopException unwinding) matches the
reference so user callbacks port unchanged.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from .utils.log import log_info, log_warning

__all__ = ["EarlyStopException", "CallbackEnv", "log_evaluation",
           "record_evaluation", "reset_parameter", "early_stopping"]


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _fmt_eval(res: Tuple) -> str:
    if len(res) == 4:
        return f"{res[0]}'s {res[1]}: {res[2]:g}"
    return f"{res[0]}'s {res[1]}: {res[2]:g} + {res[4]:g}"


class _LogEvaluationCallback:
    order = 10

    def __init__(self, period: int = 1, show_stdv: bool = True):
        self.period = period
        self.show_stdv = show_stdv
        self.before_iteration = False

    def __call__(self, env: CallbackEnv) -> None:
        if self.period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % self.period == 0:
            result = "\t".join(
                _fmt_eval(x) for x in env.evaluation_result_list)
            log_info(f"[{env.iteration + 1}]\t{result}")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    return _LogEvaluationCallback(period=period, show_stdv=show_stdv)


class _RecordEvaluationCallback:
    order = 20

    def __init__(self, eval_result: Dict):
        if not isinstance(eval_result, dict):
            raise TypeError("eval_result should be a dictionary")
        self.eval_result = eval_result
        self.before_iteration = False

    def _init(self, env: CallbackEnv) -> None:
        self.eval_result.clear()
        for item in env.evaluation_result_list:
            data_name, eval_name = item[0], item[1]
            self.eval_result.setdefault(data_name, collections.OrderedDict())
            if len(item) == 4:
                self.eval_result[data_name].setdefault(eval_name, [])
            else:
                self.eval_result[data_name].setdefault(eval_name, [])
                self.eval_result[data_name].setdefault(
                    f"{eval_name}-stdv", [])

    def __call__(self, env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            self._init(env)
        for item in env.evaluation_result_list:
            if len(item) == 4:
                data_name, eval_name, result = item[:3]
                self.eval_result[data_name][eval_name].append(result)
            else:
                data_name, eval_name, result, _, stdv = item
                self.eval_result[data_name][eval_name].append(result)
                self.eval_result[data_name][f"{eval_name}-stdv"].append(stdv)


def record_evaluation(eval_result: Dict) -> Callable:
    return _RecordEvaluationCallback(eval_result)


class _ResetParameterCallback:
    order = 10

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        self.before_iteration = True

    def __call__(self, env: CallbackEnv) -> None:
        new_parameters = {}
        for key, value in self.kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {key!r} has to equal to "
                        "'num_boost_round'.")
                new_param = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_param = value(env.iteration - env.begin_iteration)
            else:
                raise ValueError("Only list and callable values are "
                                 "supported as a mapping from boosting "
                                 "round index to new parameter value.")
            if new_param != env.params.get(key, None):
                new_parameters[key] = new_param
        if new_parameters:
            if "learning_rate" in new_parameters and env.model is not None:
                env.model._engine._shrinkage = \
                    new_parameters["learning_rate"]
            env.params.update(new_parameters)


def reset_parameter(**kwargs) -> Callable:
    return _ResetParameterCallback(**kwargs)


class _EarlyStoppingCallback:
    """Early stopping on validation metrics (callback.py:278)."""

    order = 30

    def __init__(self, stopping_rounds: int, first_metric_only: bool = False,
                 verbose: bool = True,
                 min_delta: Union[float, List[float]] = 0.0):
        if stopping_rounds <= 0:
            raise ValueError("stopping_rounds should be greater than zero.")
        self.stopping_rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.min_delta = min_delta
        self.before_iteration = False
        self.enabled = True
        self._reset_storages()

    def _reset_storages(self) -> None:
        self.best_score: List[float] = []
        self.best_iter: List[int] = []
        self.best_score_list: List[Any] = []
        self.cmp_op: List[Callable[[float, float], bool]] = []
        self.first_metric = ""

    def _init(self, env: CallbackEnv) -> None:
        self._reset_storages()
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        n_metrics = len({m[1] for m in env.evaluation_result_list})
        n_datasets = len(env.evaluation_result_list) // max(n_metrics, 1)
        if isinstance(self.min_delta, list):
            if len(self.min_delta) != n_metrics:
                raise ValueError(
                    "Must provide a single value for min_delta or as many "
                    "as metrics.")
            if self.first_metric_only and self.verbose:
                log_info(f"Using only {self.min_delta[0]} as early "
                         "stopping min_delta.")
            deltas = self.min_delta * n_datasets
        else:
            if self.min_delta < 0:
                raise ValueError("Early stopping min_delta must be "
                                 "non-negative.")
            deltas = [self.min_delta] * n_datasets * n_metrics
        self.first_metric = env.evaluation_result_list[0][1]
        for eval_ret, delta in zip(env.evaluation_result_list, deltas):
            self.best_iter.append(0)
            if eval_ret[3]:  # higher is better
                self.best_score.append(float("-inf"))
                self.cmp_op.append(partial(self._gt_delta, delta=delta))
            else:
                self.best_score.append(float("inf"))
                self.cmp_op.append(partial(self._lt_delta, delta=delta))
            self.best_score_list.append(None)

    @staticmethod
    def _gt_delta(curr: float, best: float, delta: float) -> bool:
        return curr > best + delta

    @staticmethod
    def _lt_delta(curr: float, best: float, delta: float) -> bool:
        return curr < best - delta

    def _final_iteration_check(self, env, eval_name_splitted, i) -> None:
        if env.iteration == env.end_iteration - 1:
            if self.verbose:
                best = "\t".join(
                    _fmt_eval(x) for x in self.best_score_list[i])
                log_info("Did not meet early stopping. Best iteration is:"
                         f"\n[{self.best_iter[i] + 1}]\t{best}")
                if self.first_metric_only:
                    log_info(f"Evaluated only: {eval_name_splitted[-1]}")
            raise EarlyStopException(self.best_iter[i],
                                     self.best_score_list[i])

    def __call__(self, env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            self._init(env)
        if not self.enabled:
            return
        for i in range(len(env.evaluation_result_list)):
            score = env.evaluation_result_list[i][2]
            if self.best_score_list[i] is None \
                    or self.cmp_op[i](score, self.best_score[i]):
                self.best_score[i] = score
                self.best_iter[i] = env.iteration
                self.best_score_list[i] = env.evaluation_result_list
            eval_name_splitted = env.evaluation_result_list[i][1].split(" ")
            if self.first_metric_only \
                    and self.first_metric != eval_name_splitted[-1]:
                continue
            if env.evaluation_result_list[i][0] == "cv_agg" \
                    and eval_name_splitted[0] == "train":
                continue
            if env.model is not None and env.evaluation_result_list[i][0] \
                    == env.model._train_data_name:
                continue
            if env.iteration - self.best_iter[i] >= self.stopping_rounds:
                if self.verbose:
                    best = "\t".join(
                        _fmt_eval(x) for x in self.best_score_list[i])
                    log_info("Early stopping, best iteration is:"
                             f"\n[{self.best_iter[i] + 1}]\t{best}")
                    if self.first_metric_only:
                        log_info(
                            f"Evaluated only: {eval_name_splitted[-1]}")
                raise EarlyStopException(self.best_iter[i],
                                         self.best_score_list[i])
            self._final_iteration_check(env, eval_name_splitted, i)


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True,
                   min_delta: Union[float, List[float]] = 0.0) -> Callable:
    return _EarlyStoppingCallback(stopping_rounds=stopping_rounds,
                                  first_metric_only=first_metric_only,
                                  verbose=verbose, min_delta=min_delta)
