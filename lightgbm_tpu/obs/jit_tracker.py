"""Recompile tracking for jitted hot-path entry points.

A silent XLA recompile is the single most expensive event this codebase
can hit mid-training (PROFILE.md's 530 ms/iter regression class), and it
never announces itself. Every jitted boosting-path entry point registers
here (``register_jit``); the per-function compile-cache size
(``PjitFunction._cache_size``) is then a direct compile counter — a
cache miss IS a compilation — and :class:`RecompileWatcher` turns the
sizes into per-interval deltas for the JSONL event stream.

Registration keys on ``(name, seq)`` with a monotonic sequence number:
rebuilding an entry point (the fused step is re-jitted after
``reset_parameter``; cv builds one per fold) registers a NEW key whose
whole cache size counts as fresh compiles, so replacement never hides
work behind a shrinking counter — and a recycled object address
(``id()`` reuse after GC) can never alias a new function onto a dead
entry. Entries hold their callables by WEAKREF and retire once the
callable is collected (the OOM ladder's jit rebuilds and the engine's
``_scan_fns`` resets would otherwise leave dead functions' last cache
sizes in ``jit_cache_sizes()``/``total_recompiles()`` forever —
tests/test_metrics_export.py pins the rebuild-then-count behavior).

Since the fleet-metrics PR, ``register_jit`` additionally wraps each
entry point in :class:`~lightgbm_tpu.obs.cost.CostTracked` (XLA cost
attribution: one ``{"event": "compile"}`` record with flops/bytes per
first compile per signature; LIGHTGBM_TPU_COST_ATTRIBUTION=0
disables). Definition sites therefore REBIND the registered name —
``fn = register_jit("x", fn)`` — so calls route through the wrapper;
the wrapper proxies ``_cache_size`` and the AOT surface, so this
module's polling is unchanged.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, Tuple

__all__ = ["register_jit", "jit_cache_sizes", "total_recompiles",
           "jit_declarations", "RecompileWatcher"]

_lock = threading.Lock()
# (name, seq) -> weakref to the jitted callable; weak so per-booster
# fused functions don't outlive their engine
_tracked: Dict[Tuple[str, int], "weakref.ref"] = {}
_seq = 0
# name -> declared recompile surface: the number of distinct call
# signatures the entry point is ALLOWED to compile over a process
# lifetime (the pow2 serve buckets, the per-(W, bag_live) scan
# variants, ...). ``lint --ir`` (analysis/ircheck.py, TPL014) demands a
# declaration at every register_jit site and the telemetry consistency
# test cross-checks jit_cache_sizes() against it — an entry whose
# cache outgrows its declaration is a recompile storm by definition.
_declared: Dict[str, int] = {}


def register_jit(name: str, fn: Callable,
                 max_signatures: int = None) -> Callable:
    """Track a jitted callable's compile cache and wrap it for XLA
    cost attribution; returns the (wrapped) callable, so definition
    sites rebind: ``fn = register_jit("name", fn)``. Non-jitted
    callables (no ``_cache_size``) are accepted and returned
    unchanged — callers never need to branch. Re-registering the same
    live object (or its already-registered wrapper) under the same
    name returns the existing wrapper, never a duplicate entry.

    ``max_signatures`` declares the entry point's recompile surface:
    the maximum number of distinct trace signatures the function is
    expected to compile. The declaration is advisory at runtime (no
    enforcement here — a hot path must never raise over telemetry) but
    is enforced statically by ``lint --ir`` (TPL014) and dynamically by
    the telemetry consistency test."""
    global _seq
    if max_signatures is not None:
        with _lock:
            prev = _declared.get(name)
            _declared[name] = max(prev, max_signatures) \
                if prev is not None else max_signatures
    if not hasattr(fn, "_cache_size"):
        return fn
    from .cost import CostTracked, cost_wrap_enabled
    with _lock:
        for (tracked_name, _), r in _tracked.items():
            if tracked_name != name:
                continue
            live = r()
            if live is fn or getattr(live, "unwrapped", None) is fn:
                return live
    if cost_wrap_enabled() and not isinstance(fn, CostTracked):
        fn = CostTracked(name, fn)
    try:
        ref = weakref.ref(fn)
    except TypeError:  # not weakref-able; keep a strong closure
        ref = (lambda f: (lambda: f))(fn)
    with _lock:
        _seq += 1
        _tracked[(name, _seq)] = ref
    return fn


def jit_cache_sizes() -> Dict[Tuple[str, int], int]:
    """Current compile-cache size per live tracked function."""
    out: Dict[Tuple[str, int], int] = {}
    dead = []
    with _lock:
        items = list(_tracked.items())
    for key, ref in items:
        fn = ref()
        if fn is None:
            dead.append(key)
            continue
        try:
            out[key] = int(fn._cache_size())
        except Exception:
            out[key] = 0
    if dead:
        with _lock:
            for key in dead:
                _tracked.pop(key, None)
    return out


def total_recompiles() -> int:
    """Total compilations across all live tracked entry points."""
    return sum(jit_cache_sizes().values())


def jit_declarations() -> Dict[str, int]:
    """Declared recompile surface per entry name (``max_signatures``
    passed to :func:`register_jit`). Re-registrations keep the largest
    declaration seen (cv folds / rebuilt fused steps re-declare)."""
    with _lock:
        return dict(_declared)


class RecompileWatcher:
    """Delta view over the tracked cache sizes.

    ``delta()`` returns compilations since the previous ``delta()`` (or
    construction): new keys contribute their full size, grown keys the
    growth. A function garbage-collected between calls simply drops out;
    its past compiles were already reported.
    """

    def __init__(self):
        self._last = jit_cache_sizes()
        self.total = 0

    def delta(self) -> int:
        now = jit_cache_sizes()
        d = 0
        for key, size in now.items():
            d += max(0, size - self._last.get(key, 0))
        self._last = now
        self.total += d
        return d
