"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

A from-scratch re-design of the LightGBM feature set
(reference: /root/reference, PieterPel/LightGBM @ 4.6.0.99) on JAX/XLA:
histogram-based leaf-wise GBDT with the binned data, gradients and
histograms resident in HBM; collectives over a `jax.sharding.Mesh`
instead of sockets/MPI; and a drop-in `Dataset`/`Booster`/`train` Python
API mirroring the reference python-package.

Importing this package is LAZY (PEP 562): the training stack — and
with it jax — only loads when a training/data symbol is first touched.
That keeps jax-free tools runnable anywhere: ``python -m lightgbm_tpu
lint`` (the tpulint static analyzer, docs/STATIC_ANALYSIS.md) must work
in environments that cannot initialize any jax backend at all.
"""

__version__ = "0.1.0"

# symbol -> providing submodule; resolved on first attribute access
_LAZY = {
    "Booster": "basic", "Dataset": "basic", "LightGBMError": "basic",
    "Sequence": "basic",
    "EarlyStopException": "callback", "checkpoint": "callback",
    "early_stopping": "callback", "log_evaluation": "callback",
    "record_evaluation": "callback", "reset_parameter": "callback",
    "telemetry": "callback",
    "Config": "config",
    "CVBooster": "engine", "cv": "engine", "train": "engine",
    "register_logger": "utils.log",
    # optional extras (sklearn / plotting deps may be absent)
    "LGBMModel": "sklearn", "LGBMClassifier": "sklearn",
    "LGBMRegressor": "sklearn", "LGBMRanker": "sklearn",
    "plot_importance": "plotting", "plot_metric": "plotting",
    "plot_split_value_histogram": "plotting", "plot_tree": "plotting",
    "create_tree_digraph": "plotting",
}

__all__ = [
    "Dataset", "Booster", "CVBooster", "LightGBMError",
    "train", "cv",
    "early_stopping", "log_evaluation", "record_evaluation",
    "reset_parameter", "telemetry", "checkpoint", "EarlyStopException",
    "register_logger", "Config",
    "LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker",
    "plot_importance", "plot_metric", "plot_split_value_histogram",
    "plot_tree", "create_tree_digraph",
]


# submodules reachable as attributes (`lightgbm_tpu.basic`, ...) — the
# eager __init__ used to bind these as an import side effect
_SUBMODULES = {
    "analysis", "basic", "callback", "cli", "config", "convert",
    "data", "engine", "metrics", "models", "objectives", "obs", "ops",
    "parallel", "plotting", "prediction", "ranking", "resilience",
    "serve", "shap", "sklearn", "utils",
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None and name not in _SUBMODULES:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    try:
        mod = importlib.import_module(f".{target or name}", __name__)
    except ImportError as e:
        # optional extras: surface as the AttributeError the import
        # protocol expects, with the real cause chained
        raise AttributeError(
            f"{name} is unavailable: importing "
            f"{__name__}.{target or name} failed ({e})") from e
    value = getattr(mod, name) if target is not None else mod
    globals()[name] = value  # cache: __getattr__ runs once per symbol
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
