# tpulint fixture: TPL008 negative — the same autoscaling policy as
# resilience/tpl008_pos.py with every scrape/decide-shared field
# guarded by one common lock (the resilience/autoscale.py discipline:
# observations in on the scrape thread, decisions out on the
# supervision loop, every byte of shared state under self._lock).
# No EXPECT lines.
import threading


class Policy:
    def __init__(self):
        self._lock = threading.Lock()
        self.qps = 0.0
        self.seq = 0
        self.scale_ups = 0
        self._scraper = threading.Thread(target=self._scrape_loop,
                                         daemon=True)
        self._scraper.start()

    def _scrape_loop(self):
        while True:
            with self._lock:
                self.qps = 12.5
                self.seq += 1

    def decide(self, n_active):
        with self._lock:
            if self.seq == 0:
                return None
            if self.qps > n_active * 10.0:
                self.scale_ups += 1
                return "up"
            return None

    def snapshot(self):
        with self._lock:
            return {"qps": self.qps, "ups": self.scale_ups}
