# tpulint fixture: TPL008 positive — an autoscaling policy whose
# scrape thread feeds observations into fields the supervision loop's
# decide() reads and mutates with no lock. This is the "strip the
# autoscaler lock" acceptance shape: resilience/tpl008_neg.py is the
# same policy WITH the lock, and removing it must re-surface these
# findings.
import threading


class Policy:
    def __init__(self):
        self.qps = 0.0
        self.seq = 0
        self.scale_ups = 0
        self._scraper = threading.Thread(target=self._scrape_loop,
                                         daemon=True)
        self._scraper.start()

    def _scrape_loop(self):
        while True:
            # EXPECT: TPL008
            self.qps = 12.5
            # EXPECT: TPL008
            self.seq += 1

    def decide(self, n_active):
        if self.seq == 0:
            return None
        if self.qps > n_active * 10.0:
            self.scale_ups += 1
            return "up"
        return None

    def snapshot(self):
        return {"qps": self.qps, "ups": self.scale_ups}
