"""Leaf-wise tree growth as one jitted XLA program.

Re-design of SerialTreeLearner::Train
(/root/reference/src/treelearner/serial_tree_learner.cpp:179-245) and the
device-resident CUDA learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp) for TPU:

- The growth loop runs ``num_leaves - 1`` *static* split steps inside a
  ``lax.fori_loop`` (XLA needs static trip counts); a step whose best gain
  is <= 0 is a no-op, and since nothing changes afterwards all remaining
  steps stay no-ops — equivalent to the reference's early ``break``
  (serial_tree_learner.cpp:225).
- Rows are never compacted per leaf: a ``row_leaf`` vector (the
  DataPartition analog, data_partition.hpp) assigns each row to a leaf
  slot, and leaf histograms are built by masking the per-row payload.
- Leaf slots follow the reference Tree convention (tree.h: ``Split``):
  the left child keeps the parent's leaf slot, the right child takes slot
  ``num_leaves_so_far``; internal node k is created by split k; child
  pointers store ``~leaf`` for leaves.
- Histogram subtraction: only the smaller child is scatter-accumulated,
  the sibling = parent - smaller (serial_tree_learner.cpp:473-520).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import build_histogram, subtract_histogram
from .split import SplitParams, SplitResult, find_best_split, leaf_output

__all__ = ["GrowConfig", "TreeArrays", "grow_tree"]

NEG_INF = -jnp.inf


class GrowConfig(NamedTuple):
    """Static (trace-time) growth configuration.

    ``axis_name``: when set, the grower runs inside shard_map/pjit with
    rows sharded over that mesh axis; histograms and root sums are
    psum-reduced — the TPU analog of the reference's data-parallel
    ReduceScatter+Allreduce (data_parallel_tree_learner.cpp:284-294,
    SURVEY.md §2.6). Split finding then happens identically on every
    device (deterministic), replacing SyncUpGlobalBestSplit.
    """
    num_leaves: int
    num_bins: int
    max_depth: int = -1
    split: SplitParams = SplitParams()
    hist_method: str = "scatter"
    axis_name: Optional[str] = None


class TreeArrays(NamedTuple):
    """Flat-tensor tree (the Tree class re-imagined as arrays;
    include/LightGBM/tree.h:63-252). Sizes: L leaves, L-1 internal nodes."""
    split_feature: jnp.ndarray   # [L-1] i32
    threshold_bin: jnp.ndarray   # [L-1] i32
    default_left: jnp.ndarray    # [L-1] bool
    left_child: jnp.ndarray      # [L-1] i32 (~leaf for leaves)
    right_child: jnp.ndarray     # [L-1] i32
    split_gain: jnp.ndarray      # [L-1] f32
    internal_value: jnp.ndarray  # [L-1] f32
    internal_weight: jnp.ndarray  # [L-1] f32
    internal_count: jnp.ndarray  # [L-1] f32
    leaf_value: jnp.ndarray      # [L] f32
    leaf_weight: jnp.ndarray     # [L] f32 (sum of hessians)
    leaf_count: jnp.ndarray      # [L] f32
    leaf_parent: jnp.ndarray     # [L] i32
    leaf_depth: jnp.ndarray      # [L] i32
    num_leaves: jnp.ndarray      # scalar i32 (actual leaves grown)
    split_is_cat: jnp.ndarray    # [L-1] bool — categorical membership split
    split_cat_mask: jnp.ndarray  # [L-1, B] bool — bins routed left


class _BestSplits(NamedTuple):
    """Per-leaf-slot best candidate split (the SplitInfo-per-leaf arrays)."""
    gain: jnp.ndarray
    feature: jnp.ndarray
    threshold_bin: jnp.ndarray
    default_left: jnp.ndarray
    is_cat: jnp.ndarray        # [L] bool
    cat_mask: jnp.ndarray      # [L, B] bool
    left_sum_g: jnp.ndarray
    left_sum_h: jnp.ndarray
    left_count: jnp.ndarray
    right_sum_g: jnp.ndarray
    right_sum_h: jnp.ndarray
    right_count: jnp.ndarray
    left_output: jnp.ndarray
    right_output: jnp.ndarray

    @staticmethod
    def init(L: int, B: int, dtype) -> "_BestSplits":
        zf = jnp.zeros((L,), dtype=dtype)
        return _BestSplits(
            gain=jnp.full((L,), NEG_INF, dtype=dtype),
            feature=jnp.zeros((L,), jnp.int32),
            threshold_bin=jnp.zeros((L,), jnp.int32),
            default_left=jnp.zeros((L,), jnp.bool_),
            is_cat=jnp.zeros((L,), jnp.bool_),
            cat_mask=jnp.zeros((L, B), jnp.bool_),
            left_sum_g=zf, left_sum_h=zf, left_count=zf,
            right_sum_g=zf, right_sum_h=zf, right_count=zf,
            left_output=zf, right_output=zf,
        )

    def store(self, i, r: SplitResult, allowed) -> "_BestSplits":
        gain = jnp.where(allowed, r.gain, NEG_INF)
        return _BestSplits(
            gain=self.gain.at[i].set(gain),
            feature=self.feature.at[i].set(r.feature),
            threshold_bin=self.threshold_bin.at[i].set(r.threshold_bin),
            default_left=self.default_left.at[i].set(r.default_left),
            is_cat=self.is_cat.at[i].set(r.is_cat),
            cat_mask=self.cat_mask.at[i].set(r.cat_mask),
            left_sum_g=self.left_sum_g.at[i].set(r.left_sum_g),
            left_sum_h=self.left_sum_h.at[i].set(r.left_sum_h),
            left_count=self.left_count.at[i].set(r.left_count),
            right_sum_g=self.right_sum_g.at[i].set(r.right_sum_g),
            right_sum_h=self.right_sum_h.at[i].set(r.right_sum_h),
            right_count=self.right_count.at[i].set(r.right_count),
            left_output=self.left_output.at[i].set(r.left_output),
            right_output=self.right_output.at[i].set(r.right_output),
        )


class _GrowState(NamedTuple):
    tree: TreeArrays
    best: _BestSplits
    hists: jnp.ndarray      # [L, F, B, 3]
    row_leaf: jnp.ndarray   # [n] i32
    num_splits: jnp.ndarray  # scalar i32


def _init_tree(L: int, B: int, dtype) -> TreeArrays:
    return TreeArrays(
        split_is_cat=jnp.zeros((L - 1,), jnp.bool_),
        split_cat_mask=jnp.zeros((L - 1, B), jnp.bool_),
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        threshold_bin=jnp.zeros((L - 1,), jnp.int32),
        default_left=jnp.zeros((L - 1,), jnp.bool_),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), dtype),
        internal_value=jnp.zeros((L - 1,), dtype),
        internal_weight=jnp.zeros((L - 1,), dtype),
        internal_count=jnp.zeros((L - 1,), dtype),
        leaf_value=jnp.zeros((L,), dtype),
        leaf_weight=jnp.zeros((L,), dtype),
        leaf_count=jnp.zeros((L,), dtype),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
    )


def grow_tree_impl(cfg: GrowConfig,
                   bins_T: jnp.ndarray,
                   grad: jnp.ndarray,
                   hess: jnp.ndarray,
                   row_weight: jnp.ndarray,
                   feature_mask: jnp.ndarray,
                   feat_num_bins: jnp.ndarray,
                   feat_nan_bin: jnp.ndarray,
                   monotone_constraints: Optional[jnp.ndarray] = None,
                   feat_is_cat: Optional[jnp.ndarray] = None):
    """Grow one leaf-wise tree. Returns (TreeArrays, row_leaf).

    Args:
      bins_T: [F, n] uint8/uint16 bin matrix.
      grad/hess: [n] float.
      row_weight: [n] float sampling weight (bagging/GOSS; 1.0 = use row).
      feature_mask: [F] bool usable-feature mask (feature_fraction etc).
      feat_num_bins / feat_nan_bin: [F] i32 per-feature bin metadata.
    """
    L = cfg.num_leaves
    B = cfg.num_bins
    F = bins_T.shape[0]
    n = bins_T.shape[1]
    dtype = grad.dtype
    p = cfg.split

    def psum(x):
        return lax.psum(x, cfg.axis_name) if cfg.axis_name else x

    def best_for(hist, sg, sh, sc):
        return find_best_split(hist, sg, sh, sc, feat_num_bins, feat_nan_bin,
                               feature_mask, p, monotone_constraints,
                               feat_is_cat)

    # ---- root (GlobalSyncUpBySum analog for the root tuple) ----
    w = row_weight.astype(dtype)
    total_g = psum(jnp.sum(grad * w))
    total_h = psum(jnp.sum(hess * w))
    total_c = psum(jnp.sum(w))
    all_rows = jnp.ones((n,), jnp.bool_)
    root_hist = psum(build_histogram(bins_T, grad, hess, row_weight,
                                     all_rows, B, cfg.hist_method))

    tree = _init_tree(L, B, dtype)
    tree = tree._replace(
        leaf_value=tree.leaf_value.at[0].set(leaf_output(total_g, total_h, p)),
        leaf_weight=tree.leaf_weight.at[0].set(total_h),
        leaf_count=tree.leaf_count.at[0].set(total_c),
    )
    best = _BestSplits.init(L, B, dtype)
    best = best.store(0, best_for(root_hist, total_g, total_h, total_c),
                      jnp.asarray(True))
    hists = jnp.zeros((L, F, B, 3), dtype).at[0].set(root_hist)
    state = _GrowState(tree=tree, best=best, hists=hists,
                       row_leaf=jnp.zeros((n,), jnp.int32),
                       num_splits=jnp.asarray(0, jnp.int32))

    def depth_ok(d):
        if cfg.max_depth <= 0:
            return jnp.asarray(True)
        return d < cfg.max_depth

    def do_split(state: _GrowState) -> _GrowState:
        tree, best, hists, row_leaf, ns = state
        leaf = jnp.argmax(best.gain).astype(jnp.int32)
        R = ns + 1  # new (right-child) leaf slot
        f = best.feature[leaf]
        t = best.threshold_bin[leaf]
        dl = best.default_left[leaf]

        # -- partition rows of `leaf` (DataPartition::Split analog) --
        col = lax.dynamic_index_in_dim(bins_T, f, axis=0,
                                       keepdims=False).astype(jnp.int32)
        nan_bin = feat_nan_bin[f]
        go_left_num = jnp.where((nan_bin >= 0) & (col == nan_bin), dl,
                                col <= t)
        cm = best.cat_mask[leaf]
        go_left = jnp.where(best.is_cat[leaf], cm[col], go_left_num)
        on_leaf = row_leaf == leaf
        row_leaf = jnp.where(on_leaf & ~go_left, R, row_leaf)

        # -- tree arrays update (Tree::Split, tree.h:63) --
        parent = tree.leaf_parent[leaf]
        pidx = jnp.maximum(parent, 0)
        lc = tree.left_child
        rc = tree.right_child
        lc = lc.at[pidx].set(jnp.where((parent >= 0) & (lc[pidx] == ~leaf),
                                       ns, lc[pidx]))
        rc = rc.at[pidx].set(jnp.where((parent >= 0) & (rc[pidx] == ~leaf),
                                       ns, rc[pidx]))
        lc = lc.at[ns].set(~leaf)
        rc = rc.at[ns].set(~R)
        parent_g = best.left_sum_g[leaf] + best.right_sum_g[leaf]
        parent_h = best.left_sum_h[leaf] + best.right_sum_h[leaf]
        parent_c = best.left_count[leaf] + best.right_count[leaf]
        new_depth = tree.leaf_depth[leaf] + 1
        tree = tree._replace(
            split_feature=tree.split_feature.at[ns].set(f),
            threshold_bin=tree.threshold_bin.at[ns].set(t),
            default_left=tree.default_left.at[ns].set(dl),
            split_is_cat=tree.split_is_cat.at[ns].set(best.is_cat[leaf]),
            split_cat_mask=tree.split_cat_mask.at[ns].set(cm),
            left_child=lc,
            right_child=rc,
            split_gain=tree.split_gain.at[ns].set(best.gain[leaf]),
            internal_value=tree.internal_value.at[ns].set(
                leaf_output(parent_g, parent_h, p)),
            internal_weight=tree.internal_weight.at[ns].set(parent_h),
            internal_count=tree.internal_count.at[ns].set(parent_c),
            leaf_value=tree.leaf_value.at[leaf].set(best.left_output[leaf])
            .at[R].set(best.right_output[leaf]),
            leaf_weight=tree.leaf_weight.at[leaf].set(best.left_sum_h[leaf])
            .at[R].set(best.right_sum_h[leaf]),
            leaf_count=tree.leaf_count.at[leaf].set(best.left_count[leaf])
            .at[R].set(best.right_count[leaf]),
            leaf_parent=tree.leaf_parent.at[leaf].set(ns).at[R].set(ns),
            leaf_depth=tree.leaf_depth.at[leaf].set(new_depth)
            .at[R].set(new_depth),
            num_leaves=tree.num_leaves + 1,
        )

        # -- histograms: scatter the smaller child, subtract for sibling --
        left_smaller = best.left_count[leaf] <= best.right_count[leaf]
        small_slot = jnp.where(left_smaller, leaf, R)
        small_mask = row_leaf == small_slot
        small_hist = psum(build_histogram(bins_T, grad, hess, row_weight,
                                          small_mask, B, cfg.hist_method))
        parent_hist = hists[leaf]
        big_hist = subtract_histogram(parent_hist, small_hist)
        left_hist = jnp.where(left_smaller, small_hist, big_hist)
        right_hist = jnp.where(left_smaller, big_hist, small_hist)
        hists = hists.at[leaf].set(left_hist).at[R].set(right_hist)

        # -- child best splits --
        can_go_deeper = depth_ok(new_depth)
        rl = best_for(left_hist, best.left_sum_g[leaf],
                      best.left_sum_h[leaf], best.left_count[leaf])
        rr = best_for(right_hist, best.right_sum_g[leaf],
                      best.right_sum_h[leaf], best.right_count[leaf])
        best = best.store(leaf, rl, can_go_deeper)
        best = best.store(R, rr, can_go_deeper)

        return _GrowState(tree=tree, best=best, hists=hists,
                          row_leaf=row_leaf, num_splits=ns + 1)

    def step(_, state: _GrowState) -> _GrowState:
        can = jnp.max(state.best.gain) > 0.0
        return lax.cond(can, do_split, lambda s: s, state)

    state = lax.fori_loop(0, L - 1, step, state)
    return state.tree, state.row_leaf


grow_tree = jax.jit(grow_tree_impl, static_argnames=("cfg",))
