"""Small-table row gathers that compile well on TPU.

``table[idx]`` with a million-row ``idx`` and a tiny table lowers to an
XLA gather that TPUs execute one element at a time (~8.6 ms per million
rows measured — benchmarks/PROFILE.md). The boosting loop needs exactly
this shape in several places (leaf value -> row score contribution, the
reference's ScoreUpdater::AddScore walk, score_updater.hpp:58): a [n]
index vector into an [L <= a few hundred] table. ``gather_small``
replaces it with L sequential full-width selects — O(L * n / lanes)
vector work, ~30x faster at L=255 — while keeping exact dtype semantics
(values are moved bit-for-bit, never re-rounded).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["gather_small"]


@jax.jit
def gather_small(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``table[idx]`` via a fori_loop of vector selects.

    Args:
      table: ``[L]`` values (any dtype); L is static and small.
      idx: ``[n]`` int indices into the table (out-of-range behaves as
        "unchanged zero", matching XLA's drop semantics closely enough
        for in-range callers).
    Returns:
      ``[n]`` array of ``table.dtype``.
    """
    L = table.shape[0]
    init = jnp.zeros(idx.shape, table.dtype)

    def body(l, acc):
        return jnp.where(idx == l, table[l], acc)

    return lax.fori_loop(0, L, body, init)
