"""Continuous train -> publish -> serve lifecycle (ISSUE 13,
docs/PIPELINE.md).

Layers under test:

1. Atomic publisher (resilience/publisher.py): manifest-first
   publication, torn-artifact detection, jittered retry/backoff with
   the publish_torn chaos kind, newest-validated lookup.
2. Warm start: Booster.refit parity with the reference
   FitByExistingTree contract (structures unchanged, leaf values
   re-derived, shifted labels move eval the right direction, fused
   and eager trained forests), the refit-side non-finite guard
   (refit_nan chaos x all three policies), and init_model continued
   training on FRESH data through the PR-7 chunk sources — including
   checkpoint resume finishing at init + num_boost_round.
3. Load shedding (serve/batcher.py SheddingError): queue-depth and
   latency-budget sheds, the daemon's typed {"shed": true} reply.
4. Watch-dir poller resilience: a torn/partial artifact is skipped
   with a swap_failure fault event and RETRIED next poll.
5. Supervisor: RestartBudget sliding window + backoff, one-shot
   serve_kill stripping, and (slow) per-replica fleet restart,
   daemon graceful shutdown, and the full chaos pipeline e2e.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.resilience.elastic import (  # noqa: E402
    RestartBudget, strip_one_shot_faults, supervise)
from lightgbm_tpu.resilience.publisher import (  # noqa: E402
    PublishError, latest_manifest, load_manifest, manifest_path,
    publish_model, validate_artifact)

from tests._mp_utils import REPO_DIR, free_port, kill_group  # noqa: E402
from tests.conftest import make_synthetic_binary  # noqa: E402


def _logloss(p, y):
    p = np.clip(np.asarray(p), 1e-9, 1 - 1e-9)
    y = np.asarray(y)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def _train(params, X, y, rounds=5, **kwargs):
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    return lgb.train({"verbosity": -1, **params}, ds,
                     num_boost_round=rounds, **kwargs)


@pytest.fixture(scope="module")
def binary_model():
    X, y = make_synthetic_binary(n=900, f=8)
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y)
    return bst, X, y


# ---------------------------------------------------------------------
# 1. atomic publisher
# ---------------------------------------------------------------------

def test_publish_roundtrip_and_validation(binary_model, tmp_path):
    bst, X, y = binary_model
    manifest = publish_model(bst, str(tmp_path), "model_g0000.txt",
                             metadata={"generation": 0,
                                       "train_auc": 0.9})
    target = str(tmp_path / "model_g0000.txt")
    assert os.path.exists(target)
    assert os.path.exists(manifest_path(target))
    assert manifest["generation"] == 0
    # the published bytes validate and round-trip to a live model
    assert validate_artifact(target)["sha256"] == manifest["sha256"]
    reloaded = lgb.Booster(model_file=target)
    np.testing.assert_allclose(reloaded.predict(X[:16]),
                               bst.predict(X[:16]), atol=1e-9)
    # newest-validated lookup
    got = latest_manifest(str(tmp_path))
    assert got is not None and got[0] == target
    assert got[1]["sha256"] == manifest["sha256"]


def test_torn_artifact_fails_validation(binary_model, tmp_path):
    bst, _, _ = binary_model
    publish_model(bst, str(tmp_path), "m.txt")
    target = str(tmp_path / "m.txt")
    data = open(target, "rb").read()
    # tear it the way a dying non-atomic writer would: partial prefix
    with open(target, "wb") as fh:
        fh.write(data[: len(data) // 2])
    with pytest.raises(PublishError, match="torn or partial"):
        validate_artifact(target)
    # latest_manifest skips the torn one instead of serving it
    assert latest_manifest(str(tmp_path)) is None
    # unmanaged artifacts (no sidecar) stay legacy: None, no raise
    plain = str(tmp_path / "plain.txt")
    with open(plain, "w") as fh:
        fh.write("hand-dropped model\n")
    assert validate_artifact(plain) is None
    assert load_manifest(plain) is None


def test_publish_torn_chaos_retries_to_success(binary_model, tmp_path,
                                               monkeypatch):
    """publish_torn@G: the first attempt leaves a torn artifact and
    fails; the jittered-backoff retry republishes atomically and the
    final artifact validates."""
    bst, _, _ = binary_model
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "publish_torn@2")
    sleeps = []
    manifest = publish_model(bst, str(tmp_path), "model_g0002.txt",
                             fault_iteration=2, backoff_base_sec=0.01,
                             _sleep=sleeps.append)
    assert len(sleeps) == 1 and sleeps[0] > 0
    target = str(tmp_path / "model_g0002.txt")
    assert validate_artifact(target)["sha256"] == manifest["sha256"]
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS
    assert any(e["kind"] == "publish_torn" for e in FAULT_EVENTS)


def test_publish_exhausted_retries_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT",
                       "publish_torn@1,publish_torn@1,publish_torn@1")
    with pytest.raises(PublishError, match="failed after 3 attempt"):
        publish_model("not really a model", str(tmp_path), "m.txt",
                      retries=2, fault_iteration=1,
                      backoff_base_sec=0.001, _sleep=lambda _: None)


def test_fault_plan_new_kinds(monkeypatch):
    from lightgbm_tpu.resilience.faults import FaultPlan
    plan = FaultPlan("publish_torn@1,serve_kill@5,refit_nan@3")
    assert plan.active
    assert plan.iters("serve_kill") == (5,)
    assert plan.take("refit_nan", 3) and not plan.take("refit_nan", 3)
    # serve_kill gates on LIGHTGBM_TPU_RANK (replica id), NOT
    # jax.process_index(): a non-selected replica never dies
    monkeypatch.setenv("LIGHTGBM_TPU_RANK", "1")
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_RANK", "0")
    plan.maybe_serve_kill(5)          # would SIGKILL us if mis-gated
    assert plan.iters("serve_kill") == (5,)
    # unknown kinds still rejected
    with pytest.raises(ValueError):
        FaultPlan("tea_break@4")


def test_one_shot_strip_includes_serve_kill():
    spec = "serve_kill@25,nan_grad@3,rank_kill@8"
    assert strip_one_shot_faults(spec) == "nan_grad@3"


# ---------------------------------------------------------------------
# 2. warm start: refit parity + init_model incremental data
# ---------------------------------------------------------------------

def _tree_structure(bst):
    return [(list(t.split_feature[: t.num_leaves - 1]),
             [round(float(v), 12)
              for v in t.threshold[: t.num_leaves - 1]])
            for t in bst._models]


@pytest.mark.parametrize("mode", ["fused", "eager"])
def test_refit_reference_contract(mode):
    """FitByExistingTree: tree structures unchanged, leaf values
    re-derived from fresh gradients in boosting order; shifted labels
    move eval the right direction. Both the fused-path and the
    eager-path (valid-set-bearing) trained forests refit."""
    X, y = make_synthetic_binary(n=900, f=8)
    kwargs = {}
    if mode == "eager":
        Xv, yv = make_synthetic_binary(n=200, f=8, seed=11)
        kwargs["valid_sets"] = [lgb.Dataset(Xv, label=yv,
                                            params={"verbosity": -1})]
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y,
                 rounds=6, **kwargs)
    if mode == "eager":
        assert bst._engine._fused_fn is None
    flipped = 1.0 - y
    refitted = bst.refit(X, flipped, decay_rate=0.0)
    # structures byte-for-byte, leaf values re-derived
    assert _tree_structure(refitted) == _tree_structure(bst)
    assert any(
        not np.allclose(a.leaf_value, b.leaf_value)
        for a, b in zip(refitted._models, bst._models))
    # eval moves toward the new labels, and the original is untouched
    assert _logloss(refitted.predict(X), flipped) \
        < _logloss(bst.predict(X), flipped)
    # decay blends: decay=1.0 keeps the old leaf values exactly
    kept = bst.refit(X, flipped, decay_rate=1.0)
    for a, b in zip(kept._models, bst._models):
        np.testing.assert_allclose(a.leaf_value, b.leaf_value,
                                   rtol=0, atol=0)


def test_refit_nan_guard_policies(monkeypatch):
    X, y = make_synthetic_binary(n=600, f=6)
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "refit_nan@1")

    def train_with(policy):
        monkeypatch.delenv("LIGHTGBM_TPU_FAULT_INJECT", raising=False)
        bst = _train({"objective": "binary", "num_leaves": 7,
                      "nonfinite_policy": policy}, X, y, rounds=4)
        monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "refit_nan@1")
        return bst

    bst = train_with("raise")
    with pytest.raises(lgb.LightGBMError, match="tree 1"):
        bst.refit(X, y, decay_rate=0.0)

    bst = train_with("skip_tree")
    refitted = bst.refit(X, y, decay_rate=0.0)
    # the poisoned tree keeps its OLD leaf values; the others refit
    np.testing.assert_allclose(refitted._models[1].leaf_value,
                               bst._models[1].leaf_value,
                               rtol=0, atol=0)
    assert any(e["kind"] == "refit_nan" and e["action"] == "skip_tree"
               for e in refitted._refit_fault_log)
    assert all(np.all(np.isfinite(t.leaf_value))
               for t in refitted._models)

    bst = train_with("clamp")
    refitted = bst.refit(X, y, decay_rate=0.0)
    assert all(np.all(np.isfinite(t.leaf_value))
               for t in refitted._models)


def test_init_model_booster_matches_file_on_fresh_data(tmp_path):
    """Continued training on FRESH data must be identical whether
    init_model is an in-memory Booster or its saved file: the
    in-memory path used to keep stale threshold_bin indices from the
    OLD dataset's bin space (silent mis-binning); both now go through
    the model-text round trip."""
    X0, y0 = make_synthetic_binary(n=700, f=8, seed=3)
    X1, y1 = make_synthetic_binary(n=800, f=8, seed=4)
    X1 = X1 * 1.7 + 0.3          # different bin boundaries on purpose
    params = {"objective": "binary", "num_leaves": 15,
              "verbosity": -1}
    base = _train(params, X0, y0, rounds=4)
    path = str(tmp_path / "base.txt")
    base.save_model(path)
    cont_mem = lgb.train(params, lgb.Dataset(X1, label=y1), 4,
                         init_model=base)
    cont_file = lgb.train(params, lgb.Dataset(X1, label=y1), 4,
                          init_model=path)
    assert cont_mem.model_to_string() == cont_file.model_to_string()
    assert cont_mem.num_trees() == 8


def test_init_model_streamed_chunk_source():
    """The incremental-data path rides the PR-7 chunk sources: fresh
    generation data arrives as a streamed generator source and
    continued training appends to the published forest, identical to
    the eager continuation."""
    from lightgbm_tpu.data.sources import GeneratorChunkSource
    X0, y0 = make_synthetic_binary(n=700, f=8, seed=5)
    X1, y1 = make_synthetic_binary(n=900, f=8, seed=6)
    params = {"objective": "binary", "num_leaves": 15,
              "verbosity": -1}
    base = _train(params, X0, y0, rounds=3)

    def factory():
        for lo in range(0, len(y1), 256):
            yield X1[lo:lo + 256], y1[lo:lo + 256]

    src = GeneratorChunkSource(factory, num_rows=len(y1),
                               num_features=8)
    streamed = lgb.train(
        {**params, "ingest_chunk_rows": 256},
        lgb.Dataset(src, params={"verbosity": -1,
                                 "ingest_chunk_rows": 256}),
        4, init_model=base)
    # same ingest_chunk_rows param so the model headers match too (an
    # in-memory ndarray input stays eager regardless, docs/DATA.md)
    eager = lgb.train({**params, "ingest_chunk_rows": 256},
                      lgb.Dataset(X1, label=y1), 4, init_model=base)
    assert streamed.model_to_string() == eager.model_to_string()
    assert streamed.num_trees() == 7


def test_resume_of_continued_training_reaches_init_plus_rounds(
        tmp_path):
    """The relaunch-same-command contract: a snapshot written during
    init_model continued training records the init offset, so resume
    with the identical arguments finishes at init + num_boost_round —
    byte-identical to the uninterrupted run (previously it stopped
    short at max(resumed, num_boost_round))."""
    X, y = make_synthetic_binary(n=700, f=8, seed=9)
    params = {"objective": "binary", "num_leaves": 15,
              "verbosity": -1}
    base = _train(params, X, y, rounds=4)
    ck = str(tmp_path / "ck")
    full = lgb.train(params, lgb.Dataset(X, label=y), 6,
                     init_model=base,
                     callbacks=[lgb.checkpoint(ck, every_n_iters=3,
                                               keep=10)])
    assert full.num_trees() == 10
    # keep only the mid-run snapshot (engine iteration 6 = 4 init + 2)
    import glob
    snaps = sorted(glob.glob(os.path.join(ck, "ckpt_*.npz")))
    assert snaps, "no snapshots written"
    keep = snaps[0]
    for s in snaps[1:]:
        os.unlink(s)
    resumed = lgb.train(params, lgb.Dataset(X, label=y), 6,
                        init_model=base, resume_from=ck)
    assert resumed.num_trees() == 10, (
        f"resume stopped at {resumed.num_trees()} trees "
        f"(snapshot {os.path.basename(keep)})")
    assert resumed.model_to_string() == full.model_to_string()


# ---------------------------------------------------------------------
# 3. load shedding
# ---------------------------------------------------------------------

class _GatedForest:
    """Fake forest whose predict blocks until released."""
    n_features = 4

    def __init__(self):
        import threading
        self.release = threading.Event()
        self.calls = 0

    def predict_raw(self, X):
        self.calls += 1
        assert self.release.wait(timeout=30)
        return np.zeros((X.shape[0], 1), np.float32)


def test_batcher_sheds_oldest_on_queue_depth():
    from lightgbm_tpu.serve.batcher import MicroBatcher, SheddingError
    forest = _GatedForest()
    mb = MicroBatcher(forest, batch_window_ms=0.0, max_batch_rows=4,
                      queue_max_rows=4096, shed_queue_rows=8)
    try:
        X = np.zeros((4, 4), np.float32)
        first = mb.submit(X)          # dequeued, blocks on the device
        time.sleep(0.2)
        backlog = [mb.submit(X) for _ in range(5)]   # 20 rows pending
        forest.release.set()
        # oldest backlog entries shed until <= 8 rows pending; the
        # newest survive and serve
        outcomes = []
        for fut in backlog:
            try:
                fut.result(timeout=30)
                outcomes.append("ok")
            except SheddingError:
                outcomes.append("shed")
        assert first.result(timeout=30).shape == (4, 1)
        assert outcomes.count("shed") >= 2, outcomes
        assert outcomes[-1] == "ok", (
            f"newest request must survive a queue-depth shed: "
            f"{outcomes}")
        # sheds are FIFO: no served request is older than a shed one
        assert outcomes == sorted(outcomes,
                                  key=lambda o: o == "ok"), outcomes
        st = mb.stats()
        assert st["shed_total"] == outcomes.count("shed")
        assert st["shed_rows"] == 4 * outcomes.count("shed")
        assert st["queue_depth_rows"] == 0
    finally:
        forest.release.set()
        mb.close()


def test_batcher_sheds_blown_latency_budget():
    from lightgbm_tpu.serve.batcher import MicroBatcher, SheddingError
    forest = _GatedForest()
    mb = MicroBatcher(forest, batch_window_ms=0.0, max_batch_rows=4,
                      queue_max_rows=4096, shed_p99_ms=50.0)
    try:
        X = np.zeros((2, 4), np.float32)
        first = mb.submit(X)          # occupies the device
        time.sleep(0.1)
        stale = mb.submit(X)          # will wait > 50 ms
        time.sleep(0.2)
        forest.release.set()
        assert first.result(timeout=30) is not None
        with pytest.raises(SheddingError, match="latency budget"):
            stale.result(timeout=30)
        # a fresh request after the stall serves normally
        assert mb.submit(X).result(timeout=30).shape == (2, 1)
    finally:
        forest.release.set()
        mb.close()


def test_daemon_maps_shed_to_typed_reply(binary_model):
    from lightgbm_tpu.serve.batcher import SheddingError
    from lightgbm_tpu.serve.compile import compile_forest
    from lightgbm_tpu.serve.daemon import ServeState, handle_request
    from lightgbm_tpu.serve.batcher import MicroBatcher
    bst, X, _ = binary_model
    cf = compile_forest(bst, max_batch_rows=256)
    mb = MicroBatcher(cf, batch_window_ms=0.5, max_batch_rows=256)
    state = ServeState(mb, cf.model_id, "test-model")
    try:
        class _ShedFut:
            @staticmethod
            def result():
                raise SheddingError("request shed under load: test")
        state.batcher.submit = lambda rows: _ShedFut()
        r = handle_request({"rows": X[:2].tolist()}, state)
        assert r.get("shed") and r.get("overloaded") and "error" in r
        assert state.stats()["shed_replies"] == 1
    finally:
        state.close()


def test_shed_config_validation():
    from lightgbm_tpu.config import Config
    with pytest.raises(ValueError, match="shed"):
        Config.from_params({"serve_shed_queue_rows": 200000,
                            "serve_queue_rows": 131072})
    cfg = Config.from_params({"serve_shed_queue_rows": 1000})
    assert cfg.serve_shed_queue_rows == 1000


# ---------------------------------------------------------------------
# 4. watch-dir poller resilience (torn artifacts retried)
# ---------------------------------------------------------------------

def test_watcher_retries_torn_artifact_until_republished(
        binary_model, tmp_path):
    """The torn-write regression: a torn managed artifact is skipped
    with a swap_failure fault event and RETRIED next poll — once the
    publisher's atomic retry lands, the very next poll swaps. The old
    permanently-skipped behavior would have ignored the repaired
    bytes when the retry preserved mtime-size coincidence, and a
    mid-write file would have been missed forever."""
    from lightgbm_tpu.resilience.faults import FAULT_EVENTS, drain_events
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.compile import compile_forest
    from lightgbm_tpu.serve.daemon import (ServeState, _artifact_key,
                                           _Watcher)
    bst, X, y = binary_model
    model_a = str(tmp_path / "a.txt")
    bst.save_model(model_a)
    cf = compile_forest(bst, max_batch_rows=256)
    mb = MicroBatcher(cf, batch_window_ms=0.5, max_batch_rows=256)
    state = ServeState(mb, cf.model_id, model_a)
    drain_events(FAULT_EVENTS)
    try:
        watcher = _Watcher(
            state, str(tmp_path), 0.1,
            dict(num_iteration=-1, min_bucket=16, max_batch_rows=256),
            _artifact_key(model_a), 64)
        # a NEW model published torn: manifest first, then a partial
        # model write (the publisher crashed between its two steps)
        bst_b = _train({"objective": "binary", "num_leaves": 15},
                       X, (X[:, 1] > 0).astype(np.float64))
        text = bst_b.model_to_string()
        target = str(tmp_path / "b.txt")
        publish_model(bst_b, str(tmp_path), "b.txt")
        with open(target, "w") as fh:
            fh.write(text[: len(text) // 3])
        os.utime(target, (time.time() + 2, time.time() + 2))

        assert watcher.poll_once() is False
        assert state.stats()["swap_failures"] == 1
        events = drain_events(FAULT_EVENTS)
        assert any(e["kind"] == "swap_failure" for e in events)
        # STILL torn next poll: retried (counter moves), not poisoned
        assert watcher.poll_once() is False
        assert state.stats()["swap_failures"] == 2
        # fault event fires once per observed key, not per poll
        assert not any(e["kind"] == "swap_failure"
                       for e in drain_events(FAULT_EVENTS))

        # the publisher's atomic retry lands -> next poll swaps and
        # reports the validated manifest
        manifest = publish_model(bst_b, str(tmp_path), "b.txt")
        os.utime(target, (time.time() + 4, time.time() + 4))
        assert watcher.poll_once() is True
        st = state.stats()
        assert st["model"] == compile_forest(bst_b).model_id
        assert st["manifest"]["sha256"] == manifest["sha256"]
    finally:
        state.close()


# ---------------------------------------------------------------------
# 5. supervisor: budget, backoff, routing, CLI
# ---------------------------------------------------------------------

def test_restart_budget_sliding_window():
    clock = [0.0]
    budget = RestartBudget(max_restarts=10, max_per_window=2,
                           window_sec=60.0, _now=lambda: clock[0])
    assert budget.admit() is None
    assert budget.admit() is None
    refusal = budget.admit()
    assert refusal is not None and "sliding window" in refusal
    clock[0] = 61.0               # the window slides: both entries age out
    assert budget.admit() is None
    assert budget.total == 3


def test_restart_budget_total_cap_and_backoff():
    import random
    budget = RestartBudget(max_restarts=2, _rng=random.Random(5))
    assert budget.admit() is None
    assert budget.admit() is None
    assert "total restart budget" in budget.admit()
    # jittered exponential shape: within [0.5, 1.5) x base x 2^(n-1),
    # capped at 15 s
    for consecutive, base in ((1, 0.5), (2, 1.0), (3, 2.0)):
        d = budget.backoff(consecutive)
        assert base * 0.5 <= d < base * 1.5, (consecutive, d)
    assert budget.backoff(20) < 15.0 * 1.5


def test_supervise_respects_sliding_window(tmp_path):
    """A crash-looping world stops at the window cap, well before the
    total budget."""
    rc = supervise(
        1, [sys.executable, "-c", "raise SystemExit(7)"],
        max_restarts=50, log_dir=str(tmp_path), grace=0.5,
        max_restarts_per_window=2, restart_window_sec=3600.0)
    assert rc == 7
    # generations 0..2 ran (2 admitted restarts), no more
    logs = sorted(os.listdir(tmp_path))
    assert logs == ["elastic_g0_rank0.log", "elastic_g1_rank0.log",
                    "elastic_g2_rank0.log"], logs


def test_split_faults_routing():
    from lightgbm_tpu.pipeline import _split_faults
    train, serve = _split_faults(
        "serve_kill@25, rank_kill@8,publish_torn@1,refit_nan@2")
    assert serve == "serve_kill@25"
    assert train == "rank_kill@8,publish_torn@1,refit_nan@2"
    assert _split_faults("") == ("", "")


def test_pipeline_cli_is_jax_free(tmp_path):
    """`python -m lightgbm_tpu pipeline --help` must not import jax
    (the lint/launch/serve contract, subprocess-proved)."""
    code = (
        "import sys\n"
        "from lightgbm_tpu.pipeline import main\n"
        "rc = main(['--help'])\n"
        "assert rc == 0, rc\n"
        "rc = main([])\n"
        "assert rc == 2, rc\n"
        "assert 'jax' not in sys.modules, 'pipeline CLI imported jax!'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    assert "usage: python -m lightgbm_tpu pipeline" in proc.stdout


def test_summarize_events_publish_and_stats_row(tmp_path):
    from lightgbm_tpu.obs import render_stats_table, summarize_events
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "publish", "file": "m0.txt",
                             "generation": 0, "sha256": "a" * 64,
                             "train_auc": 0.91}) + "\n")
        fh.write(json.dumps({"event": "publish", "file": "m1.txt",
                             "generation": 1, "sha256": "b" * 64,
                             "train_auc": 0.93}) + "\n")
        fh.write(json.dumps({"event": "client", "attempts": 5,
                             "ok": 5}) + "\n")
    summ = summarize_events(path)
    assert summ["publishes"] == 2
    assert summ["publish"]["file"] == "m1.txt"
    table = render_stats_table(summ)
    assert "publish" in table and "m1.txt" in table
    from lightgbm_tpu.cli import main as cli_main
    assert cli_main(["stats", path]) == 0


# ---------------------------------------------------------------------
# 6. slow: graceful shutdown, per-replica fleet restart, chaos e2e
# ---------------------------------------------------------------------

def _read_ready(proc, tries=400):
    for _ in range(tries):
        line = proc.stdout.readline()
        if not line:
            raise AssertionError("daemon exited before serve_ready")
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("event") == "serve_ready":
            return obj
    raise AssertionError("no serve_ready line")


def _connect(port, timeout=120.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port),
                                         timeout=10)
            return s, s.makefile("rw")
        except OSError as e:
            last = e
            time.sleep(0.2)
    raise AssertionError(f"could not connect on :{port}: {last}")


def _rpc(fh, obj):
    fh.write(json.dumps(obj) + "\n")
    fh.flush()
    line = fh.readline()
    assert line, "daemon closed the connection unexpectedly"
    return json.loads(line)


@pytest.mark.slow
def test_daemon_sigterm_graceful_drain(binary_model, tmp_path):
    """SIGTERM = graceful shutdown: the in-flight request's reply
    still arrives, the daemon exits 0, and the final serve event is
    written — a supervised restart never drops an accepted request."""
    bst, X, _ = binary_model
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    telem = str(tmp_path / "serve.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", "0", "--telemetry", telem, "--warmup-rows", "64",
         # a long batching window parks the ACCEPTED request in the
         # worker's coalesce loop, so SIGTERM provably lands while it
         # is in flight (close() short-circuits the window: the STOP
         # marker ends the wait and the batch still runs)
         "--window-ms", "2000",
         "--max-batch-rows", "256", "--grace", "20"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO_DIR, start_new_session=True)
    try:
        ready = _read_ready(proc)
        s, fh = _connect(ready["port"])
        try:
            # a ping first: the connection must be APPLICATION-accepted
            # (out of the TCP backlog) for the drain contract to cover
            # it — a connection still in the backlog at shutdown is
            # reset, which clients see as a retryable connect error;
            # likewise a request still in the socket buffer is not yet
            # ACCEPTED, so give the handler a beat to submit it
            assert _rpc(fh, {"cmd": "ping"})["ok"]
            fh.write(json.dumps({"rows": X[:64].tolist()}) + "\n")
            fh.flush()
            time.sleep(0.3)          # handler reads + submits; batch
            #                          now parked in the 2 s window
            os.kill(proc.pid, signal.SIGTERM)      # mid-request
            line = fh.readline()
            assert line, "reply dropped by the graceful shutdown"
            reply = json.loads(line)
            assert "predictions" in reply and reply["n"] == 64
        finally:
            s.close()
        assert proc.wait(timeout=60) == 0
        with open(telem) as fhh:
            events = [json.loads(ln) for ln in fhh if ln.strip()]
        assert any(e.get("event") == "serve" for e in events)
    finally:
        if proc.poll() is None:
            kill_group(proc)


@pytest.mark.slow
def test_fleet_mode_restarts_only_the_dead_replica(binary_model,
                                                   tmp_path):
    """launch --health-port: SIGKILL one replica -> only IT restarts
    (the survivor's pid is unchanged and it keeps serving), unlike the
    world-restart training shape."""
    bst, X, _ = binary_model
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    base = free_port()
    sup = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "launch", "2",
         "--max-restarts", "3", "--grace", "1",
         "--health-port", str(base), "--health-interval", "0.5",
         "--health-grace", "300",   # exit-code supervision drives this
         "--log-dir", str(tmp_path / "logs"), "--",
         sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", str(base), "--warmup-rows", "64",
         "--max-batch-rows", "256"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=REPO_DIR, start_new_session=True)
    want = bst.predict(X[:3])
    try:
        pids = {}
        for rank in (0, 1):
            s, fh = _connect(base + rank, timeout=180)
            pids[rank] = _rpc(fh, {"cmd": "ping"})["pid"]
            s.close()

        os.kill(pids[1], signal.SIGKILL)

        deadline = time.time() + 180
        new_pid = None
        while time.time() < deadline:
            try:
                s, fh = _connect(base + 1, timeout=10)
                r = _rpc(fh, {"cmd": "ping"})
                if r.get("pid") not in (None, pids[1]):
                    new_pid = r["pid"]
                    s.close()
                    break
                s.close()
            except (AssertionError, OSError, ValueError):
                pass
            time.sleep(0.5)
        assert new_pid is not None, "replica 1 never came back"
        # replica 0 was NOT restarted: same pid, still serving
        s, fh = _connect(base, timeout=30)
        r = _rpc(fh, {"cmd": "ping"})
        assert r["pid"] == pids[0], (
            f"fleet mode must not restart the healthy replica "
            f"(pid {pids[0]} -> {r['pid']})")
        r = _rpc(fh, {"rows": X[:3].tolist()})
        np.testing.assert_allclose(r["predictions"], want,
                                   rtol=0, atol=1e-9)
        s.close()
    finally:
        kill_group(sup)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_pipeline_chaos_end_to_end(tmp_path):
    """The ISSUE 13 acceptance run: 3 generations under two-sided
    chaos — a training rank_kill mid-generation-1, a torn publish of
    generation 1, and a serve replica SIGKILL — and the loop still
    converges: every generation published and manifest-validated, the
    final served model IS the last publication, no accepted request
    was silently dropped, and client-observed service gaps stay
    within the restart grace budget."""
    workdir = str(tmp_path / "pipe")
    env = {k: v for k, v in os.environ.items()
           if k not in ("LIGHTGBM_TPU_FAULT_INJECT",
                        "LIGHTGBM_TPU_CHECKPOINT",
                        "LIGHTGBM_TPU_TELEMETRY")}
    env["PYTHONPATH"] = REPO_DIR
    # rounds=5: gen0 runs engine iterations 0-4, gen1 warm-starts at 5
    # -> rank_kill@7 fires ONLY in generation 1; publish_torn@1 tears
    # generation 1's publish (2 s backoff so the watcher provably
    # observes the torn artifact); serve_kill@12 kills the replica at
    # its 12th accepted request
    env["LIGHTGBM_TPU_FAULT_INJECT"] = \
        "rank_kill@7,publish_torn@1,serve_kill@12"
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "pipeline",
         "--workdir", workdir, "--generations", "3",
         "--rounds", "5", "--rows", "900", "--features", "8",
         "--request-rate", "15", "--request-rows", "4",
         "--health-interval", "0.5", "--health-grace", "25",
         "--swap-timeout", "240", "--grace", "10",
         "--param", "publish_backoff_sec=2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=REPO_DIR, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=800)
    except subprocess.TimeoutExpired:
        kill_group(proc)
        out, _ = proc.communicate(timeout=30)
        pytest.fail(f"pipeline hung; partial output:\n{out[-4000:]}")
    assert proc.returncode == 0, f"pipeline failed:\n{out[-6000:]}"
    summary = None
    for line in out.splitlines():
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("event") == "pipeline_summary":
            summary = obj
    assert summary is not None, out[-4000:]
    assert summary["failures"] == []
    assert summary["generations_published"] == 3
    assert summary["swaps_confirmed"] == 2

    # final served model id == the last successfully published retrain
    fleet = summary["fleet"]
    assert fleet and all(st is not None for st in fleet)
    for st in fleet:
        assert st["manifest_sha256"] == \
            summary["last_published_sha256"]
        assert st["model_source"].endswith("model_g0002.txt")

    # no accepted request silently dropped; the replica kill was
    # client-visible as connection errors, not hangs
    client = summary["client"]
    assert client["timeout"] == 0, client
    assert client["ok"] > 0
    assert client["conn"] >= 1, (
        f"serve_kill@12 should surface as connection errors: {client}")
    # QPS/p99 continuity: the longest gap between successful replies
    # stays within the (generous) replica-restart budget
    assert client["max_ok_gap_s"] < 60.0, client

    # the torn publish was observed and refused by the watcher...
    serve_jsonl = os.path.join(workdir, "telemetry", "serve.jsonl")
    fault_kinds = set()
    with open(serve_jsonl) as fh:
        for ln in fh:
            if not ln.strip():
                continue
            ev = json.loads(ln)
            if ev.get("event") == "fault":
                fault_kinds.add(ev.get("kind"))
    assert "swap_failure" in fault_kinds, fault_kinds

    # ...and the publisher retried through it (fault event in the
    # generation-1 training telemetry)
    train1 = os.path.join(workdir, "telemetry", "train_g0001.jsonl")
    kinds1 = set()
    publishes = 0
    with open(train1) as fh:
        for ln in fh:
            if not ln.strip():
                continue
            ev = json.loads(ln)
            if ev.get("event") == "fault":
                kinds1.add(ev.get("kind"))
            if ev.get("event") == "publish":
                publishes += 1
    assert publishes == 1
    # the training rank_kill relaunched generation 1 under the
    # supervisor (a generation-1 elastic log exists) and the run
    # still published
    relaunch_log = os.path.join(workdir, "logs", "train_g0001",
                                "elastic_g1_rank0.log")
    assert os.path.exists(relaunch_log), sorted(
        os.listdir(os.path.join(workdir, "logs", "train_g0001")))
    # the serve replica was relaunched by the fleet supervisor
    fleet_logs = sorted(os.listdir(
        os.path.join(workdir, "logs", "fleet")))
    assert "elastic_g1_rank0.log" in fleet_logs, fleet_logs

    # --- tracing plane (ISSUE 16 acceptance): the same chaos run's
    # telemetry merges into a clock-corrected trace with a full
    # train -> publish -> swap -> serve critical path, despite the
    # SIGKILLed replica's truncated stream
    telem_dir = os.path.join(workdir, "telemetry")
    tr = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "trace", telem_dir],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=REPO_DIR)
    assert tr.returncode == 0, (
        f"trace CLI failed:\n{tr.stdout}\n{tr.stderr[-3000:]}")
    assert "critical path" in tr.stdout, tr.stdout
    with open(os.path.join(telem_dir, "trace.json")) as fh:
        doc = json.load(fh)
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert xs, "empty Perfetto export from the chaos run"
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    span_names = {e["name"] for e in xs}
    for expected in ("train/iteration", "publish/model",
                     "swap/apply", "serve/request"):
        assert expected in span_names, sorted(span_names)

    from lightgbm_tpu.obs.trace import (correct_clock_skew,
                                        critical_paths, load_spans)
    spans = load_spans(telem_dir)
    offsets = correct_clock_skew(spans)
    assert len(offsets) >= 3  # trainer(s), replica, supervisor
    paths = critical_paths(spans)
    complete = [p for p in paths if p["complete"]]
    assert complete, [
        {"gen": p["generation"],
         "steps": [s["name"] for s in p["steps"]]} for p in paths]
    for p in complete:
        assert all(s["dur_s"] >= 0 for s in p["steps"]), p["steps"]
        t0s = [s["t0"] for s in p["steps"]]
        assert t0s == sorted(t0s), p["steps"]
        assert 0 < p["total_s"] < 600, p
        names = [s["name"] for s in p["steps"]]
        assert names[-1].startswith("serve/request"), names
