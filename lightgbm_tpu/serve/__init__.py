"""Production inference serving (docs/SERVING.md).

The trained-model half of the north star: a forest is *compiled* once
into tensorized SoA device arrays with one jitted batch predictor
(:mod:`~lightgbm_tpu.serve.compile`), requests are micro-batched into
power-of-two row buckets so arbitrary batch sizes never recompile
(:mod:`~lightgbm_tpu.serve.batcher`), and ``python -m lightgbm_tpu
serve <model>`` runs the JSON-lines daemon with checkpoint-directory
hot model swap and ``{"event": "serve"}`` telemetry
(:mod:`~lightgbm_tpu.serve.daemon`).

This ``__init__`` is PEP-562 lazy like the package root: the daemon's
CLI parse/--help path (dispatched jax-free in ``__main__``) imports
``serve.daemon`` through here, and jax must only load once a model is
actually being compiled.
"""

from __future__ import annotations

_LAZY = {
    "CompiledForest": "compile", "compile_forest": "compile",
    "bucket_rows": "compile",
    "MicroBatcher": "batcher", "QueueFullError": "batcher",
    "SheddingError": "batcher",
    "main": "daemon", "handle_request": "daemon", "ServeState": "daemon",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(f".{target}", __name__)
    value = getattr(mod, name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
