"""Exclusive Feature Bundling (FeatureGroup / EFB, feature_group.h:26):
zero-conflict bundles must reproduce the unbundled model exactly, and a
wide sparse matrix must collapse to few bundle columns."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.bundling import build_bundles


def _sparse_onehot(n, groups, per_group, seed=0, noise_feats=2):
    """One-hot blocks (mutually exclusive by construction) + a couple
    of dense features."""
    rs = np.random.RandomState(seed)
    cols = []
    signal = np.zeros(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        block = np.zeros((n, per_group))
        vals = rs.rand(per_group) * 2
        block[np.arange(n), pick] = vals[pick]
        cols.append(block)
        signal += vals[pick]
    dense = rs.randn(n, noise_feats)
    X = np.hstack(cols + [dense])
    y = (signal + 0.5 * dense[:, 0]
         + 0.3 * rs.randn(n) > np.median(signal)).astype(float)
    return X, y


def test_build_bundles_collapses_onehot_blocks():
    X, y = _sparse_onehot(4000, groups=6, per_group=8)
    d = lgb.Dataset(X, label=y)
    d.construct()
    info = build_bundles(d.host_bins(), d.mappers)
    assert info is not None
    F = d.num_features()
    G = info.bins_bundled.shape[1]
    assert G < F / 2
    # round-trip: every row/feature bin must be recoverable from its
    # bundle column
    bins = d.host_bins()
    for j in rs_choice(F, 12):
        g = info.bundle_of[j]
        col = info.bins_bundled[:, g].astype(np.int64)
        if info.is_direct[j]:
            rec = col
        else:
            off, nb = int(info.offset_of[j]), d.mappers[j].num_bins
            inside = (col >= off) & (col <= off + nb - 2)
            rec = np.where(inside, col - off + 1, 0)
        np.testing.assert_array_equal(rec, bins[:, j])


def rs_choice(F, k):
    rs = np.random.RandomState(1)
    return rs.choice(F, size=min(k, F), replace=False)


def test_bundled_training_matches_unbundled_exactly():
    X, y = _sparse_onehot(3000, groups=4, per_group=6, seed=3)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    assert len(plain._models) == len(bundled._models)
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        # leaf values agree up to the f32 rounding of the bin-0
        # reconstruction (total - range); structure is bit-identical
        np.testing.assert_allclose(ta.leaf_value[:ta.num_leaves],
                                   tb.leaf_value[:tb.num_leaves],
                                   rtol=5e-3, atol=1e-5)
    np.testing.assert_allclose(plain.predict(X[:200]),
                               bundled.predict(X[:200]),
                               rtol=5e-3, atol=1e-4)


def test_wide_sparse_matrix_trains_with_small_cache():
    """The VERDICT target: a multi-thousand-feature sparse synthetic
    must train with the histogram cache scaled by bundles, not
    features."""
    X, y = _sparse_onehot(3000, groups=160, per_group=25, seed=5)
    assert X.shape[1] == 160 * 25 + 2  # 4002 features
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5}, d,
                    num_boost_round=4)
    info = bst._engine.bundle
    assert info is not None
    # 4002 sparse features must collapse to ~#groups bundle columns
    assert info.bins_bundled.shape[1] < 200
    p = bst.predict(X[:500])
    assert np.all(np.isfinite(p))
    assert np.mean((p > 0.5) == (y[:500] > 0.5)) > 0.7


def test_bundling_skipped_with_dense_data():
    rs = np.random.RandomState(2)
    X = rs.randn(1500, 8)
    y = (X[:, 0] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    assert bst._engine.bundle is None


def test_bundling_engages_alongside_nan_feature():
    """A NaN-carrying numeric column must NOT disable bundling for the
    rest of the dataset: it stays a direct singleton (with its dual
    missing-direction scan) while the sparse blocks bundle — and the
    model equals the unbundled one structurally."""
    rs = np.random.RandomState(13)
    n = 2500
    X_blocks, y = _sparse_onehot(n, groups=4, per_group=6, seed=13)
    xnan = rs.randn(n, 1)
    xnan[rs.rand(n) < 0.3] = np.nan
    X = np.hstack([X_blocks, xnan])
    y = ((np.nan_to_num(xnan[:, 0]) > 0.3) ^ (y > 0.5)).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    plain = lgb.train({**params, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=6)
    bundled = lgb.train({**params, "enable_bundle": True},
                        lgb.Dataset(X, label=y), num_boost_round=6)
    assert bundled._engine.bundle is not None, "bundling did not engage"
    for ta, tb in zip(plain._models, bundled._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        np.testing.assert_array_equal(ta.split_feature[:nn],
                                      tb.split_feature[:nn])
        np.testing.assert_array_equal(ta.threshold_bin[:nn],
                                      tb.threshold_bin[:nn])
        np.testing.assert_array_equal(
            [ta.default_left(i) for i in range(nn)],
            [tb.default_left(i) for i in range(nn)])
    np.testing.assert_allclose(plain.predict(X[:200]),
                               bundled.predict(X[:200]),
                               rtol=5e-3, atol=1e-4)
