"""Mini registry whose aggregate (whole-tree) contract findings are
the fixture: one declared-but-never-emitted event, one never-bumped
metric family, one never-referenced env var. The EXPECT markers pin
the registry-assignment anchor lines the findings report at."""

# EXPECT: TPL015
EVENTS = {
    "beep": {"doc": "emitted by site.py",
             "required": ("event", "n"), "optional": ()},
    "boop": {"doc": "declared but never emitted -> finding",
             "required": ("event",), "optional": ()},
}

# EXPECT: TPL016
METRICS = {
    "beeps": {"kind": "counter", "labels": (), "doc": "bumped"},
    "boops": {"kind": "counter", "labels": (),
              "doc": "declared but never bumped -> finding"},
}

EXPORT_FAMILIES = {}

# EXPECT: TPL017
ENV_VARS = {
    "LIGHTGBM_TPU_BEEP": {"default": "5", "kind": "str",
                          "doc": "read by site.py"},
    "LIGHTGBM_TPU_BOOP": {"default": None, "kind": "str",
                          "doc": "declared but never read -> finding"},
}

FAULT_KINDS = {}

FAULT_EVENT_KINDS = {}
