"""TPL017 positives: env reads that drift from the registry."""

import os


def read():
    # EXPECT: TPL017
    a = os.environ.get("LIGHTGBM_TPU_OOPS")
    # EXPECT: TPL017
    b = os.environ.get("LIGHTGBM_TPU_PING", "2")
    # EXPECT: TPL017
    c = os.environ.get("LIGHTGBM_TPU_PONG", "x")
    return a, b, c
