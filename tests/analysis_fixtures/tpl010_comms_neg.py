# tpulint fixture: TPL010 negatives for the parallel/comms.py
# wrappers — justified replicated-predicate sites and wrapper calls
# outside conditionals report nothing.
import jax.numpy as jnp
from jax import lax

from lightgbm_tpu.parallel import comms


def justified_pool_miss(slot, hists, hist, axis, ef):
    """The pooled compact grower's recompute-on-miss shape with the
    replication invariant named on the pragma."""
    # tpulint: replicated-cond slot is pool state derived only from the replicated tree/argmax sequence
    return lax.cond(slot >= 0,
                    lambda: hists[jnp.maximum(slot, 0)],
                    lambda: comms.hist_allreduce(hist, axis, "int8"))


def wrapper_outside_cond(pred, hist, axis):
    """Every rank joins the quantized reduction; only local work
    branches afterwards."""
    g = comms.hist_allreduce(hist, axis, "int16")
    return lax.cond(pred, lambda: g * 2.0, lambda: g)


def f32_mode_is_still_a_collective_but_joined_by_all(hist, axis):
    return comms.hist_allreduce(hist, axis, "f32")
