"""Cross-module call graph + jit-reachability (pure stdlib).

The property the rules need is **jit-reachability**: which functions
are only ever *entered* through a tracing wrapper (``jax.jit`` /
``pjit`` / ``shard_map`` / ``jax.eval_shape``)? Inside such a function
a ``lax.fori_loop`` is one op of a compiled program; outside it, the
same call dispatches op-by-op through the device tunnel — the
PROFILE.md 530 ms/iter regression class. The old
``tests/test_hot_path_lint.py`` answered this with a hand-maintained
``KNOWN_JITTED`` allowlist; this module *computes* it:

- every reference to a known function is recorded with its referencing
  scope and kind: ``call`` (direct call), ``ref`` (passed as a value —
  ``lax.fori_loop(0, n, body, ...)``, ``jax.vmap(f)``, callbacks),
  ``jit`` (passed into a tracing wrapper), or ``neutral``
  (``register_jit`` pass-throughs that never enter the function);
- a function **decorated** with a tracing wrapper is traced
  unconditionally — its name *is* the wrapper, so every call by name
  enters through jit;
- every other function is jit-reachable iff it has at least one
  reference and every ``call``/``ref`` to it comes from a scope that is
  itself jit-reachable (greatest fixed point, so mutual recursion among
  traced helpers stays traced). Module level is never traced.

A function with **no** references at all is *not* jit-reachable: dead
code cannot prove how it will be entered, and an eager ``lax`` loop in
it is one import away from dispatching eagerly (exactly how the stale
``predict_forest_raw`` allowlist entry hid a dead eager loop).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .astscan import (FuncInfo, JitWrap, ModuleScan, dotted_of,
                      jit_wrap_kind)

__all__ = ["CallGraph", "CallRecord", "build_callgraph", "scan_package"]

Key = Tuple[str, str]            # (relpath, qualname)

#: tracing entries beyond jit/pjit/shard_map: abstract evaluation
#: traces without dispatching, so a function reference inside it is a
#: traced entry, not an eager one.
_TRACED_ARG_BASENAMES = {"jit", "pjit", "shard_map", "eval_shape",
                         "make_jaxpr"}
_NEUTRAL_BASENAMES = {"register_jit"}

#: dotted roots whose calls dispatch jax work
_JAX_ROOTS = ("jax",)


@dataclass
class CallRecord:
    """One interesting call site inside a scope (consumed by rules)."""
    kind: str                 # ext | known | wrapper | method
    node: ast.Call
    scope: Optional[Key]      # None = module level
    relpath: str
    dotted: Optional[str] = None      # resolved dotted (ext calls)
    attr: Optional[str] = None        # method name (method calls)
    target: Optional[Key] = None      # known-function target
    wrap: Optional[JitWrap] = None    # wrapper-call metadata
    in_loop: bool = False             # lexically inside for/while


@dataclass
class _Ref:
    target: Key
    scope: Optional[Key]
    kind: str                 # call | ref | jit
    lineno: int


@dataclass
class FuncFacts:
    """Per-scope facts the rules consume."""
    records: List[CallRecord] = field(default_factory=list)
    param_names: Set[str] = field(default_factory=set)  # incl. enclosing


class _Env:
    """Lexical name environment (module -> enclosing defs -> local)."""

    def __init__(self, parent: Optional["_Env"], names: Dict[str, tuple]):
        self.parent = parent
        self.names = names

    def lookup(self, name: str) -> Optional[tuple]:
        env: Optional[_Env] = self
        while env is not None:
            if name in env.names:
                return env.names[name]
            env = env.parent
        return None


class CallGraph:
    def __init__(self, scans: List[ModuleScan]):
        self.scans = {s.relpath: s for s in scans}
        self.funcs: Dict[Key, FuncInfo] = {}
        for s in scans:
            for info in s.funcs.values():
                self.funcs[info.key] = info
        self.module_of: Dict[str, str] = {s.module: s.relpath
                                          for s in scans}
        self.refs: List[_Ref] = []
        self.facts: Dict[Optional[Key], FuncFacts] = {}
        self._global_symbols = self._build_global_symbols()
        for s in scans:
            _ModuleAnalyzer(self, s).run()
        self.jit_reachable: Set[Key] = self._fixed_point()
        self._dispatches: Dict[Key, bool] = self._dispatch_closure()

    # -- symbol table --------------------------------------------------
    def _build_global_symbols(self) -> Dict[str, tuple]:
        out: Dict[str, tuple] = {}
        for s in self.scans.values():
            for qual, info in s.funcs.items():
                if "." not in qual:
                    out[f"{s.module}.{qual}"] = ("func", info.key)
            for name, binding in s.aliases.items():
                if binding[0] == "func":
                    tgt = s.funcs.get(binding[1])
                    if tgt is not None:
                        out[f"{s.module}.{name}"] = ("func", tgt.key)
                elif binding[0] == "wrapper":
                    tgt = s.funcs.get(binding[1])
                    out[f"{s.module}.{name}"] = (
                        "wrapper", tgt.key if tgt else None, binding[2])
        return out

    def lookup_dotted(self, dotted: str, _seen=None) -> tuple:
        hit = self._global_symbols.get(dotted)
        if hit is not None:
            return hit
        if dotted in self.module_of:
            return ("module", dotted)
        # a re-export: `pkg.sub.kernel` where sub/__init__.py (or any
        # module) merely imported `kernel` — follow its import table
        mod, _, attr = dotted.rpartition(".")
        if attr and mod in self.module_of:
            scan = self.scans[self.module_of[mod]]
            target = scan.imports.get(attr)
            if target is not None and target != dotted:
                _seen = _seen or set()
                if dotted not in _seen:
                    _seen.add(dotted)
                    return self.lookup_dotted(target, _seen)
        return ("ext", dotted)

    # -- reachability --------------------------------------------------
    def _fixed_point(self) -> Set[Key]:
        refs_by_target: Dict[Key, List[_Ref]] = {}
        for r in self.refs:
            refs_by_target.setdefault(r.target, []).append(r)
        decorated = {k for k, f in self.funcs.items()
                     if f.decorator_wrap is not None}
        traced: Set[Key] = set(decorated)
        for k in self.funcs:
            if k in traced:
                continue
            if refs_by_target.get(k) or self.funcs[k].wrappers:
                traced.add(k)
        changed = True
        while changed:
            changed = False
            for k in list(traced):
                if k in decorated:
                    continue
                for r in refs_by_target.get(k, ()):
                    if r.kind == "jit":
                        continue
                    if r.scope is None or r.scope not in traced:
                        traced.discard(k)
                        changed = True
                        break
        # the greatest fixed point keeps orphan cycles (a recursive
        # helper nothing else references certifies itself); require a
        # real traced ENTRY: forward reachability from an actual jit
        # seed (decorator or jit(f)/shard_map(f) wrapping)
        seeds = decorated | {k for k, f in self.funcs.items()
                             if f.wrappers} \
            | {r.target for r in self.refs if r.kind == "jit"}
        out_edges: Dict[Optional[Key], Set[Key]] = {}
        for r in self.refs:
            out_edges.setdefault(r.scope, set()).add(r.target)
        entered: Set[Key] = set()
        frontier = [k for k in seeds if k in self.funcs]
        while frontier:
            k = frontier.pop()
            if k in entered:
                continue
            entered.add(k)
            frontier.extend(out_edges.get(k, ()))
        return traced & entered

    def _dispatch_closure(self) -> Dict[Key, bool]:
        """Does calling this function (transitively) dispatch jax work?"""
        out: Dict[Key, bool] = {}
        calls_out: Dict[Key, Set[Key]] = {k: set() for k in self.funcs}
        for scope, facts in self.facts.items():
            if scope is None:
                continue
            direct = False
            for rec in facts.records:
                if rec.kind == "wrapper":
                    direct = True
                elif rec.kind == "ext" and rec.dotted and (
                        rec.dotted.split(".", 1)[0] in _JAX_ROOTS):
                    direct = True
                elif rec.kind == "known" and rec.target is not None:
                    calls_out.setdefault(scope, set()).add(rec.target)
            out[scope] = direct
        for k in self.funcs:
            out.setdefault(k, False)
            calls_out.setdefault(k, set())
        changed = True
        while changed:
            changed = False
            for k, callees in calls_out.items():
                if out.get(k):
                    continue
                if any(out.get(c, False) for c in callees):
                    out[k] = True
                    changed = True
        return out

    def dispatches_jax(self, key: Key) -> bool:
        return self._dispatches.get(key, False)

    def record_dispatches(self, rec: CallRecord) -> bool:
        """Does this one call site dispatch jax work?"""
        if rec.kind == "wrapper":
            return True
        if rec.kind == "ext" and rec.dotted:
            return rec.dotted.split(".", 1)[0] in _JAX_ROOTS
        if rec.kind == "known" and rec.target is not None:
            return self.dispatches_jax(rec.target)
        return False

    # -- convenience ---------------------------------------------------
    def hot_functions(self) -> Set[Key]:
        return {k for k, f in self.funcs.items() if f.is_hot}

    def reachable_in(self, relpath: str) -> Set[str]:
        return {q for (p, q) in self.jit_reachable if p == relpath}


class _ModuleAnalyzer:
    """Phase-2 walk of one module: resolve references + call records."""

    def __init__(self, graph: CallGraph, scan: ModuleScan):
        self.g = graph
        self.s = scan

    def run(self) -> None:
        names: Dict[str, tuple] = {}
        for name, dotted in self.s.imports.items():
            names[name] = self.g.lookup_dotted(dotted)
        classes: Dict[str, Set[str]] = {}
        for qual, info in self.s.funcs.items():
            parts = qual.split(".")
            if len(parts) == 2 and info.class_name == parts[0]:
                classes.setdefault(parts[0], set()).add(parts[1])
        self.classes = classes
        for qual, info in self.s.funcs.items():
            if "." not in qual:
                names[qual] = ("func", info.key)
        for name, binding in self.s.aliases.items():
            if binding[0] == "func":
                tgt = self.s.funcs.get(binding[1])
                if tgt is not None:
                    names[name] = ("func", tgt.key)
            elif binding[0] == "wrapper":
                tgt = self.s.funcs.get(binding[1])
                names[name] = ("wrapper",
                               tgt.key if tgt else None, binding[2])
        for cname in classes:
            names[cname] = ("class", cname)
        env = _Env(None, names)
        self.g.facts.setdefault(None, FuncFacts())
        self._walk_block(self.s.tree, None, env, None, set(), False)

    # -- scope construction --------------------------------------------
    def _enter_function(self, fn_node, env: _Env,
                        outer_params: Set[str]) -> Tuple[_Env, Set[str]]:
        a = fn_node.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        names: Dict[str, tuple] = {p: ("param",) for p in params}
        # sibling/nested defs + local aliases + local imports
        for child in ast.walk(fn_node):
            for name, dotted in self.s.import_bindings(child):
                names.setdefault(name, self.g.lookup_dotted(dotted))
        # defs anywhere in this function's own statements (loop/if
        # bodies included), but not inside nested functions — those
        # bind in the nested scope
        stack = list(fn_node.body)
        while stack:
            child = stack.pop()
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = self._qual_of(child)
                if qual:
                    names[child.name] = ("func", (self.s.relpath, qual))
                continue
            if isinstance(child, ast.ClassDef):
                continue
            stack.extend(ast.iter_child_nodes(child))
        for child in fn_node.body:
            if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                    and isinstance(child.targets[0], ast.Name):
                got = self._local_wrap_or_func(child.value, names)
                if got is not None:
                    names[child.targets[0].id] = got
        all_params = outer_params | {p for p in params}
        return _Env(env, names), all_params

    def _local_wrap_or_func(self, value, names):
        if isinstance(value, ast.Name) and names.get(value.id, (None,))[0] \
                == "func":
            return names[value.id]
        if isinstance(value, ast.Call):
            base = dotted_of(value.func) or ""
            kind = jit_wrap_kind(base)
            if kind and value.args and isinstance(value.args[0], ast.Name):
                tgt = names.get(value.args[0].id)
                from .astscan import _wrap_from_call_kwargs
                w = _wrap_from_call_kwargs(kind, value.lineno,
                                           value.keywords)
                return ("wrapper",
                        tgt[1] if tgt and tgt[0] == "func" else None, w)
        return None

    def _qual_of(self, fn_node) -> Optional[str]:
        for qual, info in self.s.funcs.items():
            if info.node is fn_node:
                return qual
        return None

    # -- traversal -----------------------------------------------------
    def _walk_block(self, node, scope: Optional[Key], env: _Env,
                    cls: Optional[str], params: Set[str],
                    in_loop: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in child.decorator_list:
                    self._visit_expr(deco, scope, env, cls, params,
                                     in_loop, "plain")
                qual = self._qual_of(child)
                if qual is None:
                    continue
                info = self.s.funcs[qual]
                child_env, child_params = self._enter_function(
                    child, env, params)
                key = info.key
                self.g.facts.setdefault(key, FuncFacts()).param_names \
                    |= child_params
                self._walk_block(child, key, child_env,
                                 info.class_name, child_params, False)
            elif isinstance(child, ast.ClassDef):
                self._walk_block(child, scope, env, child.name, params,
                                 in_loop)
            elif isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                # loop bodies re-enter the SAME dispatch (a function
                # defined inside a loop body must still get its own
                # scope), just with in_loop set
                self._walk_block(child, scope, env, cls, params, True)
            elif isinstance(child, ast.expr):
                self._visit_expr(child, scope, env, cls, params,
                                 in_loop, "plain")
            else:
                self._walk_block(child, scope, env, cls, params, in_loop)

    # -- expression resolution -----------------------------------------
    def _resolve(self, node, env: _Env, cls: Optional[str]):
        """-> ("func", key) | ("wrapper", key|None, wrap) | ("ext", dotted)
        | ("param",) | None."""
        if isinstance(node, ast.Name):
            return env.lookup(node.id)
        if isinstance(node, ast.Attribute):
            dotted = dotted_of(node)
            if dotted is None:
                return None
            root, _, rest = dotted.partition(".")
            if root in ("self", "cls") and cls is not None and rest \
                    and "." not in rest:
                if rest in self.classes.get(cls, ()):
                    return ("func", (self.s.relpath, f"{cls}.{rest}"))
                wrap = self.s.attr_wrappers.get((cls, rest))
                if wrap is not None:
                    return ("wrapper", None, wrap[1])
                return None
            base = env.lookup(root)
            if base is None:
                return None
            if base[0] in ("module", "ext"):
                return self.g.lookup_dotted(f"{base[1]}.{rest}")
            return None
        return None

    def _visit_expr(self, node, scope, env, cls, params, in_loop,
                    ctx: str) -> None:
        """ctx: how a *function-valued* name found here is entered —
        "plain" (eager ref), "traced" (inside a jit-wrapper argument),
        "neutral" (register_jit pass-through)."""
        if isinstance(node, ast.Call):
            self._visit_call(node, scope, env, cls, params, in_loop, ctx)
            return
        if isinstance(node, (ast.Name, ast.Attribute)):
            got = self._resolve(node, env, cls)
            if got is not None and got[0] == "func" and ctx != "neutral":
                self.g.refs.append(_Ref(
                    target=got[1], scope=scope,
                    kind="jit" if ctx == "traced" else "ref",
                    lineno=node.lineno))
            if isinstance(node, ast.Attribute):
                self._visit_expr(node.value, scope, env, cls, params,
                                 in_loop, "plain")
            return
        if isinstance(node, ast.Lambda):
            self._visit_expr(node.body, scope, env, cls, params,
                             in_loop, ctx)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, scope, env, cls, params,
                                 in_loop, ctx)
            elif isinstance(child, ast.comprehension):
                self._visit_expr(child.iter, scope, env, cls, params,
                                 in_loop, ctx)
                for cond in child.ifs:
                    self._visit_expr(cond, scope, env, cls, params,
                                     in_loop, ctx)

    def _visit_call(self, node: ast.Call, scope, env, cls, params,
                    in_loop, ctx) -> None:
        callee = self._resolve(node.func, env, cls)
        arg_ctx = "plain" if ctx == "neutral" else ctx
        rec: Optional[CallRecord] = None
        if callee is not None and callee[0] == "wrapper":
            rec = CallRecord(kind="wrapper", node=node, scope=scope,
                             relpath=self.s.relpath, target=callee[1],
                             wrap=callee[2], in_loop=in_loop)
        elif callee is not None and callee[0] == "func":
            self.g.refs.append(_Ref(
                target=callee[1], scope=scope,
                kind="jit" if ctx == "traced" else "call",
                lineno=node.lineno))
            rec = CallRecord(kind="known", node=node, scope=scope,
                             relpath=self.s.relpath, target=callee[1],
                             in_loop=in_loop)
            # a local shim NAMED like a tracing wrapper (e.g. the
            # shard_map compat wrapper in parallel/data_parallel.py)
            # traces its function arguments like the real thing
            if callee[1][1].rsplit(".", 1)[-1] in \
                    _TRACED_ARG_BASENAMES:
                arg_ctx = "traced"
        elif callee is not None and callee[0] == "ext":
            dotted = callee[1]
            base = dotted.rsplit(".", 1)[-1]
            rec = CallRecord(kind="ext", node=node, scope=scope,
                             relpath=self.s.relpath, dotted=dotted,
                             in_loop=in_loop)
            if base in _TRACED_ARG_BASENAMES:
                arg_ctx = "traced"
                if jit_wrap_kind(dotted):
                    self._attach_wrap(node, env, cls)
            elif base in _NEUTRAL_BASENAMES:
                arg_ctx = "neutral"
            elif base == "partial":
                arg_ctx = ctx if ctx != "neutral" else "plain"
                if node.args:
                    first = dotted_of(node.args[0])
                    if first and jit_wrap_kind(first):
                        arg_ctx = "traced"
        else:
            raw = dotted_of(node.func)
            if raw is not None and raw.rsplit(".", 1)[-1] in \
                    _TRACED_ARG_BASENAMES:
                # unresolved but unmistakably named (e.g. a method
                # returning jax.jit objects): still a traced entry
                arg_ctx = "traced"
            if isinstance(node.func, ast.Attribute):
                rec = CallRecord(kind="method", node=node, scope=scope,
                                 relpath=self.s.relpath,
                                 attr=node.func.attr, in_loop=in_loop)
                self._visit_expr(node.func.value, scope, env, cls,
                                 params, in_loop, "plain")
            elif isinstance(node.func, ast.Name):
                # unresolved bare-name call (builtins like float/int,
                # sorted, set): rules match on the raw name
                rec = CallRecord(kind="builtin", node=node, scope=scope,
                                 relpath=self.s.relpath,
                                 dotted=node.func.id, in_loop=in_loop)
        if rec is not None:
            self.g.facts.setdefault(scope, FuncFacts()).records \
                .append(rec)
        if isinstance(node.func, (ast.Call, ast.Lambda, ast.Subscript,
                                  ast.BoolOp, ast.IfExp)):
            # curried/derived callee, e.g. jax.vmap(f)(xs) — the inner
            # expression carries its own references
            self._visit_expr(node.func, scope, env, cls, params,
                             in_loop, "plain")
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            self._visit_expr(arg, scope, env, cls, params, in_loop,
                             arg_ctx)

    def _attach_wrap(self, node: ast.Call, env, cls) -> None:
        """jit(f, ...) call: attach wrap metadata to f for TPL003/004."""
        from .astscan import _wrap_from_call_kwargs
        if not node.args:
            return
        got = self._resolve(node.args[0], env, cls)
        if got is not None and got[0] == "func":
            info = self.g.funcs.get(got[1])
            if info is not None:
                kind = jit_wrap_kind(dotted_of(node.func)) or "jit"
                info.wrappers.append(_wrap_from_call_kwargs(
                    kind, node.lineno, node.keywords))


def scan_package(root: str, package: str = "lightgbm_tpu",
                 exclude: Tuple[str, ...] = ("analysis",),
                 files: Optional[List[str]] = None) -> List[ModuleScan]:
    """Parse every ``*.py`` under ``root`` into ModuleScans.

    ``root`` is the package directory; relpaths are package-relative
    posix paths ("ops/grow.py"). ``exclude`` prunes subpackage names
    (the analyzer does not lint itself).
    """
    scans: List[ModuleScan] = []
    if files is not None:
        targets = [os.path.join(root, f) for f in files]
    else:
        targets = []
        for dirpath, dirnames, filenames in os.walk(root):
            rel = os.path.relpath(dirpath, root)
            parts = [] if rel == "." else rel.split(os.sep)
            if parts and parts[0] in exclude:
                dirnames[:] = []
                continue
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"
                           and (parts or d not in exclude)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    targets.append(os.path.join(dirpath, fn))
    for path in targets:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        mod = package + "." + rel[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        scans.append(ModuleScan(rel, source, mod))
    return scans


def build_callgraph(root: str, package: str = "lightgbm_tpu",
                    files: Optional[List[str]] = None) -> CallGraph:
    return CallGraph(scan_package(root, package=package, files=files))
