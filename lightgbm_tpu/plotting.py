"""Plotting utilities.

Re-design of the reference python-package/lightgbm/plotting.py
(plot_importance, plot_split_value_histogram, plot_metric, plot_tree,
create_tree_digraph) for the TPU-native booster. matplotlib is imported
lazily; graphviz is optional (ImportError raised at call time, matching
the reference's behavior).
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError

__all__ = ["plot_importance", "plot_split_value_histogram", "plot_metric",
           "plot_tree", "create_tree_digraph"]


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _to_booster(booster) -> Booster:
    if isinstance(booster, Booster):
        return booster
    if hasattr(booster, "booster_"):
        return booster.booster_
    raise TypeError("booster must be Booster or fitted LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None,
                    ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar plot of feature importances
    (reference plotting.py plot_importance)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = getattr(booster, "importance_type", "split")
    importance = bst.feature_importance(importance_type=importance_type)
    feature_name = bst.feature_name()

    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples) if tuples else ((), ())

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        if importance_type == "gain" and precision is not None:
            ax.text(x + 1, y, f"{x:.{precision}f}", va="center")
        else:
            ax.text(x + 1, y, str(x), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1 if values else 1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8,
                               xlim: Optional[Tuple] = None,
                               ylim: Optional[Tuple] = None,
                               title: Optional[str] = "Split value histogram "
                               "for feature with @index/name@ @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of a feature's split thresholds across the model
    (reference plotting.py plot_split_value_histogram)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    names = bst.feature_name()
    if isinstance(feature, str):
        fidx = names.index(feature)
    else:
        fidx = int(feature)
    values = []
    for tree in bst._models:
        for node in range(tree.num_nodes):
            if tree.split_feature[node] == fidx \
                    and not tree.is_categorical_node(node):
                values.append(tree.threshold[node])
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2.0
    widths = width_coef * np.diff(bin_edges)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.bar(centers, hist, width=widths, align="center", **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(hist) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature)).replace(
            "@index/name@", "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@", figsize=None, dpi=None,
                grid: bool = True):
    """Plot metric curves from a record_evaluation dict or fitted sklearn
    estimator (reference plotting.py plot_metric)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        eval_results = deepcopy(booster.evals_result_)
    else:
        raise TypeError(
            "booster must be dict (from record_evaluation) or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names_iter = iter(eval_results.keys())
    else:
        dataset_names_iter = iter(dataset_names)

    name = next(dataset_names_iter)
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError(
                "more than one metric available, pick one with the "
                "'metric' parameter")
        metric, results = metrics_for_one.popitem()
    else:
        if metric not in metrics_for_one:
            raise ValueError("No given metric in eval results.")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)

    for name in dataset_names_iter:
        if name not in eval_results:
            continue
        results = eval_results[name][metric]
        max_result = max(max(results), max_result)
        min_result = min(min(results), min_result)
        ax.plot(range(len(results)), results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        margin = 0.05 * (max_result - min_result + 1e-12)
        ylim = (min_result - margin, max_result + margin)
    ax.set_ylim(ylim)
    if ylabel is not None:
        ylabel = ylabel.replace("@metric@", metric)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _tree_label(tree, node: int, is_leaf: bool, show_info: List[str],
                precision: int, feature_names: List[str]) -> str:
    if is_leaf:
        parts = [f"leaf {node}",
                 f"value: {tree.leaf_value[node]:.{precision}f}"]
        if "leaf_count" in show_info:
            parts.append(f"count: {int(tree.leaf_count[node])}")
        if "leaf_weight" in show_info:
            parts.append(f"weight: {tree.leaf_weight[node]:.{precision}f}")
        return "\n".join(parts)
    f = tree.split_feature[node]
    fname = feature_names[f] if f < len(feature_names) else f"f{f}"
    if tree.is_categorical_node(node):
        dec = f"{fname} in categories"
    else:
        dec = f"{fname} <= {tree.threshold[node]:.{precision}f}"
    parts = [dec]
    if "split_gain" in show_info:
        parts.append(f"gain: {tree.split_gain[node]:.{precision}f}")
    if "internal_value" in show_info:
        parts.append(f"value: {tree.internal_value[node]:.{precision}f}")
    if "internal_count" in show_info:
        parts.append(f"count: {int(tree.internal_count[node])}")
    return "\n".join(parts)


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal", **kwargs):
    """Build a graphviz Digraph of one tree
    (reference plotting.py create_tree_digraph)."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "You must install graphviz and restart your session to "
            "plot tree.") from e

    bst = _to_booster(booster)
    if tree_index < 0 or tree_index >= len(bst._models):
        raise IndexError("tree_index is out of range.")
    tree = bst._models[tree_index]
    feature_names = bst.feature_name()
    show_info = show_info or []
    precision = 3 if precision is None else precision

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)

    def add(node: int, parent: Optional[str]) -> None:
        if node < 0:  # leaf
            leaf = ~node
            name = f"leaf{leaf}"
            graph.node(name, _tree_label(tree, leaf, True, show_info,
                                         precision, feature_names))
        else:
            name = f"split{node}"
            graph.node(name, _tree_label(tree, node, False, show_info,
                                         precision, feature_names))
            add(int(tree.left_child[node]), name)
            add(int(tree.right_child[node]), name)
        if parent is not None:
            graph.edge(parent, name)

    if tree.num_leaves <= 1:
        graph.node("leaf0", _tree_label(tree, 0, True, show_info,
                                        precision, feature_names))
    else:
        add(0, None)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: Optional[int] = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via graphviz
    (reference plotting.py plot_tree)."""
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    from io import BytesIO
    s = BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
