"""Finding renderers: human text and machine JSON."""

from __future__ import annotations

import json
from typing import List

__all__ = ["render_text", "render_json"]


def render_text(result) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.relpath}:{f.lineno}:{f.col + 1}: "
                     f"{f.rule} [{f.fid}]")
        lines.append(f"    {f.message}")
    if result.stale_baseline:
        lines.append("")
        lines.append("stale baseline entries (finding no longer "
                     "occurs — delete them):")
        for e in result.stale_baseline:
            lines.append(f"    {e.fid}")
    n = len(result.findings)
    b = len(result.baselined)
    lines.append("")
    lines.append(
        f"tpulint: {n} finding{'s' if n != 1 else ''}"
        + (f" ({b} baselined and suppressed)" if b else "")
        + f", {len(result.files)} files, "
        f"{len(result.graph.jit_reachable)} jit-reachable functions, "
        f"{result.elapsed:.2f}s")
    return "\n".join(lines)


def render_json(result) -> str:
    def fdict(f):
        return {"id": f.fid, "rule": f.rule, "path": f.relpath,
                "line": f.lineno, "col": f.col + 1, "function": f.func,
                "symbol": f.symbol, "message": f.message}

    return json.dumps({
        "findings": [fdict(f) for f in result.findings],
        "baselined": [fdict(f) for f in result.baselined],
        "stale_baseline": [e.fid for e in result.stale_baseline],
        "files": sorted(result.files),
        "jit_reachable": sorted(
            f"{p}:{q}" for (p, q) in result.graph.jit_reachable),
        "elapsed_seconds": result.elapsed,
    }, indent=2, sort_keys=False)
