# tpulint fixture: TPL006 negative — the watchdog idiom done right:
# state copied under the lock, the collective dispatched outside it.
import threading

import jax.numpy as jnp

_lock = threading.Lock()
_heartbeat = {"t": 0.0}


def guarded_sync(values):
    total = jnp.sum(values)          # dispatch outside any lock
    with _lock:
        _heartbeat["t"] = float(total)


def read_heartbeat():
    with _lock:
        return dict(_heartbeat)
