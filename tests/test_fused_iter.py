"""Fused-iteration fast path (gbdt.py _train_one_iter_fused).

One boosting iteration = ONE XLA program (gradients -> grow -> pack ->
contrib -> score update). The on-chip decomposition
(benchmarks/DECOMP_r05.txt) showed each separate program launch paying
~15-25 ms through the device tunnel — ~106 ms/iter of pure dispatch —
so the eager path's 6 launches/iter were the second-largest cost of
training after the grower itself.

Contract: for every eligible config the fused path must produce the
same model as the eager path (same split structure, leaf values to
float tolerance — host RNG streams are shared by construction, device
RNG keys by an identical fold_in schedule). Ineligible configs
(CEGB, GOSS, RenewTreeOutput objectives, DART/RF, linear trees, valid
sets, custom gradients, mesh) must fall back to the eager path and
keep working.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDTBooster


@pytest.fixture
def data():
    rs = np.random.RandomState(7)
    X = rs.randn(3000, 10)
    y = ((X[:, :4] @ rs.randn(4) + 0.3 * rs.randn(3000)) > 0).astype(float)
    return X, y


def _train(params, X, y, n=8, fused=True, valid=False):
    if not fused:
        orig = GBDTBooster._fused_ok
        GBDTBooster._fused_ok = lambda self: False
    try:
        ds = lgb.Dataset(X, label=y)
        kw = {}
        if valid:
            kw = {"valid_sets": [lgb.Dataset(X[:500], label=y[:500],
                                             reference=ds)]}
        return lgb.train(dict(params, verbosity=-1), ds,
                         num_boost_round=n, **kw)
    finally:
        if not fused:
            GBDTBooster._fused_ok = orig


def _assert_same_model(a, b, rtol=1e-5, atol=1e-6):
    assert len(a._models) == len(b._models)
    for ta, tb in zip(a._models, b._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn], tb.split_feature[:nn])
        # trees adopted through init_model / checkpoint restore carry
        # threshold_bin = -1 (re-mapped lazily against the current
        # mappers, basic.Booster._preload); where EITHER side is
        # unbinned, the real-valued thresholds are the identity
        ba, bb = ta.threshold_bin[:nn], tb.threshold_bin[:nn]
        both = (ba >= 0) & (bb >= 0)
        assert np.array_equal(ba[both], bb[both])
        np.testing.assert_allclose(ta.threshold[:nn], tb.threshold[:nn],
                                   rtol=0, atol=0)
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=rtol, atol=atol)


ELIGIBLE = [
    ("plain", {"objective": "binary", "num_leaves": 15}),
    ("bagging", {"objective": "binary", "num_leaves": 15,
                 "bagging_fraction": 0.7, "bagging_freq": 2,
                 "bagging_seed": 5}),
    ("pos_neg_bagging", {"objective": "binary", "num_leaves": 15,
                         "pos_bagging_fraction": 0.8,
                         "neg_bagging_fraction": 0.6, "bagging_freq": 1}),
    ("quantized", {"objective": "binary", "num_leaves": 15,
                   "use_quantized_grad": True}),
    ("colsample", {"objective": "binary", "num_leaves": 15,
                   "feature_fraction": 0.7,
                   "feature_fraction_bynode": 0.8}),
    ("regression", {"objective": "regression", "num_leaves": 15}),
    ("monotone", {"objective": "regression", "num_leaves": 15,
                  "monotone_constraints": [1, -1] + [0] * 8}),
    # Pallas histogram kernel inside the fused program (interpret mode
    # on CPU): growth rides the same sibling-subtraction pipeline, so
    # fused == eager proves the kernel composes with the one-program
    # iteration (tests/test_pallas_hist.py owns numeric parity)
    ("pallas_hist", {"objective": "binary", "num_leaves": 15,
                     "hist_method": "pallas"}),
    ("pallas_quantized", {"objective": "binary", "num_leaves": 15,
                          "hist_method": "pallas",
                          "use_quantized_grad": True}),
    # depth-wise level grower fused into the one-program iteration
    ("level_grower", {"objective": "binary", "num_leaves": 15,
                      "max_depth": 4, "grower": "level"}),
    ("level_pallas", {"objective": "binary", "num_leaves": 15,
                      "max_depth": 4, "grower": "level",
                      "hist_method": "pallas"}),
]


@pytest.mark.parametrize("name,params", ELIGIBLE, ids=[e[0] for e in ELIGIBLE])
def test_fused_matches_eager(name, params, data):
    X, y = data
    yy = X[:, 0] * 2 + X[:, 1] if params["objective"] == "regression" else y
    a = _train(params, X, yy, fused=True)
    b = _train(params, X, yy, fused=False)
    assert a._engine._fused_fn is not None, "fused path did not engage"
    assert b._engine._fused_fn is None
    _assert_same_model(a, b)
    np.testing.assert_allclose(a.predict(X[:400]), b.predict(X[:400]),
                               rtol=1e-5, atol=1e-6)


def test_fused_multiclass_matches_eager(data):
    X, y = data
    y3 = (y + (X[:, 5] > 0)).astype(float)  # 3 well-populated classes
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7}
    a = _train(params, X, y3, fused=True)
    b = _train(params, X, y3, fused=False)
    assert a._engine._fused_fn is not None
    _assert_same_model(a, b)


@pytest.mark.parametrize("params", [
    {"objective": "regression_l1", "num_leaves": 15},   # need_renew
    {"objective": "binary", "boosting": "dart", "num_leaves": 15},
    {"objective": "binary", "data_sample_strategy": "goss",
     "num_leaves": 15},
    {"objective": "binary", "num_leaves": 15, "linear_tree": True},
], ids=["renew-objective", "dart", "goss", "linear-tree"])
def test_ineligible_configs_fall_back_and_train(params, data):
    X, y = data
    yy = X[:, 0] * 2 if params["objective"] == "regression_l1" else y
    bst = _train(params, X, yy, n=5)
    assert bst._engine._fused_fn is None, "fused path must not engage"
    assert len(bst._models) == 5
    assert np.isfinite(bst.predict(X[:100])).all()


def test_ranking_falls_back(data):
    """Ranking objectives mutate host state per iteration (lambdarank
    position biases, xendcg's key counter); under a traced program
    those updates would freeze at trace time — they must stay eager."""
    X, y = data
    group = [300] * 10
    for obj in ("lambdarank", "rank_xendcg"):
        ds = lgb.Dataset(X, label=(y * 3).astype(int), group=group)
        bst = lgb.train({"objective": obj, "num_leaves": 15,
                         "verbosity": -1}, ds, num_boost_round=4)
        assert bst._engine._fused_fn is None, obj
        assert len(bst._models) == 4


def test_valid_sets_fall_back(data):
    X, y = data
    bst = _train({"objective": "binary", "num_leaves": 15}, X, y, n=5,
                 valid=True)
    assert bst._engine._fused_fn is None
    assert len(bst._models) == 5


def test_fused_rollback_and_continue(data):
    """rollback_one_iter after fused iterations, then continue: the
    deferred-tree queue, score, and iteration counter all stay
    consistent (the Booster.rollback API is what network training and
    early-stopping-with-refit use)."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(4):
        bst._engine.train_one_iter()
    assert bst._engine._fused_fn is not None
    bst.rollback_one_iter()
    assert bst.current_iteration() == 3
    for _ in range(2):
        bst._engine.train_one_iter()
    assert bst.current_iteration() == 5
    # equivalent straight-through run of the SAME final tree sequence:
    # iterations 0,1,2 then 3,4 recompute on the rolled-back state
    assert np.isfinite(bst.predict(X[:100])).all()


def test_fused_bagging_toggle_mid_training(data):
    """reset_parameter can switch bagging on mid-training
    (LGBM_BoosterResetParameter); the fused path must evaluate the
    bagging gate live, matching the eager path's per-iteration cfg
    read — not an __init__-time snapshot."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}

    def run(fused):
        if not fused:
            orig = GBDTBooster._fused_ok
            GBDTBooster._fused_ok = lambda self: False
        try:
            bst = lgb.Booster(params=dict(params),
                              train_set=lgb.Dataset(X, label=y))
            for _ in range(3):
                bst._engine.train_one_iter()
            bst.reset_parameter({"bagging_fraction": 0.6,
                                 "bagging_freq": 1})
            for _ in range(3):
                bst._engine.train_one_iter()
            return bst
        finally:
            if not fused:
                GBDTBooster._fused_ok = orig

    a, b = run(True), run(False)
    assert a._engine._fused_fn is not None
    _assert_same_model(a, b)
    # and the toggle actually changed the trees (bagging engaged)
    c = _train(params, X, y, n=6, fused=True)
    assert any(ta.num_leaves != tc.num_leaves
               or not np.allclose(ta.leaf_value, tc.leaf_value)
               for ta, tc in zip(a._models[3:], c._models[3:]))


def test_fused_bynode_reset_mid_training(data):
    """feature_fraction_bynode is baked into the traced grow program;
    reset_parameter must re-trace BOTH paths (refresh grow_cfg, drop
    the cached fused program) so they keep matching."""
    X, y = data

    def run(fused):
        if not fused:
            orig = GBDTBooster._fused_ok
            GBDTBooster._fused_ok = lambda self: False
        try:
            bst = lgb.Booster(
                params={"objective": "binary", "num_leaves": 15,
                        "feature_fraction_bynode": 0.7, "verbosity": -1},
                train_set=lgb.Dataset(X, label=y))
            for _ in range(3):
                bst._engine.train_one_iter()
            bst.reset_parameter({"feature_fraction_bynode": 1.0})
            for _ in range(3):
                bst._engine.train_one_iter()
            return bst
        finally:
            if not fused:
                GBDTBooster._fused_ok = orig

    a, b = run(True), run(False)
    assert a._engine._fused_fn is not None
    _assert_same_model(a, b)


def test_fused_init_model_continuation(data):
    """Training continued from a saved model (init_model) goes through
    preload_models; the fused path must keep producing the same trees
    as an uninterrupted run (keys are folded with the absolute
    iteration index, so the streams line up)."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    full = _train(params, X, y, n=6)
    half = _train(params, X, y, n=3)
    cont = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3,
                     init_model=half)
    assert len(cont._models) == 6
    _assert_same_model(full, cont)


def test_bynode_reset_rebuilds_distributed_grow_fn(data):
    """reset_parameter('feature_fraction_bynode') under mesh training:
    the distributed grow fn bakes grow_cfg + a has_node_key flag at
    build time, so the reset must rebuild it (not just the fused/eager
    paths) — enabling bynode mid-training used to crash with an arity
    mismatch, disabling silently kept sampling."""
    import jax
    if len(jax.devices()) < 2:
        import pytest
        pytest.skip("needs the multi-device CPU mesh")
    X, y = data
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "tree_learner": "data", "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(2):
        bst._engine.train_one_iter()
    bst.reset_parameter({"feature_fraction_bynode": 0.6})
    for _ in range(2):
        bst._engine.train_one_iter()
    bst.reset_parameter({"feature_fraction_bynode": 1.0})
    for _ in range(2):
        bst._engine.train_one_iter()
    assert len(bst._models) == 6
    assert np.isfinite(bst.predict(X[:100])).all()
