"""Device trace of one WIDE (Allstate-shaped, EFB-bundled) iteration.

Round-5 diagnostic for the ~10 ms/split fixed cost at width
(benchmarks/PROFILE.md "131K x 4228 diagnostic"): traces one
train_one_iter at BENCH_ROWS x BENCH_FEATURES through the real
engine, parses the xplane directly and prints device-time by op
category, so the per-split fixed path can be attributed to actual
HLOs instead of suspicion.

Run on TPU:  python benchmarks/wide_trace.py
Env: BENCH_ROWS (131072), BENCH_FEATURES (4228), BENCH_LEAVES (255)
"""
import collections
import glob
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N = int(os.environ.get("BENCH_ROWS", 131_072))
F = int(os.environ.get("BENCH_FEATURES", 4228))
L = int(os.environ.get("BENCH_LEAVES", 255))
TRACE_DIR = os.environ.get("TRACE_DIR", "/tmp/wide_trace")


def make_allstate_like(n, f, seed=0, per_group=128):
    rs = np.random.RandomState(seed)
    groups = f // per_group
    X = np.zeros((n, f), np.float32)
    signal = np.zeros(n, np.float32)
    vals = np.random.RandomState(12345).rand(
        groups, per_group).astype(np.float32) * 2
    rows = np.arange(n)
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        X[rows, g * per_group + pick] = vals[g, pick]
        signal += vals[g, pick]
    nanmask = rs.rand(n) < 0.1
    X[nanmask, 0] = np.nan
    y = (signal > np.median(signal)).astype(np.float32)
    return X, y.astype(np.float64)


def main():
    import jax
    import lightgbm_tpu as lgb

    X, y = make_allstate_like(N, F)
    t0 = time.time()
    ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
    ds.construct()
    del X
    print(f"construct: {time.time() - t0:.1f} s", flush=True)

    bst = lgb.Booster(params={"objective": "binary", "num_leaves": L,
                              "max_bin": 255, "learning_rate": 0.1,
                              "verbosity": -1}, train_set=ds)
    eng = bst._engine
    if eng.bundle is not None:
        print(f"bundles: {len(eng.bundle.groups)} "
              f"(from {F} features)", flush=True)

    t0 = time.time()
    eng.train_one_iter()
    eng.score.block_until_ready()
    print(f"warmup (incl compile): {time.time() - t0:.1f} s", flush=True)
    t0 = time.time()
    eng.train_one_iter()
    eng.score.block_until_ready()
    steady = time.time() - t0
    print(f"steady: {steady * 1e3:.1f} ms/iter", flush=True)

    with jax.profiler.trace(TRACE_DIR):
        eng.train_one_iter()
        eng.score.block_until_ready()

    report(steady)


def report(steady):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(glob.glob(
        os.path.join(TRACE_DIR, "**", "*.xplane.pb"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        print("no xplane written", flush=True)
        return
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as f:
        xs.ParseFromString(f.read())

    # device plane: op events with durations
    by_op = collections.Counter()
    n_ev = collections.Counter()
    total_ps = 0
    for plane in xs.planes:
        if "TPU" not in plane.name and "/device" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        for line in plane.lines:
            if "XLA Ops" not in line.name and "Steps" not in line.name \
                    and "XLA Modules" not in line.name:
                # keep only the op-level line when present
                pass
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                if line.name.startswith("XLA Ops"):
                    by_op[name] += ev.duration_ps
                    n_ev[name] += 1
                    total_ps += ev.duration_ps

    # bucket by HLO category (fusion names carry the root op)
    def cat(name):
        m = re.match(r"%?([a-z-]+)", name)
        base = m.group(1) if m else name
        return base

    by_cat = collections.Counter()
    for name, ps in by_op.items():
        by_cat[cat(name)] += ps

    print(f"\ndevice total: {total_ps / 1e9:.1f} ms "
          f"(steady wall {steady * 1e3:.1f} ms)")
    print("\n-- by category --")
    for name, ps in by_cat.most_common(15):
        print(f"{name:40s} {ps / 1e9:9.1f} ms")
    print("\n-- top individual ops --")
    for name, ps in by_op.most_common(30):
        print(f"{name[:90]:90s} {ps / 1e9:9.2f} ms  x{n_ev[name]}")


if __name__ == "__main__":
    main()
