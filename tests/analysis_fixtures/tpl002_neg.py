# tpulint fixture: TPL002 negative — no findings expected.
import jax
import jax.numpy as jnp
import numpy as np

_TABLE = np.asarray([1.0, 2.0, 4.0])  # module level: host code, fine


@jax.jit
def traced_const(x):
    # np on values NOT derived from parameters = trace-time constant
    # folding (building a static table), not a runtime sync
    table = np.asarray([0.0, 1.0])
    return x + jnp.asarray(table) + jnp.asarray(_TABLE[0])


# tpulint: hot
def hot_but_async(vec):
    # the async-copy API is the FIX for TPL002, never a finding
    vec.copy_to_host_async()
    return vec


def cold_host_path(x):
    # not traced, not hot: host materialization is this layer's job
    arr = np.asarray(x)
    return float(arr[0])


# tpulint: hot
def hot_with_justified_sync(flags):
    # tpulint: disable=TPL002 flags were copy_to_host_async'd an iteration ago
    return np.asarray(flags)
