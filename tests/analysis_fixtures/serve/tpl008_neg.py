# tpulint fixture: TPL008 negative — the same micro-batcher as
# serve/tpl008_pos.py with every worker/caller-shared field guarded by
# one common lock (proved on the lock-acquisition CFG), the request
# handoff on a Queue (sync primitives are exempt), and the jax-side
# dispatch outside the lock. No EXPECT lines.
import queue
import threading

_inflight = []
_inflight_lock = threading.Lock()


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue()
        self.pending_rows = 0
        self.requests_total = 0
        self._worker = threading.Thread(target=self._worker_loop,
                                        daemon=True)
        self._worker.start()

    def _worker_loop(self):
        while True:
            req = self._queue.get()
            with self._lock:
                self.pending_rows = 0
                self.requests_total += 1
            req.run()       # dispatch outside the lock (TPL006 shape)

    def submit(self, n):
        with self._lock:
            self.pending_rows += n
            return self.pending_rows

    def stats(self):
        with self._lock:
            return {"pending": self.pending_rows,
                    "requests": self.requests_total}


def _drain_worker():
    with _inflight_lock:
        _inflight.clear()


def start_drain():
    threading.Thread(target=_drain_worker).start()
    with _inflight_lock:
        return list(_inflight)
