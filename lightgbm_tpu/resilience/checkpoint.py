"""Atomic training checkpoints + auto-resume.

One snapshot = one ``ckpt_<iteration>.npz`` file written atomically
(in-memory npz -> same-directory tmp -> ``os.replace``, via
utils/atomic.py), so a process killed mid-write can never leave a
truncated snapshot: the directory always holds only complete files plus
at most one orphaned ``*.tmp`` that readers ignore.

A snapshot carries everything a bit-exact continuation needs:

- the model text (same ``%.17g`` format as ``save_model``; float64
  leaf values round-trip exactly),
- the raw-score matrix ``[K, n]`` float32 — restored verbatim instead
  of being recomputed from trees, because the incremental in-program
  f32 score accumulation and a from-scratch traversal can differ in the
  last ulp, which would eventually flip a split,
- the host RNG streams (per-tree feature sampling, DART drop RNG) by
  Mersenne state, and per-model tree weights,
- bookkeeping: iteration, best_score / best_iteration, string
  attributes, and a parameter fingerprint (mismatches at resume warn,
  they do not fail).

Device-keyed streams (bagging, GOSS, quantization, by-node sampling)
are pure ``fold_in(key, iteration)`` functions and need no state; the
bagging *cache* (re-used between refresh iterations) is re-derived at
restore from the last refresh iteration's key.

Resume flow: ``train(..., resume_from=dir)`` — or the
``LIGHTGBM_TPU_CHECKPOINT=<dir>`` environment variable, which also
installs the checkpoint callback — loads the newest snapshot that
validates, silently skipping corrupted/truncated files in favor of the
previous one, and continues training at the recorded iteration toward
``num_boost_round`` *total* iterations. See docs/RESILIENCE.md.
"""

from __future__ import annotations

import io
import json
import os
import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.atomic import atomic_write_bytes
from ..utils.log import log_info, log_warning

__all__ = ["checkpoint", "Checkpoint", "CheckpointError", "snapshot_path",
           "write_snapshot", "load_snapshot", "load_latest_snapshot",
           "list_snapshots", "restore_booster"]

CHECKPOINT_MAGIC = "lightgbm_tpu.checkpoint.v1"
_FILE_RE = re.compile(r"^ckpt_(\d{8})\.npz$")


class CheckpointError(ValueError):
    """A snapshot file failed validation (corrupt / truncated / foreign)."""


def snapshot_path(directory: str, iteration: int) -> str:
    return os.path.join(os.fspath(directory), f"ckpt_{iteration:08d}.npz")


# ---------------------------------------------------------------------
# RNG state (numpy legacy MT19937 tuple) <-> npz-storable pieces
# ---------------------------------------------------------------------

def _rng_state_arrays(rng: np.random.RandomState):
    name, keys, pos, has_gauss, cached = rng.get_state()
    meta = {"name": name, "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached)}
    return np.asarray(keys, np.uint32), meta


def _rng_restore(rng: np.random.RandomState, keys: np.ndarray,
                 meta: Dict[str, Any]) -> None:
    rng.set_state((meta["name"], np.asarray(keys, np.uint32),
                   int(meta["pos"]), int(meta["has_gauss"]),
                   float(meta["cached_gaussian"])))


# ---------------------------------------------------------------------
# write
# ---------------------------------------------------------------------

def write_snapshot(directory: str, booster, keep: int = 3,
                   score_host=None) -> str:
    """Snapshot ``booster`` into ``directory`` atomically; prune old
    snapshots beyond ``keep``. Returns the snapshot path.

    ``score_host``: the assembled ``[K, n]`` score matrix, required on
    a multi-controller mesh whose score is globally sharded — the
    assembly is a world collective (``placement.fetch_global``), so a
    rank-gated caller (the checkpoint callback writes rank-0-only)
    must run it on EVERY rank first and pass the result down; this
    function itself never joins a collective."""
    eng = booster._engine
    if eng is None:
        raise CheckpointError(
            "cannot checkpoint a prediction-only Booster (no engine)")
    # drain the one-iteration-late non-finite guard flags FIRST: under
    # nonfinite_policy=raise a poisoned iteration must raise here,
    # before its NaN trees/score become the newest "valid" snapshot
    # that auto-resume would then restore forever
    drain = getattr(eng, "finish_faults", None)
    if drain is not None:
        drain()
    # the one-iteration-late no-growth marker must survive resume: if
    # the just-finished iteration grew nothing (and not because a
    # skip_tree fault demoted it), the NEXT update() of an
    # uninterrupted run stops before growing — a resumed run has to
    # make the same call, or it regrows an extra constant tree (and
    # burns an extra feature-RNG draw), breaking byte-exact resume.
    # Reading the async counts does not consume the engine's queue.
    nl_pending = [int(np.asarray(x))
                  for x in getattr(eng, "_nl_async", [])]
    stalled = (getattr(eng, "_finished_natural", False)
               or (bool(nl_pending) and all(nl <= 1 for nl in nl_pending)
                   and not getattr(eng, "_fault_recent", False)))
    # model_to_string flushes the async pending-tree queue, so the
    # score fetched below is consistent with the serialized trees
    model_str = booster.model_to_string()
    iteration = int(eng.iter_)
    frng_keys, frng_meta = _rng_state_arrays(eng._feature_rng)
    drng_keys, drng_meta = _rng_state_arrays(eng._dart_rng)
    # a device-resident run holds the score SHARDED over the mesh
    # (shard_residency=device, parallel/placement.py): the snapshot
    # always stores the assembled [K, n] host matrix — so resume works
    # across residency modes — plus one sha256 per device shard, the
    # identity a re-placed score is verified against at restore
    # (docs/SHARDING.md)
    from ..parallel import placement
    if score_host is None:
        score_host = placement.fetch_addressable(eng.score)
    score_host = np.asarray(score_host, np.float32)
    score_fps = placement.shard_fingerprints(eng.score)
    state = {
        "magic": CHECKPOINT_MAGIC,
        "iteration": iteration,
        "num_trees": len(booster._models),
        "num_model_per_iteration": int(eng.K),
        # init_model offset of a continued-training run: resume must
        # finish at init + num_boost_round, not num_boost_round
        # (engine.py iteration window; docs/PIPELINE.md warm start)
        "num_init_iteration": int(getattr(eng, "init_iteration", 0)),
        "best_iteration": int(booster.best_iteration),
        "best_score": {str(d): {str(m): float(v)
                                for m, v in sub.items()}
                       for d, sub in (booster.best_score or {}).items()},
        "tree_weights": [float(w) for w in eng._tree_weights],
        "feature_rng": frng_meta,
        "dart_rng": drng_meta,
        "attrs": dict(booster._attrs),
        "train_data_name": booster._train_data_name,
        "params_fingerprint": _params_fingerprint(booster.params),
        "data_fingerprint": _dataset_fingerprint(eng),
        "stalled": stalled,
        "score_shard_fingerprints": score_fps,
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        state_json=np.frombuffer(
            json.dumps(state).encode("utf-8"), np.uint8),
        model_str=np.frombuffer(model_str.encode("utf-8"), np.uint8),
        score=score_host,
        frng_keys=frng_keys,
        drng_keys=drng_keys,
    )
    path = snapshot_path(directory, iteration)
    atomic_write_bytes(path, buf.getvalue())
    _prune(os.fspath(directory), keep)
    return path


def _dataset_fingerprint(eng) -> Dict[str, Any]:
    """Cheap identity of the TRAINING DATA a snapshot was written
    against: shape plus a sha256 over the labels and the first binned
    rows. Guards the hands-off env-var mode, where a still-exported
    ``LIGHTGBM_TPU_CHECKPOINT`` plus a second experiment on different
    data of the same shape would otherwise silently continue the first
    run's trees. A streaming construct (lightgbm_tpu/data/) accumulated
    the identical digest incrementally over its pass-2 label/bin chunks
    (``data.ingest.dataset_digest``), so resume works across ingestion
    modes — and still refuses different data. Hashed once per engine
    (the data is immutable during training), so per-snapshot cost is a
    dict lookup."""
    cached = getattr(eng, "_ckpt_data_fp", None)
    if cached is not None:
        return cached
    digest = getattr(eng.train_set, "_data_digest", None)
    if digest is None:
        from ..data.ingest import dataset_digest
        digest = dataset_digest(
            np.asarray(eng.train_set.get_label(), np.float64),
            eng.train_set.host_bins())
    fp = {"n": int(eng.n), "F": int(eng.F), "K": int(eng.K),
          "digest": digest}
    eng._ckpt_data_fp = fp
    return fp


#: params whose drift between write and resume is expected and benign
#: (the resume target legitimately differs; IO paths don't shape the
#: model). shard_residency / split_search are model-neutral by
#: construction (byte-identical trees either way, docs/SHARDING.md),
#: so resuming a device-resident snapshot on a host-resident run — or
#: flipping the split search — is a supported topology change, not
#: drift.
_FINGERPRINT_IGNORE = {"num_iterations", "input_model", "output_model",
                       "snapshot_freq", "data", "valid", "output_result",
                       "shard_residency", "split_search",
                       # pure perf knob: the scan window re-partitions
                       # the SAME iteration stream (models byte-equal
                       # under any windowing — tests/test_fused_scan.py),
                       # so a resume may legally change or disable it
                       "fused_scan_iters"}


def _params_fingerprint(params) -> Dict[str, str]:
    from ..config import resolve_params
    return {str(k): str(v) for k, v in
            sorted(resolve_params(params or {}).items())
            if k not in _FINGERPRINT_IGNORE}


def _prune(directory: str, keep: int) -> None:
    if keep is None or keep <= 0:
        return
    snaps = sorted(_snapshot_files(directory))
    for _, name in snaps[:-keep]:
        try:
            os.unlink(os.path.join(directory, name))
        except OSError:
            pass


def _snapshot_files(directory: str):
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        m = _FILE_RE.match(name)
        if m:
            out.append((int(m.group(1)), name))
    return out


# ---------------------------------------------------------------------
# read
# ---------------------------------------------------------------------

def load_snapshot(path: str) -> Dict[str, Any]:
    """Load + validate one snapshot. Raises :class:`CheckpointError`
    on anything short of a complete, well-formed file."""
    try:
        with np.load(path, allow_pickle=False) as z:
            files = set(z.files)
            required = {"state_json", "model_str", "score",
                        "frng_keys", "drng_keys"}
            missing = required - files
            if missing:
                raise CheckpointError(
                    f"{path}: missing members {sorted(missing)}")
            state = json.loads(bytes(z["state_json"]).decode("utf-8"))
            if state.get("magic") != CHECKPOINT_MAGIC:
                raise CheckpointError(f"{path}: bad magic "
                                      f"{state.get('magic')!r}")
            snap = dict(state)
            snap["model_str"] = bytes(z["model_str"]).decode("utf-8")
            snap["score"] = np.asarray(z["score"], np.float32)
            snap["frng_keys"] = np.asarray(z["frng_keys"], np.uint32)
            snap["drng_keys"] = np.asarray(z["drng_keys"], np.uint32)
    except CheckpointError:
        raise
    except Exception as e:  # zip/json/np errors: corrupt or foreign file
        raise CheckpointError(f"{path}: unreadable snapshot ({e})") from e
    if snap["score"].ndim != 2:
        raise CheckpointError(f"{path}: score must be [K, n]")
    snap["path"] = path
    return snap


def load_latest_snapshot(directory: str) -> Optional[Dict[str, Any]]:
    """Newest snapshot in ``directory`` that validates; corrupted or
    truncated files are skipped (with a warning) in favor of the
    previous one. None when the directory holds no usable snapshot."""
    directory = os.fspath(directory)
    for _, name in sorted(_snapshot_files(directory), reverse=True):
        path = os.path.join(directory, name)
        try:
            return load_snapshot(path)
        except CheckpointError as e:
            log_warning(f"checkpoint: skipping invalid snapshot: {e}")
    return None


def list_snapshots(directory: str) -> List[Dict[str, Any]]:
    """Every ``ckpt_*.npz`` in ``directory`` with validation status —
    the ``lightgbm_tpu checkpoints <dir>`` inspection surface."""
    out = []
    directory = os.fspath(directory)
    for it, name in sorted(_snapshot_files(directory)):
        path = os.path.join(directory, name)
        row: Dict[str, Any] = {
            "path": path, "iteration": it,
            "bytes": os.path.getsize(path),
            "mtime": os.path.getmtime(path),
        }
        try:
            snap = load_snapshot(path)
            row.update(status="ok", num_trees=snap["num_trees"],
                       best_iteration=snap["best_iteration"])
        except CheckpointError as e:
            row.update(status="corrupt", error=str(e))
        out.append(row)
    return out


# ---------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------

def restore_booster(booster, snap: Dict[str, Any]) -> int:
    """Install a snapshot into a freshly-built training Booster and
    return the iteration to continue from."""
    from ..basic import Booster, LightGBMError

    eng = booster._engine
    if eng is None:
        raise LightGBMError("restore requires a Booster built with a "
                            "train_set")
    fp_now = _params_fingerprint(booster.params)
    fp_then = snap.get("params_fingerprint") or {}
    drift = {k for k in set(fp_now) | set(fp_then)
             if fp_now.get(k) != fp_then.get(k)}
    if drift:
        log_warning(
            "checkpoint: resuming with different parameters than the "
            f"snapshot was written with ({', '.join(sorted(drift))}); "
            "the resumed model will not match an uninterrupted run")
    fp_data = snap.get("data_fingerprint")
    if fp_data is not None and fp_data != _dataset_fingerprint(eng):
        raise LightGBMError(
            f"checkpoint {snap.get('path')} was written against "
            "different training data (label/bin fingerprint mismatch) "
            "— refusing to silently continue another run's trees. "
            "Point resume_from/LIGHTGBM_TPU_CHECKPOINT at a fresh "
            "directory for this dataset.")
    parsed = Booster(model_str=snap["model_str"])
    trees = parsed._trees
    if len(trees) != int(snap["num_trees"]):
        raise LightGBMError(
            f"checkpoint {snap.get('path')}: model text holds "
            f"{len(trees)} trees, state says {snap['num_trees']}")
    score = np.asarray(snap["score"], np.float32)
    if score.shape != (eng.K, eng.n):
        raise LightGBMError(
            f"checkpoint {snap.get('path')}: score shape {score.shape} "
            f"does not match this training set [{eng.K}, {eng.n}] — "
            "was the checkpoint written against different data?")
    eng.preload_models(trees, score=score)
    # re-placed sharded score (shard_residency=device) must byte-match
    # what the snapshot saved: when the restored layout matches the
    # written one, recompute the per-shard sha256s and compare —
    # resume-equality holds by proof, not assumption. Cross-residency
    # resumes (device snapshot -> host run or vice versa) skip the
    # check; the assembled matrix was installed verbatim either way.
    from ..parallel import placement
    fps_then = snap.get("score_shard_fingerprints")
    fps_now = placement.shard_fingerprints(eng.score)
    if fps_then and fps_now:
        then = {f["index"]: f["sha256"] for f in fps_then}
        now = {f["index"]: f["sha256"] for f in fps_now}
        if set(then) == set(now) and then != now:
            bad = sorted(k for k in then if then[k] != now[k])
            raise LightGBMError(
                f"checkpoint {snap.get('path')}: re-placed score "
                f"shards differ from the saved ones at {bad} — the "
                "device placement corrupted the score matrix")
    eng.init_iteration = int(snap.get("num_init_iteration", 0))
    eng._resume_stalled = bool(snap.get("stalled", False))
    eng._tree_weights = [float(w) for w in snap.get("tree_weights", [])] \
        or [1.0] * len(trees)
    _rng_restore(eng._feature_rng, snap["frng_keys"], snap["feature_rng"])
    _rng_restore(eng._dart_rng, snap["drng_keys"], snap["dart_rng"])
    _rewarm_bagging_cache(eng, int(snap["iteration"]))
    booster.best_iteration = int(snap.get("best_iteration", -1))
    booster.best_score = {
        d: dict(sub) for d, sub in (snap.get("best_score") or {}).items()}
    booster._attrs = dict(snap.get("attrs") or {})
    booster._train_data_name = snap.get("train_data_name",
                                        booster._train_data_name)
    return int(snap["iteration"])


def _rewarm_bagging_cache(eng, iteration: int) -> None:
    """Re-derive the cached bagging weights an uninterrupted run would
    be holding at ``iteration``: the draw from the last refresh
    iteration (``_row_weights`` reuses it until the next refresh)."""
    cfg = eng.cfg
    bag_active = cfg.bagging_freq > 0 and (
        cfg.bagging_fraction < 1.0 or cfg.pos_bagging_fraction < 1.0
        or cfg.neg_bagging_fraction < 1.0)
    if not bag_active or iteration <= 0 \
            or cfg.data_sample_strategy == "goss":
        return
    last_refresh = (iteration // cfg.bagging_freq) * cfg.bagging_freq
    if last_refresh >= iteration:
        return  # next iteration draws fresh anyway
    eng._cached_bag = None
    eng._row_weights(last_refresh, None, None)


# ---------------------------------------------------------------------
# callback
# ---------------------------------------------------------------------

@dataclass(eq=False)
class Checkpoint:
    """Periodic atomic snapshot callback (after-iteration, order 50 so
    the iteration's telemetry event lands first)."""
    directory: str
    every_n_iters: int = 1
    keep: int = 3
    order: int = 50
    before_iteration: bool = False
    _warned_unsupported: bool = False

    def __call__(self, env) -> None:
        eng = getattr(env.model, "_engine", None)
        if eng is None:
            if not self._warned_unsupported:
                self._warned_unsupported = True
                log_warning("checkpoint: cv()/CVBooster checkpointing is "
                            "not supported; callback disabled")
            return
        it = int(eng.iter_)
        last = env.iteration + 1 >= env.end_iteration
        if it <= 0 or (not last and self.every_n_iters > 1
                       and it % self.every_n_iters != 0):
            return
        # under multi-process SPMD every rank holds the identical
        # replicated model: verify that before rank 0 writes for all
        try:
            import jax
            nproc, rank = jax.process_count(), jax.process_index()
        except Exception:
            nproc, rank = 1, 0
        score_host = None
        if nproc > 1:
            from ..parallel.spmd import verify_step_consistency
            verify_step_consistency(
                it, len(eng._models_store) + len(eng._pending_dev))
            # a globally-sharded score (shard_residency=device on a
            # multi-controller mesh) is assembled by a WORLD collective
            # — every rank joins the gather HERE, above the rank gate;
            # only the file write below is rank-0-only (TPL007)
            from ..parallel import placement
            score_host = placement.fetch_global(eng.score)
            if rank != 0:
                return
        path = write_snapshot(self.directory, env.model, keep=self.keep,
                              score_host=score_host)
        log_info(f"checkpoint: wrote {path}")


def checkpoint(directory: str, every_n_iters: int = 1,
               keep: int = 3) -> Checkpoint:
    """Create the checkpoint callback: atomically snapshot the model
    and training state into ``directory`` every ``every_n_iters``
    boosting iterations (and at the final one), retaining the ``keep``
    newest snapshots. Pair with ``train(..., resume_from=directory)``
    or ``LIGHTGBM_TPU_CHECKPOINT=<directory>`` to survive crashes."""
    if every_n_iters <= 0:
        raise ValueError("every_n_iters must be positive")
    return Checkpoint(directory=os.fspath(directory),
                      every_n_iters=int(every_n_iters), keep=int(keep))
