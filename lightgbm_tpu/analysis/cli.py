"""``python -m lightgbm_tpu lint`` — the tpulint CLI.

Deliberately importable (and runnable) WITHOUT jax: the dispatcher in
``lightgbm_tpu/__main__.py`` routes ``lint`` here before the training
CLI (and its jax import) ever loads, so the analyzer runs in
environments that cannot initialize a backend at all (CI formatters,
pre-commit hooks, docs builds).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

EXIT_CODES = """\
exit codes:
  0  clean: no findings outside the baseline
  1  findings (or stale/unjustified baseline entries with --strict)
  2  usage or internal error
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu lint",
        description=(
            "tpulint: JAX/TPU-aware static analyzer for the boosting "
            "hot path. Builds a cross-module call graph, computes "
            "jit-reachability (which functions are only ever entered "
            "through a jax.jit/pjit/shard_map wrapper), and checks "
            "the hazard catalog TPL001-TPL006 (eager lax loops, host "
            "syncs, recompile storms, donation violations, "
            "order-unstable iteration, locks across dispatch). "
            "See docs/STATIC_ANALYSIS.md."),
        epilog=EXIT_CODES,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format (default: text)")
    p.add_argument("--baseline", metavar="FILE", default=None,
                   help="accepted-findings file (default: "
                        "tools/tpulint_baseline.txt when present; "
                        "pass an empty string to disable)")
    p.add_argument("--rule", metavar="TPLNNN", action="append",
                   default=None,
                   help="run only this rule (repeatable); default: "
                        "TPL001-TPL006")
    p.add_argument("--root", metavar="DIR", default=None,
                   help="package directory to analyze (default: the "
                        "installed lightgbm_tpu package)")
    p.add_argument("--write-baseline", metavar="FILE", default=None,
                   help="write ALL current findings to FILE as a "
                        "baseline skeleton (justifications left as "
                        "TODOs) and exit 0")
    p.add_argument("--strict", action="store_true",
                   help="also fail (exit 1) on stale or unjustified "
                        "baseline entries")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    if args.write_baseline and args.rule:
        # a rule-filtered run sees only a slice of the findings;
        # writing it out would silently drop every other rule's
        # accepted entries (and their justifications)
        print("tpulint: error: --write-baseline requires a full run "
              "(drop --rule)", file=sys.stderr)
        return 2
    from .engine import run_lint
    try:
        result = run_lint(root=args.root, rules=args.rule,
                          baseline_path=args.baseline)
    except (ValueError, OSError, SyntaxError) as e:
        print(f"tpulint: error: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        result.write_baseline(args.write_baseline)
        print(f"tpulint: wrote {len(result.findings) + len(result.baselined)} "
              f"entries to {args.write_baseline}")
        return 0
    if args.format == "json":
        from .report import render_json
        print(render_json(result))
    else:
        from .report import render_text
        print(render_text(result))
    if result.findings:
        return 1
    if args.strict and (result.stale_baseline
                        or result.unjustified_baseline):
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
