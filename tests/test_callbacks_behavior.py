"""Callback behavioral surface (reference callback.py semantics:
reset_parameter schedules, early stopping with min_delta and
first_metric_only, log/record interplay)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary


def _data(n=1500, f=5, seed=0):
    X, y = make_synthetic_binary(n=n, f=f, seed=seed)
    d = lgb.Dataset(X[: n - 300], label=y[: n - 300])
    v = lgb.Dataset(X[n - 300:], label=y[n - 300:], reference=d)
    return X, y, d, v


def test_reset_parameter_learning_rate_schedule():
    X, y, d, v = _data()
    lrs = [0.3] * 3 + [0.05] * 5
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, d, num_boost_round=8,
                    callbacks=[lgb.reset_parameter(learning_rate=lrs)])
    # shrinkage changes are visible in the leaf magnitudes of the
    # serialized trees: early trees scale ~6x the late ones
    mags = [np.max(np.abs(t.leaf_value[: t.num_leaves]))
            for t in bst._models]
    assert np.mean(mags[:3]) > 2.5 * np.mean(mags[4:])

    # callable schedule variant
    bst2 = lgb.train({"objective": "binary", "verbosity": -1,
                      "num_leaves": 7}, lgb.Dataset(X[:1200], label=y[:1200]),
                     num_boost_round=6,
                     callbacks=[lgb.reset_parameter(
                         learning_rate=lambda i: 0.3 * (0.5 ** i))])
    mags2 = [np.max(np.abs(t.leaf_value[: t.num_leaves]))
             for t in bst2._models]
    assert mags2[0] > mags2[-1]

    # wrong-length list raises
    with pytest.raises(ValueError):
        lgb.train({"objective": "binary", "verbosity": -1},
                  lgb.Dataset(X[:500], label=y[:500]), num_boost_round=4,
                  callbacks=[lgb.reset_parameter(learning_rate=[0.1])])


def test_early_stopping_min_delta_stops_sooner():
    X, y, d, v = _data(seed=3)
    kw = dict(params={"objective": "binary", "verbosity": -1,
                      "num_leaves": 31, "metric": "binary_logloss",
                      "learning_rate": 0.02},
              train_set=d, num_boost_round=200, valid_sets=[v])
    plain = lgb.train(callbacks=[lgb.early_stopping(10, verbose=False)],
                      **kw)
    delta = lgb.train(callbacks=[lgb.early_stopping(
        10, verbose=False, min_delta=5e-3)], **kw)
    # requiring a 5e-3 improvement per round must stop no later -
    # and on this slow learning rate, strictly sooner
    assert delta.best_iteration <= plain.best_iteration
    assert delta.current_iteration() < 200


def test_early_stopping_first_metric_only():
    X, y, d, v = _data(seed=5)
    params = {"objective": "binary", "verbosity": -1, "num_leaves": 31,
              "metric": ["auc", "binary_logloss"],
              "first_metric_only": True, "learning_rate": 0.05}
    bst = lgb.train(params, d, num_boost_round=120, valid_sets=[v],
                    callbacks=[lgb.early_stopping(8, verbose=False,
                                                  first_metric_only=True)])
    assert bst.best_iteration > 0
    # the recorded best score is the first metric's (auc) entry
    assert "auc" in bst.best_score.get("valid_0", {})


def test_record_and_log_together_capture_stdv_free_entries():
    X, y, d, v = _data(seed=7)
    rec = {}
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "metric": "auc", "num_leaves": 7}, d,
                    num_boost_round=5, valid_sets=[v],
                    callbacks=[lgb.record_evaluation(rec),
                               lgb.log_evaluation(period=2,
                                                  show_stdv=False)])
    assert len(rec["valid_0"]["auc"]) == 5
    assert all(np.isfinite(rec["valid_0"]["auc"]))
