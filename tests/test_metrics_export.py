"""Fleet metrics plane (ISSUE 15; docs/OBSERVABILITY.md).

Layers under test:

1. OpenMetrics render/parse (obs/export.py): golden-parse of every
   rendered byte through the strict line grammar (no client library),
   name sanitization, label escaping, the kind mappings
   (counter ``_total``, gauge + ``_max``, histogram -> summary).
2. The /metrics HTTP endpoint: live scrape, content type, scrape
   counter, 404s — plus the subprocess proof that the whole export
   path is jax-free (supervisors serve it without pinning a backend).
3. XLA cost attribution (obs/cost.py): one ``{"event": "compile"}``
   record with flops+bytes per first compile per signature, none on
   cache hits, registry families fed; the jit_tracker
   rebuild-then-count regression (dead entries retire).
4. The serve daemon's ``{"cmd": "metrics"}`` protocol verb.
5. ``lightgbm_tpu stats <dir> [--fleet]``: per-file provenance and
   the merged fleet view, with the single-file path byte-compatible.
6. `slow`: a live 2-replica serve fleet under ``launch --health-port
   --metrics-port --scrape-interval`` plus an in-process trainer
   endpoint — scraped end-to-end, through a replica SIGKILL, with the
   supervisor's restarts label bumped (the ISSUE 15 acceptance run).
"""

from __future__ import annotations

import gc
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.export import (  # noqa: E402
    CONTENT_TYPE, MetricsHTTPServer, parse_openmetrics,
    render_openmetrics)
from lightgbm_tpu.obs.registry import MetricsRegistry  # noqa: E402

from tests._mp_utils import REPO_DIR, free_port, kill_group  # noqa: E402
from tests.conftest import make_synthetic_binary  # noqa: E402


# ---------------------------------------------------------------------
# 1. render / parse
# ---------------------------------------------------------------------

def _populated_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("iterations").inc(7)
    reg.counter("comm_bytes", mode="data", wire="int8").inc(4096)
    reg.gauge("hbm_bytes_in_use").set(1000)
    reg.gauge("hbm_bytes_in_use").set(800)       # max stays 1000
    reg.histogram("phase_seconds", phase="tree_learner/grow") \
        .observe(0.5)
    reg.histogram("phase_seconds", phase="tree_learner/grow") \
        .observe(0.7)
    return reg


def test_render_golden_parses_and_round_trips():
    text = render_openmetrics(_populated_registry().snapshot())
    assert text.endswith("# EOF\n")
    samples = parse_openmetrics(text)      # strict grammar: any bad
    # line raises, so a full parse IS the golden check
    assert samples["lightgbm_tpu_iterations_total"][()] == 7.0
    key = (("mode", "data"), ("wire", "int8"))
    assert samples["lightgbm_tpu_comm_bytes_total"][key] == 4096.0
    assert samples["lightgbm_tpu_hbm_bytes_in_use"][()] == 800.0
    assert samples["lightgbm_tpu_hbm_bytes_in_use_max"][()] == 1000.0
    pkey = (("phase", "tree_learner/grow"),)
    assert samples["lightgbm_tpu_phase_seconds_count"][pkey] == 2.0
    assert samples["lightgbm_tpu_phase_seconds_sum"][pkey] \
        == pytest.approx(1.2)
    assert samples["lightgbm_tpu_phase_seconds_min"][pkey] == 0.5
    assert samples["lightgbm_tpu_phase_seconds_max"][pkey] == 0.7


def test_render_sanitizes_names_and_escapes_labels():
    reg = MetricsRegistry()
    reg.counter("weird/name-with.dots", path='a"b\\c\nd').inc()
    text = render_openmetrics(reg.snapshot())
    samples = parse_openmetrics(text)
    name = "lightgbm_tpu_weird_name_with_dots_total"
    assert name in samples
    (labels, value), = samples[name].items()
    assert value == 1.0
    assert labels == (("path", 'a"b\\c\nd'),)   # escape round-trip


@pytest.mark.parametrize("value", [
    'a"b\\c\nd',
    "C:\\new_model",      # literal backslash followed by 'n': chained
    "\\n",                # str.replace unescaping corrupts these two
    "\\", "\n", 'tricky\\"quote', "\\\\n"])
def test_label_escape_round_trip_is_exact(value):
    reg = MetricsRegistry()
    reg.gauge("g", v=value).set(1.0)
    samples = parse_openmetrics(render_openmetrics(reg.snapshot()))
    (labels, _), = samples["lightgbm_tpu_g"].items()
    assert labels == (("v", value),)


def test_parser_rejects_malformed_text():
    with pytest.raises(ValueError):
        parse_openmetrics("lightgbm_tpu_x_total 1\n")   # missing EOF
    with pytest.raises(ValueError):
        parse_openmetrics("not a metric line\n# EOF\n")
    with pytest.raises(ValueError):
        parse_openmetrics('x{bad labels} 1\n# EOF\n')
    with pytest.raises(ValueError):
        parse_openmetrics("# HELP x about\n# EOF\n")  # HELP not in
    # the strict subset this exporter emits
    with pytest.raises(ValueError):
        parse_openmetrics("# EOF\nx 1\n")       # content after EOF


def test_none_valued_gauges_are_skipped():
    reg = MetricsRegistry()
    reg.gauge("maybe").set(None)
    samples = parse_openmetrics(render_openmetrics(reg.snapshot()))
    assert "lightgbm_tpu_maybe" not in samples


# ---------------------------------------------------------------------
# 2. the /metrics endpoint
# ---------------------------------------------------------------------

def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.headers["Content-Type"], \
            resp.read().decode("utf-8")


def test_http_endpoint_serves_and_counts_scrapes():
    reg = _populated_registry()
    extra_calls = []

    def extra():
        extra_calls.append(1)
        return {"custom_gauge": {
            "kind": "gauge",
            "series": [{"labels": {"k": "v"}, "value": 3.5}]}}

    srv = MetricsHTTPServer(0, registry=reg, extra_families=extra)
    try:
        ctype, body = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert ctype == CONTENT_TYPE
        samples = parse_openmetrics(body)
        assert samples["lightgbm_tpu_iterations_total"][()] == 7.0
        assert samples["lightgbm_tpu_custom_gauge"][(("k", "v"),)] \
            == 3.5
        assert samples["lightgbm_tpu_metrics_scrapes_total"][()] == 1.0
        assert extra_calls
        _, body2 = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert parse_openmetrics(body2)[
            "lightgbm_tpu_metrics_scrapes_total"][()] == 2.0
        assert srv.scrape_count() == 2
        with pytest.raises(urllib.error.HTTPError):
            _get(f"http://127.0.0.1:{srv.port}/other")
    finally:
        srv.close()


def test_metrics_endpoint_is_jax_free():
    """The whole export path — registry, render, HTTP endpoint, strict
    parser — must work where no backend can initialize: the launch and
    pipeline supervisors serve /metrics without ever importing jax
    (the ISSUE 15 jax-free battery case)."""
    code = (
        "import sys, urllib.request\n"
        "from lightgbm_tpu.obs.registry import registry\n"
        "from lightgbm_tpu.obs.export import (MetricsHTTPServer,\n"
        "    parse_openmetrics, CONTENT_TYPE)\n"
        "registry.counter('iterations').inc(3)\n"
        "registry.gauge('fleet_replica_qps', rank=0).set(12.5)\n"
        "srv = MetricsHTTPServer(0)\n"
        "url = f'http://127.0.0.1:{srv.port}/metrics'\n"
        "with urllib.request.urlopen(url, timeout=10) as r:\n"
        "    assert r.headers['Content-Type'] == CONTENT_TYPE\n"
        "    body = r.read().decode('utf-8')\n"
        "s = parse_openmetrics(body)\n"
        "assert s['lightgbm_tpu_iterations_total'][()] == 3.0\n"
        "assert s['lightgbm_tpu_fleet_replica_qps']"
        "[(('rank', '0'),)] == 12.5\n"
        "srv.close()\n"
        "assert 'jax' not in sys.modules, "
        "'the metrics endpoint imported jax!'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")


# ---------------------------------------------------------------------
# 3. XLA cost attribution + jit_tracker retirement
# ---------------------------------------------------------------------

def test_cost_tracked_emits_one_compile_event_per_signature():
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.obs import register_jit
    from lightgbm_tpu.obs.cost import CostTracked, drain_compile_events
    from lightgbm_tpu.obs.registry import registry

    drain_compile_events()
    name = "test/cost_entry"
    fn = register_jit(name, jax.jit(lambda x: (x * 2.0).sum()))
    assert isinstance(fn, CostTracked)
    # re-registering the same wrapper (or its wrapped fn) is a no-op
    assert register_jit(name, fn) is fn

    fn(jnp.ones((8,), jnp.float32))
    events = [e for e in drain_compile_events() if e["entry"] == name]
    assert len(events) == 1
    ev = events[0]
    assert ev["event"] == "compile"
    assert ev["flops"] is not None and ev["flops"] > 0
    assert ev["bytes_accessed"] is not None \
        and ev["bytes_accessed"] > 0
    assert ev["wall_ms"] > 0
    assert "float32[8]" in ev["signature"]

    # same signature again: a cache hit, no event
    fn(jnp.ones((8,), jnp.float32))
    assert not [e for e in drain_compile_events()
                if e["entry"] == name]

    # a new signature compiles again: one more event
    fn(jnp.ones((16,), jnp.float32))
    events = [e for e in drain_compile_events() if e["entry"] == name]
    assert len(events) == 1
    assert "float32[16]" in events[0]["signature"]

    # the registry families carried both compiles
    assert registry.counter("xla_compiles", entry=name) \
        .snapshot() == 2.0
    assert registry.gauge("xla_flops", entry=name) \
        .snapshot()["value"] > 0


def test_cost_wrapper_proxies_the_jit_surface():
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.obs import register_jit

    fn = register_jit("test/proxy_entry", jax.jit(lambda x: x + 1))
    fn(jnp.ones((4,)))
    assert int(fn._cache_size()) == 1         # proxied attr
    lowered = fn.lower(jnp.ones((4,)))        # proxied AOT surface
    assert lowered.cost_analysis() is not None


def test_jit_rebuild_retires_dead_entries():
    """The stale-entry regression (ISSUE 15 satellite): rebuilding an
    entry point under the same name must not leave the collected
    function's last cache size in jit_cache_sizes()/total_recompiles()
    forever."""
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.obs import (jit_cache_sizes, register_jit,
                                  total_recompiles)

    name = "test/rebuild_entry"
    fn = register_jit(name, jax.jit(lambda x: x + 1.0))
    fn(jnp.ones((4,)))
    sizes = jit_cache_sizes()
    keys = [k for k in sizes if k[0] == name]
    assert len(keys) == 1 and sizes[keys[0]] == 1
    before = total_recompiles()

    # the OOM-ladder / _scan_fns reset shape: drop the old function,
    # rebuild, re-register under the same name
    fn = None
    gc.collect()
    fn = register_jit(name, jax.jit(lambda x: x + 2.0))
    fn(jnp.ones((4,)))
    sizes = jit_cache_sizes()
    keys = [k for k in sizes if k[0] == name]
    assert len(keys) == 1, (
        f"dead entry not retired: {sorted(sizes)}")
    assert sizes[keys[0]] == 1
    # the dead function's cache no longer inflates the total
    assert total_recompiles() <= before
    fn = None
    gc.collect()


def test_compile_events_ride_the_telemetry_stream(tmp_path):
    """End-to-end through the recorder: a training run's JSONL stream
    carries {"event": "compile"} records with flops+bytes, and the
    stats table renders the xla cost section."""
    from lightgbm_tpu.obs import render_stats_table, summarize_events

    X, y = make_synthetic_binary(n=400, f=6, seed=9)
    path = str(tmp_path / "run.jsonl")
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    lgb.train({"objective": "binary", "num_leaves": 7,
               "verbosity": -1}, ds, num_boost_round=3,
              callbacks=[lgb.callback.telemetry(path)])
    with open(path, encoding="utf-8") as fh:
        events = [json.loads(line) for line in fh if line.strip()]
    compiles = [e for e in events if e.get("event") == "compile"]
    assert compiles, "no compile events in the stream"
    fused = [e for e in compiles if e["entry"] == "gbdt/fused_iter"]
    assert fused and fused[0]["flops"] is not None \
        and fused[0]["bytes_accessed"] is not None
    summary = summarize_events(path)
    assert "gbdt/fused_iter" in summary["compiles"]
    table = render_stats_table(summary)
    assert "xla cost attribution" in table
    assert "gbdt/fused_iter" in table


# ---------------------------------------------------------------------
# 4. the serve daemon's metrics verb
# ---------------------------------------------------------------------

class _FakeBatcher:
    def stats(self):
        return {"queue_depth_rows": 2, "requests_total": 5,
                "rows_total": 40, "batches_total": 3,
                "swaps_total": 0, "rejected_total": 0,
                "shed_total": 1, "shed_rows": 4,
                "p50_ms": 1.25, "p99_ms": 9.5}

    def close(self, timeout=None):
        pass


def test_serve_metrics_verb_returns_openmetrics_text():
    from lightgbm_tpu.serve.daemon import ServeState, handle_request

    state = ServeState(_FakeBatcher(), "abcd1234", "model.txt",
                       registry=MetricsRegistry())
    try:
        state.stats()                  # primes the cached rate window
        reply = handle_request({"cmd": "metrics"}, state)
        assert reply.get("ok"), reply
        assert reply["content_type"] == CONTENT_TYPE
        samples = parse_openmetrics(reply["metrics"])
        assert samples["lightgbm_tpu_serve_requests_total"][()] == 5.0
        assert samples["lightgbm_tpu_serve_shed_total"][()] == 1.0
        assert samples["lightgbm_tpu_serve_p99_ms"][()] == 9.5
        assert samples["lightgbm_tpu_serve_qps"][()] is not None
        mkey = (("model", "abcd1234"),)
        assert samples["lightgbm_tpu_serve_model_info"][mkey] == 1.0
    finally:
        state.close()


# ---------------------------------------------------------------------
# 5. stats over a directory + the merged fleet view
# ---------------------------------------------------------------------

def _write_jsonl(path, events):
    with open(path, "w", encoding="utf-8") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")


def _fake_iteration(i):
    return {"event": "iteration", "iteration": i, "wall_time": i + 1.0,
            "phases": {"tree_learner/grow": {"total": 0.1,
                                             "count": 1}},
            "recompiles": {"delta": 1 if i == 0 else 0, "total": 1},
            "hbm": {}, "tree": {"trees": 1, "leaves": 7,
                                "split_gain_sum": 2.0},
            "eval": {}, "comm": None, "scan": None}


def _fake_serve(requests):
    return {"event": "serve", "requests_total": requests,
            "rows_total": requests * 4, "batches_total": 3,
            "queue_depth_rows": 0, "qps": 11.0, "rows_per_sec": 44.0,
            "p50_ms": 1.0, "p99_ms": 8.0, "swaps_total": 1,
            "swap_failures": 0, "rejected_total": 0, "shed_total": 2,
            "recompiles": {"delta": 0, "total": 4},
            "hbm": {}, "model": "m1", "model_source": "x.txt",
            "uptime_s": 9.0}


def test_stats_directory_provenance_and_fleet_view(tmp_path, capsys):
    from lightgbm_tpu.cli import _task_stats

    train = [_fake_iteration(i) for i in range(3)]
    train.insert(0, {"event": "compile", "entry": "gbdt/fused_iter",
                     "flops": 1e9, "bytes_accessed": 2e9,
                     "wall_ms": 120.0, "compiles": 1,
                     "optimal_ms": 3.0, "device_kind": "fake-tpu",
                     "time": 1.0})
    _write_jsonl(tmp_path / "train.jsonl", train)
    _write_jsonl(tmp_path / "serve.jsonl", [_fake_serve(10)])
    _write_jsonl(tmp_path / "serve.jsonl.rank1", [_fake_serve(6)])
    _write_jsonl(tmp_path / "serve.jsonl.fleet", [
        {"event": "fleet", "shape": "replicas",
         "replicas": [{"rank": 0, "alive": True, "restarts": 0},
                      {"rank": 1, "alive": True, "restarts": 2}],
         "restarts_total": 2, "time": 2.0}])

    # per-file provenance
    rc = _task_stats([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for rel in ("train.jsonl", "serve.jsonl", "serve.jsonl.rank1",
                "serve.jsonl.fleet"):
        assert f"== {rel} ==" in out, out
    assert "xla cost attribution (fake-tpu)" in out

    # merged fleet view sums the replicas and keeps the restarts
    rc = _task_stats([str(tmp_path), "--fleet"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet (merged view)" in out
    assert "2 replica(s), 16 req" in out
    assert "restarts 2" in out

    # the single-file path is unchanged by the directory feature
    rc = _task_stats([str(tmp_path / "train.jsonl")])
    single = capsys.readouterr().out
    assert rc == 0
    assert "== " not in single
    assert "iterations           : 3" in single


def test_stats_directory_without_events_fails(tmp_path, capsys):
    from lightgbm_tpu.cli import _task_stats
    _write_jsonl(tmp_path / "empty.jsonl", [])
    assert _task_stats([str(tmp_path)]) == 1


# ---------------------------------------------------------------------
# 6. live fleet scrape (slow: real sockets, subprocess fleet)
# ---------------------------------------------------------------------

def _rpc_once(port, obj, timeout=10.0):
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((json.dumps(obj) + "\n").encode("utf-8"))
        return json.loads(s.makefile("r").readline())


def _wait_ping(port, deadline):
    while time.time() < deadline:
        try:
            if _rpc_once(port, {"cmd": "ping"}).get("ok"):
                return True
        except (OSError, ValueError):
            pass
        time.sleep(0.3)
    return False


def _scrape(port):
    _, body = _get(f"http://127.0.0.1:{port}/metrics")
    return parse_openmetrics(body)


@pytest.mark.slow
def test_live_fleet_scrape_and_restart_accounting(tmp_path):
    """The ISSUE 15 acceptance run: an in-process trainer endpoint
    plus a 2-replica serve fleet under `launch --health-port
    --metrics-port --scrape-interval`, scraped live end-to-end —
    OpenMetrics-parseable text carrying serve QPS/p99/shed, compile
    totals and publish counters — then a replica SIGKILL, after which
    the replica serves again and the supervisor's fleet records carry
    the bumped restarts label."""
    # ---- trainer side (in-process): train, publish, scrape ----------
    from lightgbm_tpu.obs.export import ensure_metrics_server
    from lightgbm_tpu.resilience.publisher import publish_model

    X, y = make_synthetic_binary(n=500, f=8, seed=21)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=4,
                    callbacks=[lgb.callback.telemetry(
                        str(tmp_path / "telemetry" / "train.jsonl"))])
    publish_dir = str(tmp_path / "publish")
    os.makedirs(publish_dir, exist_ok=True)
    publish_model(bst, publish_dir, "model_g0000.txt",
                  metadata={"generation": 0})
    trainer_srv = ensure_metrics_server(0)
    assert trainer_srv is not None
    samples = _scrape(trainer_srv.port)
    assert samples["lightgbm_tpu_iterations_total"][()] >= 4.0
    assert "lightgbm_tpu_jit_recompiles_total" in samples
    assert any(name.startswith("lightgbm_tpu_xla_compiles_total")
               for name in samples), sorted(samples)[:20]
    assert samples["lightgbm_tpu_publish_total"][()] >= 1.0

    # ---- serve fleet (subprocess): 2 replicas + supervisor ----------
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    base = free_port()
    metrics_base = free_port()
    env = dict(os.environ)
    env["LIGHTGBM_TPU_TELEMETRY"] = str(
        tmp_path / "telemetry" / "serve.jsonl")
    sup = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "launch", "2",
         "--max-restarts", "3", "--grace", "1",
         "--health-port", str(base),
         "--health-interval", "1", "--health-grace", "300",
         "--metrics-port", str(metrics_base),
         "--scrape-interval", "0.5",
         "--log-dir", str(tmp_path / "logs"), "--",
         sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", str(base), "--warmup-rows", "64",
         "--max-batch-rows", "256", "--stats-interval", "0.5"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=REPO_DIR, env=env, start_new_session=True)
    try:
        deadline = time.time() + 180
        assert _wait_ping(base, deadline), "replica 0 never served"
        assert _wait_ping(base + 1, deadline), "replica 1 never served"
        pids = {r: _rpc_once(base + r, {"cmd": "ping"})["pid"]
                for r in (0, 1)}
        for r in (0, 1):                       # traffic for the rates
            for _ in range(3):
                reply = _rpc_once(base + r,
                                  {"rows": X[:4].tolist()})
                assert "predictions" in reply, reply
        time.sleep(1.5)                        # one stats cadence

        # replica endpoints: launch exported metrics_base+1, the
        # daemon added its rank
        for r in (0, 1):
            samples = _scrape(metrics_base + 1 + r)
            assert samples["lightgbm_tpu_serve_requests_total"][()] \
                >= 3.0
            assert "lightgbm_tpu_serve_shed_total" in samples
            assert "lightgbm_tpu_serve_p99_ms" in samples
            assert "lightgbm_tpu_serve_qps" in samples
            assert any(n.startswith("lightgbm_tpu_xla_compiles")
                       for n in samples)
        # the protocol verb serves the same text
        reply = _rpc_once(base, {"cmd": "metrics"})
        assert reply.get("ok"), reply
        assert parse_openmetrics(reply["metrics"])[
            "lightgbm_tpu_serve_requests_total"][()] >= 3.0

        # supervisor endpoint: per-replica fleet gauges
        samples = _scrape(metrics_base)
        up = samples.get("lightgbm_tpu_fleet_replica_up", {})
        assert up.get((("rank", "0"),)) == 1.0, samples.keys()
        assert up.get((("rank", "1"),)) == 1.0

        # ---- chaos: SIGKILL replica 1; fleet mode restarts it -------
        os.kill(pids[1], signal.SIGKILL)
        deadline = time.time() + 180
        new_pid = None
        while time.time() < deadline:
            try:
                got = _rpc_once(base + 1, {"cmd": "ping"})
                if got.get("pid") not in (None, pids[1]):
                    new_pid = got["pid"]
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.5)
        assert new_pid is not None, "replica 1 never came back"
        # its endpoint answers again (fresh process, fresh counters)
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                samples = _scrape(metrics_base + 2)
                break
            except OSError:
                time.sleep(0.5)
        # the supervisor's restarts label carries the history the
        # replica's own counters lost with the process
        deadline = time.time() + 60
        restarts = 0.0
        while time.time() < deadline and restarts < 1.0:
            samples = _scrape(metrics_base)
            restarts = samples.get(
                "lightgbm_tpu_fleet_replica_restarts", {}).get(
                (("rank", "1"),), 0.0)
            time.sleep(0.5)
        assert restarts >= 1.0, "restart never surfaced in /metrics"

        # graceful shutdown so the fleet file flushes
        for r in (0, 1):
            try:
                _rpc_once(base + r, {"cmd": "shutdown"})
            except (OSError, ValueError):
                pass
        sup.wait(timeout=60)
    finally:
        if sup.poll() is None:
            kill_group(sup)
            try:
                sup.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass

    # ---- the fleet telemetry + merged stats view --------------------
    fleet_file = str(tmp_path / "telemetry" / "serve.jsonl.fleet")
    assert os.path.exists(fleet_file), os.listdir(
        str(tmp_path / "telemetry"))
    with open(fleet_file, encoding="utf-8") as fh:
        fleet_events = [json.loads(line) for line in fh
                        if line.strip()]
    assert fleet_events
    assert fleet_events[-1]["event"] == "fleet"
    assert fleet_events[-1]["restarts_total"] >= 1
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "stats",
         str(tmp_path / "telemetry"), "--fleet"],
        capture_output=True, text=True, cwd=REPO_DIR, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "fleet (merged view)" in proc.stdout
    assert "restarts" in proc.stdout
