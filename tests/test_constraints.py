"""Interaction constraints + forced splits
(col_sampler.hpp GetByNode; serial_tree_learner.cpp ForceSplits)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary


def _tree_features_used(bst):
    """Set of (real) split features per tree."""
    out = []
    for t in bst._models:
        out.append(set(int(f) for f in t.split_feature[: t.num_nodes]))
    return out


def test_interaction_constraints_respected():
    X, y = make_synthetic_binary(n=2500, f=6, seed=13)
    groups = [[0, 1], [2, 3], [4, 5]]
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 12,
                     "min_data_in_leaf": 10, "verbosity": -1,
                     "interaction_constraints": groups}, d,
                    num_boost_round=8)
    # every root->leaf path must stay inside one group; verify per node
    # path by walking each tree
    for t in bst._models:
        nn = t.num_nodes
        if nn == 0:
            continue
        parent = np.full(nn, -1)
        for i in range(nn):
            for c in (t.left_child[i], t.right_child[i]):
                if c >= 0:
                    parent[c] = i
        for i in range(nn):
            path = set()
            node = i
            while node >= 0:
                path.add(int(t.split_feature[node]))
                node = parent[node]
            assert any(path <= set(g) for g in groups), \
                f"path {path} violates constraints"


def test_forced_splits_applied(tmp_path):
    X, y = make_synthetic_binary(n=2000, f=5, seed=21)
    fs = {"feature": 2, "threshold": 0.0,
          "left": {"feature": 0, "threshold": 0.5}}
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(fs))
    d = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 8,
                     "min_data_in_leaf": 5, "verbosity": -1,
                     "forcedsplits_filename": str(path)}, d,
                    num_boost_round=3)
    for t in bst._models:
        # split 0 is the root: forced feature 2 near threshold 0.0;
        # split 1 is the root's left child: feature 0
        assert int(t.split_feature[0]) == 2
        assert abs(float(t.threshold[0]) - 0.0) < 0.2
        assert int(t.split_feature[1]) == 0
    p = bst.predict(X)
    assert np.all(np.isfinite(p))


def test_cegb_split_penalty_shrinks_trees():
    """Calibrated against a reference oracle build (v4.6.0.99, this exact
    dataset): total leaves over 3 rounds are 93 at penalty<=0.03, 63 at
    0.1, and 1 at >=0.3 — DeltaGain = tradeoff*penalty_split*count
    (cost_effective_gradient_boosting.hpp:81-97) only bites once
    penalty*count crosses the gain scale, so sub-threshold penalties are
    legitimately no-ops and large ones stop the root."""
    X, y = make_synthetic_binary(n=2000, f=6, seed=31)
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}

    def leaves(extra):
        b = lgb.train(dict(base, **extra), lgb.Dataset(X, label=y),
                      num_boost_round=3)
        return sum(t.num_leaves for t in b._models)

    l_none = leaves({})
    l_mid = leaves({"cegb_penalty_split": 0.1})
    l_big = leaves({"cegb_penalty_split": 0.3})
    assert l_none == 93  # oracle: 93
    assert l_mid == 63   # oracle: 63
    assert l_big == 1    # oracle: 1 (root refuses to split)
    assert l_big < l_mid < l_none


def test_cegb_coupled_penalty_concentrates_features():
    X, y = make_synthetic_binary(n=2500, f=8, seed=33)
    base = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(dict(base), lgb.Dataset(X, label=y), num_boost_round=6)
    pen = [5.0] * 8
    b1 = lgb.train(dict(base, cegb_penalty_feature_coupled=pen),
                   lgb.Dataset(X, label=y), num_boost_round=6)
    used0 = set()
    used1 = set()
    for t in b0._models:
        used0 |= set(int(f) for f in t.split_feature[: t.num_nodes])
    for t in b1._models:
        used1 |= set(int(f) for f in t.split_feature[: t.num_nodes])
    assert len(used1) <= len(used0)


def test_cegb_lazy_penalty_trains():
    X, y = make_synthetic_binary(n=1500, f=5, seed=35)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5,
                     "cegb_penalty_feature_lazy": [0.001] * 5},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    p = bst.predict(X)
    assert np.all(np.isfinite(p)) and len(bst._models) == 4
