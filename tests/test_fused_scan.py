"""Multi-iteration fused scan (gbdt.py _dispatch_scan_window /
_get_scan_fn; docs/FUSED.md).

A whole window of boosting iterations runs as ONE lax.scan program with
donated score/bagging carries; trees come back as one batched pack per
window and the driver pops them per iteration, so callbacks, telemetry
and the one-late guard drain keep their exact per-iteration semantics.

Contract under test: for every scan-eligible config the scan-trained
model is BYTE-IDENTICAL to the per-iteration fused path (and the fused
path to eager, modulo the documented float tolerance), windows
partition the iteration stream without changing it (tails, natural
early stop, checkpoint cadence, SIGKILL resume), and fault injection
fires at the correct ABSOLUTE iteration inside a window.
"""
import glob
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback as cbm
from lightgbm_tpu.models.gbdt import GBDTBooster, resolve_scan_iters

_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture
def data():
    # same shape/seed as tests/test_fused_iter.py: the fused-vs-eager
    # float contract (rtol 1e-5) is calibrated on this distribution
    rs = np.random.RandomState(7)
    X = rs.randn(3000, 10)
    y = ((X[:, :4] @ rs.randn(4) + 0.3 * rs.randn(3000)) > 0).astype(float)
    return X, y


def _train(params, X, y, n=10, mode="scan", callbacks=None, W=4,
           resume_from=None):
    """mode: 'scan' (windows of W), 'fused' (per-iteration fused),
    'eager' (fused gate forced off)."""
    p = dict(params, verbosity=-1)
    if mode == "scan":
        p["fused_scan_iters"] = W
    orig = None
    if mode == "eager":
        orig = GBDTBooster._fused_ok
        GBDTBooster._fused_ok = lambda self: False
    try:
        return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=n,
                         callbacks=callbacks, resume_from=resume_from)
    finally:
        if orig is not None:
            GBDTBooster._fused_ok = orig


def _model_bytes(bst, ignore=()) -> str:
    """model_to_string minus the fused_scan_iters params echo — the
    only legal difference between a scan- and a fused-trained model —
    plus any extra ``ignore`` params-echo prefixes a test legitimately
    varies (e.g. num_iterations on resume-to-total runs)."""
    skip = ("[fused_scan_iters",) + tuple(ignore)
    return "\n".join(ln for ln in bst.model_to_string().split("\n")
                     if not ln.startswith(skip))


def _assert_byte_identical(a, b):
    assert _model_bytes(a) == _model_bytes(b)


# ---------------------------------------------------------------------
# byte-identity battery: growers x hist_comm wires, plus the sampling /
# quantization / multiclass arms the fused path carries
# ---------------------------------------------------------------------

# every grower's loop-carry plumbing (incl. the comm_ef error-feedback
# slots, inert on one device but ALLOCATED and threaded per tree for
# the int wires) must survive being traced inside the scan body. The
# full grower x wire cross product compiles ~9 scan programs; tier-1
# keeps one arm per grower plus one int wire per grower-class and the
# redundant combinations ride the slow tier (each wire arm differs
# only in the inert EF slot dtype threading on one device).
_T1 = {"compact-f32", "compact-int8", "masked-int16", "level-f32"}
GROWER_ARMS = [
    pytest.param(
        f"{grower}-{wire}",
        dict({"objective": "binary", "num_leaves": 15,
              "hist_comm": wire},
             **({"grower": grower, "max_depth": 4} if grower == "level"
                else {"grower": grower})),
        id=f"{grower}-{wire}",
        marks=([] if f"{grower}-{wire}" in _T1
               else [pytest.mark.slow]))
    for grower in ("compact", "masked", "level")
    for wire in ("f32", "int16", "int8")
]

EXTRA_ARMS = [
    pytest.param(name, params, id=name)
    for name, params in [
        ("bagging", {"objective": "binary", "num_leaves": 15,
                     "bagging_fraction": 0.7, "bagging_freq": 2,
                     "bagging_seed": 5}),
        ("pos_neg_bagging", {"objective": "binary", "num_leaves": 15,
                             "pos_bagging_fraction": 0.8,
                             "neg_bagging_fraction": 0.6,
                             "bagging_freq": 1}),
        ("quantized", {"objective": "binary", "num_leaves": 15,
                       "use_quantized_grad": True}),
        ("bynode", {"objective": "binary", "num_leaves": 15,
                    "feature_fraction_bynode": 0.8}),
        ("regression_monotone", {"objective": "regression",
                                 "num_leaves": 15,
                                 "monotone_constraints":
                                     [1, -1] + [0] * 8}),
    ]
]


@pytest.mark.parametrize("name,params", GROWER_ARMS + EXTRA_ARMS)
def test_scan_matches_fused_and_eager(name, params, data):
    X, y = data
    yy = X[:, 0] * 2 + X[:, 1] \
        if params["objective"] == "regression" else y
    # n=10 with W=4 also exercises the window tail (10 = 4 + 4 + 2)
    a = _train(params, X, yy, mode="scan")
    b = _train(params, X, yy, mode="fused")
    assert a._engine._scan_fns, "scan path did not engage"
    assert not b._engine._scan_fns
    _assert_byte_identical(a, b)
    # fused vs eager keeps the established float contract. The wire
    # mode is inert on one device (comms.make_hist_psum_ef pins f32
    # without an axis), so the eager leg runs once per grower config —
    # the int arms prove the scan composes with the EF carry plumbing,
    # not a different eager numeric path.
    if params.get("hist_comm", "f32") != "f32":
        return
    c = _train(params, X, yy, mode="eager")
    for ta, tc in zip(a._models, c._models):
        assert ta.num_leaves == tc.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn],
                              tc.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value, tc.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_scan_multiclass_matches_fused(data):
    X, y = data
    y3 = (y + (X[:, 5] > 0)).astype(float)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7}
    a = _train(params, X, y3, mode="scan", W=3, n=9)
    b = _train(params, X, y3, mode="fused", n=9)
    assert a._engine._scan_fns
    _assert_byte_identical(a, b)


def test_scan_window_larger_than_run(data):
    """W > num_boost_round: one window, clamped to end-of-training."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15}
    a = _train(params, X, y, mode="scan", W=64, n=6)
    b = _train(params, X, y, mode="fused", n=6)
    assert a._engine._scan_fns
    assert (64, False) not in a._engine._scan_fns, \
        "window was not clamped to the 6 remaining iterations"
    _assert_byte_identical(a, b)


# ---------------------------------------------------------------------
# eligibility / fallback
# ---------------------------------------------------------------------

def test_feature_fraction_falls_back_to_per_iteration(data):
    """feature_fraction < 1 consumes a HOST RandomState draw per tree —
    the scan cannot carry that stream; the per-iteration fused path
    must engage instead and keep matching eager."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15,
              "feature_fraction": 0.7}
    a = _train(params, X, y, mode="scan")
    b = _train(params, X, y, mode="fused")
    assert not a._engine._scan_fns, \
        "scan must not engage with host-RNG column sampling"
    assert a._engine._fused_fn is not None
    _assert_byte_identical(a, b)


def test_unknown_callback_pins_lookahead(data):
    """An arbitrary user callback may read booster state every
    iteration; the engine must pin the lookahead to 1 so the scan
    never runs ahead of it."""
    X, y = data
    seen = []
    a = _train({"objective": "binary", "num_leaves": 15}, X, y,
               mode="scan",
               callbacks=[lambda env: seen.append(env.iteration)])
    assert len(seen) == 10
    assert not a._engine._scan_fns, \
        "scan engaged under an unknown per-iteration callback"


def test_train_set_in_valid_sets_bounds_windows_to_metric_freq(data):
    """valid_sets=[train_set] keeps engine.valid_sets empty (scan stays
    eligible) but the engine loop then evaluates the TRAIN score inline
    every metric_freq iterations — a window running past an eval point
    would report future (uncommitted-lookahead) metrics. metric_freq=1
    (default) must disable windows outright; an aligned metric_freq
    must keep the reported metrics identical to the per-iteration
    path."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}

    def run(scan, metric_freq):
        rec = {}
        ds = lgb.Dataset(X, label=y)
        p = dict(params, metric_freq=metric_freq)
        if scan:
            p["fused_scan_iters"] = 4
        bst = lgb.train(p, ds, num_boost_round=8, valid_sets=[ds],
                        callbacks=[cbm.record_evaluation(rec)])
        return bst, rec

    a, rec_a = run(scan=True, metric_freq=1)
    assert not a._engine._scan_fns, \
        "per-iteration train-set eval must pin the lookahead to 1"
    b, rec_b = run(scan=True, metric_freq=4)
    assert b._engine._scan_fns, \
        "an aligned metric_freq must keep windows enabled"
    c, rec_c = run(scan=False, metric_freq=4)
    assert rec_b == rec_c, \
        "train-set metrics at eval points diverged from the " \
        "per-iteration path (a window ran past an eval)"
    _assert_byte_identical(b, c)


def test_oom_retry_bag_rederivation_invariant(data):
    """The dispatch-retry path re-derives a consumed (donated) bagging
    carry by re-drawing at the iteration the entry bag was KEYED at
    (the last refresh for a cache-served bag). Pin the invariant that
    re-derivation relies on: a fresh draw at (it // freq) * freq
    reproduces the sequentially-maintained cache byte-for-byte."""
    X, y = data
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "bagging_fraction": 0.7,
                              "bagging_freq": 3, "bagging_seed": 5,
                              "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    eng = bst._engine
    for _ in range(5):
        bst.update()   # per-iteration path; cache last refreshed at 3
    cached = np.asarray(eng._cached_bag)
    eng._cached_bag = None
    rederived = np.asarray(eng._row_weights((5 // 3) * 3, None, None))
    np.testing.assert_array_equal(cached, rederived)


@pytest.mark.parametrize("rollback_at", [3, 5],
                         ids=["on-cadence", "off-cadence"])
def test_rollback_mid_window_keeps_bagging_stream(data, rollback_at):
    """rollback_one_iter with lookahead still queued aborts the window
    (score rebuilt from trees) AND re-derives the bagging cache at the
    last refresh BEFORE the post-rollback iteration — continuing must
    reuse the same in-bag draw the per-iteration path would, not fork
    the stream with an off-cadence fresh draw. Both cadence phases of
    the rollback point matter: iter_ ON the bagging cadence (3, where
    a pre-decrement re-derivation would wrongly be skipped) and off
    it (5)."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "bagging_fraction": 0.7, "bagging_freq": 3,
              "bagging_seed": 5}

    def run(scan):
        p = dict(params)
        if scan:
            p["fused_scan_iters"] = 6
        bst = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y))

        def step():
            # emulate the engine loop's lookahead: never past the
            # 8-iteration end of this manual run
            if scan:
                bst._engine._scan_horizon = 8 - bst._engine.iter_
            bst.update()

        for _ in range(rollback_at):
            step()
        bst.rollback_one_iter()
        while bst._engine.iter_ < 8:
            step()
        return bst

    a = run(scan=True)   # window [0..5]; rollback lands mid-window
    b = run(scan=False)
    assert a._engine._scan_fns
    # the bagging caches of both paths must end keyed at the same
    # refresh draw — an off-cadence re-derivation after the abort
    # would fork the stream here
    np.testing.assert_array_equal(np.asarray(a._engine._cached_bag),
                                  np.asarray(b._engine._cached_bag))
    # score after the abort is rebuilt from trees (documented last-ulp
    # forfeit), so compare structure exactly and leaves to tolerance
    assert len(a._models) == len(b._models) == 8
    for ta, tb in zip(a._models, b._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn],
                              tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_reset_parameter_invalidates_scan_programs(data):
    """The scan body BAKES the bagging fractions into its traced
    closure (unlike the per-iteration fused fn, whose row weights are
    operands) — reset_parameter must drop the cached window programs
    so the next dispatch re-traces with the new cfg instead of
    silently sampling at the old fraction."""
    X, y = data

    def run(scan):
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "bagging_fraction": 0.8, "bagging_freq": 1,
             "bagging_seed": 5}
        if scan:
            p["fused_scan_iters"] = 4
        bst = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y))

        def step():
            if scan:
                bst._engine._scan_horizon = 8 - bst._engine.iter_
            bst.update()

        for _ in range(4):
            step()
        bst.reset_parameter({"bagging_fraction": 0.5})
        for _ in range(4):
            step()
        return bst

    a = run(scan=True)
    b = run(scan=False)
    assert a._engine._scan_fns, "post-reset window did not re-trace"
    _assert_byte_identical(a, b)


def test_fused_ok_flip_mid_pend_aborts_lookahead(data):
    """add_valid between direct update() calls flips _fused_ok while
    lookahead is still queued: the eager path must train from the
    committed score, not the window-ahead carry, and the stale packs
    must never be popped on top of eager trees."""
    X, y = data
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "fused_scan_iters": 6}
    ds = lgb.Dataset(X, label=y)
    bst = lgb.Booster(params=p, train_set=ds)
    eng = bst._engine
    eng._scan_horizon = 8
    for _ in range(3):
        bst.update()          # window [0..5] dispatched, 3 pops
    assert eng._scan_pend is not None
    ds.construct()
    bst.add_valid(lgb.Dataset(X[:500], label=y[:500], reference=ds),
                  "v")
    for _ in range(5):
        bst.update()          # eager path (valid set) from it 3
    assert eng._scan_pend is None, "stale packs survived the flip"
    assert bst.current_iteration() == 8
    assert len(bst._models) == 8

    # per-iteration reference: same add_valid at the same iteration
    bst2 = lgb.Booster(params={k: v for k, v in p.items()
                               if k != "fused_scan_iters"},
                       train_set=lgb.Dataset(X, label=y))
    for _ in range(3):
        bst2.update()
    ds2 = bst2._engine.train_set
    bst2.add_valid(lgb.Dataset(X[:500], label=y[:500],
                               reference=ds2), "v")
    for _ in range(5):
        bst2.update()
    for ta, tb in zip(bst._models, bst2._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn],
                              tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_learning_rate_reset_mid_window_takes_effect_next_iter(data):
    """reset_parameter({'learning_rate': ...}) mid-window discards the
    lookahead still scored at the old rate — the new rate applies from
    the very next iteration, like the per-iteration path."""
    X, y = data

    def run(scan):
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
        if scan:
            p["fused_scan_iters"] = 6
        bst = lgb.Booster(params=p, train_set=lgb.Dataset(X, label=y))

        def step():
            if scan:
                bst._engine._scan_horizon = 8 - bst._engine.iter_
            bst.update()

        for _ in range(3):
            step()            # scan: mid-window of [0..5]
        bst.reset_parameter({"learning_rate": 0.05})
        for _ in range(5):
            step()
        return bst

    a = run(scan=True)
    b = run(scan=False)
    # the abort's score rebuild forfeits the last ulp; structure must
    # match exactly, leaves to the established tolerance
    assert len(a._models) == len(b._models) == 8
    for ta, tb in zip(a._models, b._models):
        assert ta.num_leaves == tb.num_leaves
        nn = ta.num_nodes
        assert np.array_equal(ta.split_feature[:nn],
                              tb.split_feature[:nn])
        np.testing.assert_allclose(ta.leaf_value, tb.leaf_value,
                                   rtol=1e-5, atol=1e-6)


def test_resolve_scan_iters_env_is_capped(monkeypatch):
    monkeypatch.delenv("LIGHTGBM_TPU_DISABLE_SCAN", raising=False)
    monkeypatch.setenv("LIGHTGBM_TPU_AUTO_SCAN_ITERS", "100000")
    assert resolve_scan_iters("auto") == 1024, \
        "the env opt-in must honor the same window ceiling Config " \
        "validation enforces"


def test_known_safe_callbacks_keep_scan_enabled(data):
    X, y = data
    rec = {}
    a = _train({"objective": "binary", "num_leaves": 15}, X, y,
               mode="scan", callbacks=[cbm.record_evaluation(rec)])
    assert a._engine._scan_fns, \
        "record_evaluation is scan-inert and must not disable windows"


def test_direct_update_api_stays_per_iteration(data):
    """Raw Booster.update() callers get no engine-computed lookahead:
    the default horizon of 1 keeps mid-training state reads exact."""
    X, y = data
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                              "fused_scan_iters": 8, "verbosity": -1},
                      train_set=lgb.Dataset(X, label=y))
    for _ in range(4):
        bst.update()
    assert not bst._engine._scan_fns
    assert bst._engine._fused_fn is not None


def test_custom_fobj_never_scans(data):
    X, y = data

    def fobj(preds, ds):
        lbl = np.asarray(ds.get_label())
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - lbl, p * (1 - p)

    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "none", "num_leaves": 15,
                     "fused_scan_iters": 4, "verbosity": -1}, ds,
                    num_boost_round=5, fobj=fobj)
    assert not bst._engine._scan_fns
    assert bst.current_iteration() == 5


# ---------------------------------------------------------------------
# natural early stop: the window stops at the exact tree
# ---------------------------------------------------------------------

def _stall_data():
    rs = np.random.RandomState(3)
    X = rs.randn(500, 3)
    y = (X[:, 0] > 0).astype(float) * 2.0
    return X, y


def test_natural_stop_at_exact_tree():
    """A perfectly-fittable target with lr=1.0 stalls after a few
    iterations; a window precomputed past the stall must discard the
    lookahead slots and stop at the same tree as per-iteration."""
    X, y = _stall_data()
    params = {"objective": "regression", "num_leaves": 4,
              "learning_rate": 1.0, "min_data_in_leaf": 5}
    a = _train(params, X, y, mode="scan", W=5, n=12)
    b = _train(params, X, y, mode="fused", n=12)
    assert a._engine._scan_fns
    assert a.current_iteration() == b.current_iteration() < 12
    assert len(a._models) == len(b._models)
    _assert_byte_identical(a, b)


def test_score_frozen_at_stop_point():
    """The scan body's stop carry gates the score update: the engine's
    final score must equal the per-iteration path's (no contribution
    from the discarded lookahead slots)."""
    X, y = _stall_data()
    params = {"objective": "regression", "num_leaves": 4,
              "learning_rate": 1.0, "min_data_in_leaf": 5}
    a = _train(params, X, y, mode="scan", W=5, n=12)
    b = _train(params, X, y, mode="fused", n=12)
    np.testing.assert_array_equal(a._engine.current_score(0),
                                  b._engine.current_score(0))


# ---------------------------------------------------------------------
# fault injection inside a window (resilience/faults.py)
# ---------------------------------------------------------------------

def test_nan_grad_fires_at_absolute_iteration_raise(data, monkeypatch):
    """nan_grad@7 poisons window slot 3 of the [4..7] window; the
    one-late drain must raise naming iteration 7, exactly like the
    per-iteration path."""
    X, y = data
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@7")
    with pytest.raises(lgb.LightGBMError, match="iteration 7"):
        _train({"objective": "binary", "num_leaves": 15}, X, y,
               mode="scan", n=12)


def test_nan_grad_skip_tree_inside_window_matches_fused(data,
                                                        monkeypatch):
    X, y = data
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "nan_grad@7")
    params = {"objective": "binary", "num_leaves": 15,
              "nonfinite_policy": "skip_tree"}
    a = _train(params, X, y, mode="scan", n=12)
    b = _train(params, X, y, mode="fused", n=12)
    assert a._engine._scan_fns
    # the poisoned iteration's tree is demoted to a constant in BOTH
    assert a._models[7].num_leaves == 1 == b._models[7].num_leaves
    assert a.current_iteration() == 12 == b.current_iteration()
    _assert_byte_identical(a, b)
    ev = [f for f in a._engine.fault_log if f["kind"] == "nonfinite"]
    assert ev and ev[0]["iteration"] == 7


def test_oom_injection_falls_back_to_per_iteration(data, monkeypatch):
    """oom@N is a HOST-side injection at dispatch time — mid-window
    slots have no dispatch, so the scan gate defers to the
    per-iteration fused path while an oom fault is scheduled. The
    fault event firing at the exact iteration 3 proves iteration 3 was
    its own dispatch; once the one-shot injection is consumed the scan
    may legally re-engage for the remaining iterations."""
    X, y = data
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "oom@3")
    params = {"objective": "binary", "num_leaves": 15}
    a = _train(params, X, y, mode="scan", n=8)
    ev = [f for f in a._engine.fault_log if f["kind"] == "oom"]
    assert ev and ev[0]["iteration"] == 3, \
        "oom@3 must fire at its exact iteration (a window covering " \
        "iteration 3 would have skipped the host injection)"
    assert a.current_iteration() == 8
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "oom@3")
    b = _train(params, X, y, mode="fused", n=8)
    _assert_byte_identical(a, b)


# ---------------------------------------------------------------------
# checkpoint cadence + resume landing mid-window
# ---------------------------------------------------------------------

def test_checkpoint_cadence_bounds_windows_and_resume_is_byte_identical(
        data, tmp_path):
    """every_n_iters=5 with W=4: windows end on checkpoint boundaries,
    snapshots carry committed state, and a resume from iteration 5
    (mid-window relative to the uninterrupted run's window grid)
    retrains to a byte-identical model."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15,
              "bagging_fraction": 0.7, "bagging_freq": 3}
    ck = str(tmp_path / "ck")
    full = _train(params, X, y, mode="scan", n=12,
                  callbacks=[lgb.checkpoint(ck, every_n_iters=5,
                                            keep=10)])
    snaps = sorted(glob.glob(os.path.join(ck, "ckpt_*.npz")))
    its = [int(os.path.basename(s)[5:-4]) for s in snaps]
    assert its == [5, 10, 12], its
    # keep only the iteration-5 snapshot and resume to 12
    for s in snaps:
        if not s.endswith("00000005.npz"):
            os.unlink(s)
    resumed = _train(params, X, y, mode="scan", n=12, W=4,
                     resume_from=ck)
    assert resumed.current_iteration() == 12
    _assert_byte_identical(full, resumed)
    # and a resume that DISABLES the scan must also match
    resumed_fused = _train(params, X, y, mode="fused", n=12,
                           resume_from=ck)
    _assert_byte_identical(full, resumed_fused)


def test_init_model_offset_keeps_checkpoints_on_cadence(data, tmp_path):
    """Continued training (init_model) offsets the engine's iter_ from
    the loop index; the Checkpoint callback fires on iter_, so the
    window bound must key off iter_ too — snapshots land exactly on
    the every_n grid with committed state, and resuming reproduces the
    model byte-for-byte."""
    X, y = data
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1}
    base = _train(params, X, y, mode="fused", n=3)
    ck = str(tmp_path / "ck")

    def cont(scan, resume_from=None, rounds=10):
        p = dict(params)
        if scan:
            p["fused_scan_iters"] = 8
        return lgb.train(p, lgb.Dataset(X, label=y),
                         num_boost_round=rounds, init_model=base,
                         resume_from=resume_from,
                         callbacks=[lgb.checkpoint(
                             ck, every_n_iters=5, keep=10)])

    a = cont(scan=True)
    assert a._engine._scan_fns
    snaps = sorted(glob.glob(os.path.join(ck, "ckpt_*.npz")))
    its = [int(os.path.basename(s)[5:-4]) for s in snaps]
    assert its == [5, 10, 13], \
        f"snapshots off the iter_-keyed cadence: {its}"
    b = cont(scan=False)
    _assert_byte_identical(a, b)
    # resume from the mid-run snapshot reproduces the model with the
    # IDENTICAL command: the snapshot records the init_model offset
    # (num_init_iteration), so rounds stays the per-run delta (10) and
    # the resumed run still finishes at init(3) + 10 = 13 — the
    # relaunch-same-command contract the pipeline's rank_kill chaos
    # depends on (docs/PIPELINE.md)
    for s in snaps:
        if not s.endswith("00000005.npz"):
            os.unlink(s)
    c = cont(scan=True, resume_from=ck, rounds=10)
    assert _model_bytes(a, ignore=("[num_iterations",)) \
        == _model_bytes(c, ignore=("[num_iterations",))


def test_horizon_reset_after_train_returns():
    """A booster returned by train() (keep_training_booster semantics:
    the engine survives) must not keep a stale multi-iteration horizon
    — a natural stall breaks the loop early, and direct update() calls
    afterwards have no engine loop bounding callbacks/eval."""
    X, y = _stall_data()
    params = {"objective": "regression", "num_leaves": 4,
              "learning_rate": 1.0, "min_data_in_leaf": 5,
              "fused_scan_iters": 5, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=12, keep_training_booster=True)
    assert bst.current_iteration() < 12  # stalled -> early break
    assert bst._engine._scan_horizon == 1, \
        "train() leaked a multi-iteration horizon to the direct API"


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_sigkill_mid_window_resume_byte_identical(tmp_path):
    """SIGKILL at iteration 12 with checkpoints every 5 and W=4: the
    kill lands with a window in flight; the supervised re-run resumes
    from the newest committed snapshot and the final model is
    byte-identical to an uninterrupted run (tests/ckpt_worker.py)."""
    scan_params = json.dumps({"fused_scan_iters": 4,
                              "feature_fraction": 1.0})
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["CKPT_WORKER_PARAMS"] = scan_params
    env["LIGHTGBM_TPU_CHECKPOINT"] = str(tmp_path / "ck")
    env["LIGHTGBM_TPU_CHECKPOINT_EVERY"] = "5"
    env["LIGHTGBM_TPU_FAULT_INJECT"] = "kill@12"
    worker = [sys.executable, os.path.join(_DIR, "ckpt_worker.py")]

    killed_model = str(tmp_path / "model_killed.txt")
    p = subprocess.run(worker + [killed_model], env=env,
                       capture_output=True, timeout=300)
    assert p.returncode == -signal.SIGKILL, p.stdout.decode()

    env.pop("LIGHTGBM_TPU_FAULT_INJECT")
    p = subprocess.run(worker + [killed_model], env=env,
                       capture_output=True, timeout=300)
    assert p.returncode == 0, p.stdout.decode() + p.stderr.decode()
    assert b"WORKER DONE iterations=20" in p.stdout

    env2 = dict(os.environ)
    env2["JAX_PLATFORMS"] = "cpu"
    env2["CKPT_WORKER_PARAMS"] = scan_params
    env2["LIGHTGBM_TPU_CHECKPOINT"] = str(tmp_path / "ck2")
    env2["LIGHTGBM_TPU_CHECKPOINT_EVERY"] = "5"
    clean_model = str(tmp_path / "model_clean.txt")
    p = subprocess.run(worker + [clean_model], env=env2,
                       capture_output=True, timeout=300)
    assert p.returncode == 0, p.stdout.decode() + p.stderr.decode()

    with open(killed_model) as a, open(clean_model) as b:
        assert a.read() == b.read()


# ---------------------------------------------------------------------
# telemetry: one event per iteration, window-position field
# ---------------------------------------------------------------------

def test_telemetry_events_stay_per_iteration_with_scan_field(
        data, tmp_path):
    X, y = data
    path = str(tmp_path / "scan.jsonl")
    _train({"objective": "binary", "num_leaves": 15}, X, y,
           mode="scan", n=10, callbacks=[cbm.telemetry(path)])
    evs = [json.loads(ln) for ln in open(path) if ln.strip()]
    it_evs = [e for e in evs if e.get("event") == "iteration"]
    assert len(it_evs) == 10
    assert [e["iteration"] for e in it_evs] == list(range(10))
    # windows of 4 over 10 iterations: dispatches at 0, 4, 8
    marks = [(e["scan"]["pos"], e["scan"]["dispatch"])
             for e in it_evs if e.get("scan")]
    assert len(marks) == 10
    assert sum(1 for _, d in marks if d) == 3
    assert marks[0] == (0, True) and marks[1] == (1, False)
    from lightgbm_tpu.obs import summarize_events
    summary = summarize_events(path)
    assert summary["scan_windows"] == 3
    assert summary["scan_iterations"] == 10


def test_telemetry_scan_field_null_on_per_iteration_paths(
        data, tmp_path):
    X, y = data
    path = str(tmp_path / "noscan.jsonl")
    _train({"objective": "binary", "num_leaves": 15}, X, y,
           mode="fused", n=5, callbacks=[cbm.telemetry(path)])
    evs = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert all(e.get("scan") is None for e in evs
               if e.get("event") == "iteration")


# ---------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------

def test_resolve_scan_iters_matrix(monkeypatch):
    monkeypatch.delenv("LIGHTGBM_TPU_AUTO_SCAN_ITERS", raising=False)
    monkeypatch.delenv("LIGHTGBM_TPU_DISABLE_SCAN", raising=False)
    # auto stays per-iteration until the bench verdict flips it
    assert resolve_scan_iters("auto") == 1
    assert resolve_scan_iters(8) == 8
    monkeypatch.setenv("LIGHTGBM_TPU_AUTO_SCAN_ITERS", "16")
    assert resolve_scan_iters("auto") == 16
    # the kill switch pins EVERYTHING back to per-iteration
    monkeypatch.setenv("LIGHTGBM_TPU_DISABLE_SCAN", "1")
    assert resolve_scan_iters("auto") == 1
    assert resolve_scan_iters(8) == 1


def test_fused_scan_iters_validation():
    from lightgbm_tpu.config import Config
    assert Config.from_params(
        {"fused_scan_iters": 8}).fused_scan_iters == 8
    assert Config.from_params({}).fused_scan_iters == "auto"
    with pytest.raises(ValueError):
        Config.from_params({"fused_scan_iters": 0})
    with pytest.raises(ValueError):
        Config.from_params({"fused_scan_iters": "sometimes"})
    with pytest.raises(ValueError):
        Config.from_params({"fused_scan_iters": 100000})
