"""Per-phase wall-clock decomposition of one boosting iteration.

Times, at a Higgs-like shape (env BENCH_ROWS/BENCH_FEATURES/BENCH_LEAVES):
  - gradient computation (objective)
  - full grow_tree at num_leaves in {2, 8, 64, 255} (separates the
    root-histogram cost from per-split cost)
  - score update (predict_leaf_binned over the train rows)
  - micro: one MXU nibble histogram chunk, one pass-B variadic sort chunk

Run on TPU:  python benchmarks/profile_phases.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.grow import GrowConfig, grow_tree
from lightgbm_tpu.ops.histogram import hist_from_rows
from lightgbm_tpu.ops.split import SplitParams, find_best_split
from lightgbm_tpu.ops.predict import predict_leaf_binned

N = int(os.environ.get("BENCH_ROWS", 1_048_576))
F = int(os.environ.get("BENCH_FEATURES", 28))
L = int(os.environ.get("BENCH_LEAVES", 255))
B = 256
K = 16384

rs = np.random.RandomState(0)
bins_T = jnp.asarray(rs.randint(0, 255, size=(F, N), dtype=np.uint8))
grad = jnp.asarray(rs.randn(N).astype(np.float32))
hess = jnp.asarray(np.abs(rs.randn(N)).astype(np.float32) + 0.1)
row_w = jnp.ones((N,), jnp.float32)
fmask = jnp.ones((F,), bool)
fnb = jnp.full((F,), 255, jnp.int32)
fnan = jnp.full((F,), -1, jnp.int32)


def timeit(fn, *args, reps=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / reps, out


def report(name, secs):
    print(f"{name:55s} {secs*1e3:10.2f} ms")


# ---- full tree at varying leaf counts ----
prev = None
for leaves in (2, 8, 64, L):
    cfg = GrowConfig(num_leaves=leaves, num_bins=B, split=SplitParams(),
                     hist_method="mxu", grower="compact", chunk=K)
    s, _ = timeit(grow_tree, cfg, bins_T, grad, hess, row_w, fmask,
                  fnb, fnan, reps=2)
    extra = ""
    if prev is not None:
        ds, dl = s - prev[0], leaves - prev[1]
        extra = f"   (+{ds/dl*1e3:.2f} ms/split marginal)"
    report(f"grow_tree num_leaves={leaves}", s)
    if extra:
        print(" " * 55 + extra)
    prev = (s, leaves)

# ---- micro: one histogram chunk (K rows) ----
rows_k = jnp.asarray(rs.randint(0, 255, size=(K, F), dtype=np.uint8))
pay_k = jnp.asarray(rs.randn(K, 2).astype(np.float32))
f_hist = jax.jit(lambda r, p: hist_from_rows(r, p, B, "mxu"))
s, _ = timeit(f_hist, rows_k, pay_k, reps=20, warmup=3)
report(f"hist_from_rows mxu chunk [{K}x{F}] -> [F,{B},2]", s)
tot_chunks = N // K
report(f"  x {tot_chunks} chunks (full-data pass equivalent)",
       s * tot_chunks)

# ---- micro: pass-B variadic sort of one chunk ----
key = jnp.asarray(rs.randint(0, 2 * K, size=(K,), dtype=np.int32))
cols = tuple(jnp.asarray(rs.randint(0, 2**31, size=(K,), dtype=np.int32))
             for _ in range(F // 4 + 3))


def f_sort(key, cols):
    return jax.lax.sort((key,) + cols, num_keys=1)


s, _ = timeit(jax.jit(f_sort), key, cols, reps=20, warmup=3)
report(f"pass-B variadic sort chunk [{K}] x {len(cols)+1} ops", s)

# ---- split search over all leaves' histograms ----
hist = jnp.asarray(rs.rand(F, B, 2).astype(np.float32))
f_split = jax.jit(lambda h: find_best_split(
    h, jnp.float32(1.0), jnp.float32(100.0), jnp.float32(N), fnb, fnan,
    fmask, SplitParams()))
s, _ = timeit(f_split, hist, reps=20, warmup=3)
report("find_best_split one leaf [F,B,2]", s)

# ---- score update: predict over all rows ----
sf = jnp.zeros((L - 1,), jnp.int32)
tb = jnp.full((L - 1,), 128, jnp.int32)
dlft = jnp.zeros((L - 1,), bool)
lc = -(jnp.arange(L - 1, dtype=jnp.int32) + 1)
rc = -(jnp.arange(L - 1, dtype=jnp.int32) + 2)
f_pred = jax.jit(lambda: predict_leaf_binned(sf, tb, dlft, lc, rc, fnan,
                                             bins_T))
s, _ = timeit(f_pred, reps=5, warmup=2)
report(f"predict_leaf_binned all {N} rows", s)

# ---- gradients ----
lbl = jnp.asarray((rs.rand(N) > 0.5).astype(np.float32))


def f_grad(score):
    p = jax.nn.sigmoid(score)
    return p - lbl, p * (1 - p)


s, _ = timeit(jax.jit(f_grad), jnp.zeros((N,), jnp.float32), reps=10)
report("binary grad/hess", s)
