#!/usr/bin/env sh
# One-shot tpulint runner: analyzer + baseline check. Exits non-zero on
# any non-baselined finding AND on stale/unjustified baseline entries
# (--strict), so CI catches both new hazards and rotted acceptances.
# No jax import happens on this path — safe for backend-less runners.
# Pre-commit loop: `tools/lint.sh --changed` lints only files differing
# from HEAD (~100 ms when nothing in scope changed).
set -eu
cd "$(dirname "$0")/.."
exec python -m lightgbm_tpu lint --strict \
    --baseline tools/tpulint_baseline.txt "$@"
