"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

A from-scratch re-design of the LightGBM feature set
(reference: /root/reference, PieterPel/LightGBM @ 4.6.0.99) on JAX/XLA:
histogram-based leaf-wise GBDT with the binned data, gradients and
histograms resident in HBM; collectives over a `jax.sharding.Mesh`
instead of sockets/MPI; and a drop-in `Dataset`/`Booster`/`train` Python
API mirroring the reference python-package.
"""

from .basic import Booster, Dataset, LightGBMError, Sequence
from .callback import (EarlyStopException, checkpoint, early_stopping,
                       log_evaluation, record_evaluation, reset_parameter,
                       telemetry)
from .config import Config
from .engine import CVBooster, cv, train
from .utils.log import register_logger

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "CVBooster", "LightGBMError",
    "train", "cv",
    "early_stopping", "log_evaluation", "record_evaluation",
    "reset_parameter", "telemetry", "checkpoint", "EarlyStopException",
    "register_logger", "Config",
]

try:  # sklearn-style wrappers are optional (need scikit-learn)
    from .sklearn import (LGBMClassifier, LGBMModel, LGBMRanker,
                          LGBMRegressor)
    __all__ += ["LGBMModel", "LGBMClassifier", "LGBMRegressor",
                "LGBMRanker"]
except ImportError:  # pragma: no cover
    pass

try:
    from . import plotting
    from .plotting import (create_tree_digraph, plot_importance,
                           plot_metric, plot_split_value_histogram,
                           plot_tree)
    __all__ += ["plot_importance", "plot_metric",
                "plot_split_value_histogram", "plot_tree",
                "create_tree_digraph"]
except ImportError:  # pragma: no cover
    pass
