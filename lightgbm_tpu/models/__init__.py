"""Model structures and boosting drivers."""
