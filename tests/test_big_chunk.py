"""Big-chunk bulk batching (GrowConfig.big_chunk): the partition
streams floor(cnt/BK) BK-row bodies then K-row tail bodies per window.
Must be semantically identical to the K-only loop.

With quantized gradients the histograms are exact int32, so the tree
must be BIT-identical regardless of chunking. In float mode only the
within-window row ORDER (and hence float summation order) may differ;
trees must still agree structurally on well-separated data.
"""

import numpy as np

import lightgbm_tpu as lgb
from conftest import make_synthetic_binary


def _train(X, y, big, extra=None):
    params = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
              "chunk_rows": 256, "big_chunk_rows": big,
              "min_data_in_leaf": 5}
    params.update(extra or {})
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)


def test_big_chunk_quantized_bit_identical():
    X, y = make_synthetic_binary(n=6000, f=8, seed=3)
    extra = {"use_quantized_grad": True, "stochastic_rounding": False}
    b0 = _train(X, y, 0, extra)
    b1 = _train(X, y, 1024, extra)
    for t0, t1 in zip(b0._models, b1._models):
        np.testing.assert_array_equal(t0.split_feature, t1.split_feature)
        np.testing.assert_array_equal(t0.threshold, t1.threshold)
        np.testing.assert_array_equal(t0.leaf_value, t1.leaf_value)
    np.testing.assert_array_equal(b0.predict(X), b1.predict(X))


def test_big_chunk_float_structurally_equal():
    X, y = make_synthetic_binary(n=6000, f=8, seed=4)
    b0 = _train(X, y, 0)
    b1 = _train(X, y, 1024)
    for t0, t1 in zip(b0._models, b1._models):
        np.testing.assert_array_equal(t0.split_feature, t1.split_feature)
        np.testing.assert_array_equal(t0.threshold, t1.threshold)
    np.testing.assert_allclose(b0.predict(X), b1.predict(X), rtol=2e-5,
                               atol=1e-7)


def test_big_chunk_with_bagging_and_cat():
    rs = np.random.RandomState(9)
    n = 5000
    Xn, y = make_synthetic_binary(n=n, f=6, seed=9)
    cat = rs.randint(0, 12, size=(n, 1)).astype(np.float64)
    y = np.where((cat[:, 0] > 6) ^ (y > 0), 1.0, 0.0)
    X = np.hstack([Xn, cat])
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "chunk_rows": 256, "big_chunk_rows": 1024,
              "bagging_fraction": 0.7, "bagging_freq": 1, "seed": 5,
              "use_quantized_grad": True, "stochastic_rounding": False}
    ds = lgb.Dataset(X, label=y, categorical_feature=[6])
    b1 = lgb.train(params, ds, num_boost_round=5)
    params0 = dict(params, big_chunk_rows=0)
    ds0 = lgb.Dataset(X, label=y, categorical_feature=[6])
    b0 = lgb.train(params0, ds0, num_boost_round=5)
    for t0, t1 in zip(b0._models, b1._models):
        np.testing.assert_array_equal(t0.split_feature, t1.split_feature)
    np.testing.assert_array_equal(b0.predict(X), b1.predict(X))


def test_untracked_rows_bit_identical_to_tracked():
    """GrowConfig.track_rows=False (plain full-data path, round 4)
    drops the ord2 sort column; under quantized gradients the grown
    tree AND row_leaf must be bit-identical to the tracked path."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.grow import GrowConfig, grow_tree
    from lightgbm_tpu.ops.split import SplitParams

    rs = np.random.RandomState(2)
    n, f, B = 5000, 6, 64
    bins_T = jnp.asarray(rs.randint(0, B - 1, size=(f, n)), jnp.uint8)
    y = (np.asarray(bins_T)[0] > 30).astype(np.float32)
    grad = jnp.asarray(0.5 - y + 0.1 * rs.randn(n).astype(np.float32))
    hess = jnp.full((n,), 0.25, jnp.float32)
    ones = jnp.ones((n,), jnp.float32)
    fmask = jnp.ones((f,), bool)
    fnb = jnp.full((f,), B - 1, jnp.int32)
    fnan = jnp.full((f,), -1, jnp.int32)
    outs = {}
    for track in (True, False):
        cfg = GrowConfig(num_leaves=31, num_bins=B,
                         split=SplitParams(min_data_in_leaf=5),
                         hist_method="scatter", quantized=True,
                         stochastic=False, track_rows=track)
        tree, row_leaf = grow_tree(cfg, bins_T, grad, hess, ones,
                                   fmask, fnb, fnan)
        outs[track] = (tree, row_leaf)
    t1, rl1 = outs[True]
    t0, rl0 = outs[False]
    np.testing.assert_array_equal(np.asarray(rl1), np.asarray(rl0))
    for a, b in zip(t1, t0):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
