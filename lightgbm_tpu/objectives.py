"""Objective functions (gradient/hessian providers).

Re-design of /root/reference/src/objective/* (regression_objective.hpp,
binary_objective.hpp, multiclass_objective.hpp, xentropy_objective.hpp,
rank_objective.hpp; factory objective_function.cpp:20-100) as pure-jnp
vectorized gradient functions traced inside the jitted boosting step.

Interface (ObjectiveFunction analog, objective_function.h):
  - ``grad_hess(score, label, weight) -> (grad, hess)`` with score shaped
    ``[K, n]`` (K = models per iteration; 1 except multiclass),
  - ``boost_from_score(label, weight) -> [K]`` init scores,
  - ``convert_output(score)`` raw score -> prediction space,
  - ``renew_leaf_values(...)`` optional per-leaf output refinement
    (RenewTreeOutput analog — percentile/median leaf refits for the
    L1-family, regression_objective.hpp).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config

__all__ = ["create_objective", "Objective"]


def _wsum(x, w):
    return jnp.sum(x * w) if w is not None else jnp.sum(x)


def _weighted_percentile_np(values: np.ndarray, weights: Optional[np.ndarray],
                            alpha: float) -> float:
    """Host-side weighted percentile (PercentileFun analog,
    regression_objective.hpp)."""
    if len(values) == 0:
        return 0.0
    order = np.argsort(values, kind="stable")
    v = values[order]
    if weights is None:
        idx = alpha * (len(v) - 1)
        lo = int(np.floor(idx))
        hi = min(lo + 1, len(v) - 1)
        frac = idx - lo
        return float(v[lo] * (1 - frac) + v[hi] * frac)
    w = weights[order]
    cw = np.cumsum(w)
    cutoff = alpha * cw[-1]
    i = int(np.searchsorted(cw, cutoff))
    return float(v[min(i, len(v) - 1)])


class Objective:
    """Base objective. Subclasses override the jnp methods."""

    name = "custom"
    num_model_per_iteration = 1
    is_ranking = False
    need_renew = False          # L1-family per-leaf percentile refit
    renew_alpha = 0.5           # percentile used by renew (0.5 = median)

    def __init__(self, cfg: Config):
        self.cfg = cfg

    # -- jittable core ---------------------------------------------------
    def grad_hess(self, score: jnp.ndarray, label: jnp.ndarray,
                  weight: Optional[jnp.ndarray]
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def convert_output(self, score: jnp.ndarray) -> jnp.ndarray:
        return score

    # -- host-side init --------------------------------------------------
    def boost_from_score(self, label: np.ndarray,
                         weight: Optional[np.ndarray]) -> np.ndarray:
        return np.zeros((self.num_model_per_iteration,), np.float64)

    def transform_label(self, label: np.ndarray) -> np.ndarray:
        return label

    # residual used by the percentile renew (pred space)
    def renew_residual(self, score, label):
        return label - score

    def renew_weight(self, label: jnp.ndarray,
                     weight: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
        return weight


def _apply_weight(g, h, weight):
    if weight is None:
        return g, h
    return g * weight, h * weight


# ---------------------------------------------------------------------------
# Regression family (regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(Objective):
    name = "regression"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.sqrt = cfg.reg_sqrt

    def transform_label(self, label):
        if self.sqrt:
            return np.sign(label) * np.sqrt(np.abs(label))
        return label

    def grad_hess(self, score, label, weight):
        g = 2.0 * (score - label)
        h = jnp.full_like(score, 2.0)
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score

    def boost_from_score(self, label, weight):
        if weight is None:
            avg = float(np.mean(label))
        else:
            avg = float(np.sum(label * weight) / np.sum(weight))
        return np.array([avg])


class RegressionL1(Objective):
    name = "regression_l1"
    need_renew = True
    renew_alpha = 0.5

    def grad_hess(self, score, label, weight):
        g = jnp.sign(score - label)
        h = jnp.ones_like(score)
        return _apply_weight(g, h, weight)

    def boost_from_score(self, label, weight):
        return np.array([_weighted_percentile_np(label, weight, 0.5)])


class Huber(Objective):
    name = "huber"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.alpha = cfg.alpha

    def grad_hess(self, score, label, weight):
        d = score - label
        g = jnp.clip(d, -self.alpha, self.alpha)
        h = jnp.ones_like(score)
        return _apply_weight(g, h, weight)

    def boost_from_score(self, label, weight):
        return np.array([_weighted_percentile_np(label, weight, 0.5)])


class Fair(Objective):
    name = "fair"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.c = cfg.fair_c

    def grad_hess(self, score, label, weight):
        x = score - label
        denom = jnp.abs(x) + self.c
        g = self.c * x / denom
        h = self.c * self.c / (denom * denom)
        return _apply_weight(g, h, weight)


class Poisson(Objective):
    name = "poisson"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.max_delta = cfg.poisson_max_delta_step

    def grad_hess(self, score, label, weight):
        ex = jnp.exp(score)
        g = ex - label
        h = jnp.exp(score + self.max_delta)
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def boost_from_score(self, label, weight):
        if weight is None:
            avg = float(np.mean(label))
        else:
            avg = float(np.sum(label * weight) / np.sum(weight))
        return np.array([np.log(max(avg, 1e-20))])


class Quantile(Objective):
    name = "quantile"
    need_renew = True

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.alpha = cfg.alpha
        self.renew_alpha = cfg.alpha

    def grad_hess(self, score, label, weight):
        g = jnp.where(score < label, -self.alpha, 1.0 - self.alpha)
        h = jnp.ones_like(score)
        return _apply_weight(g, h, weight)

    def boost_from_score(self, label, weight):
        return np.array([_weighted_percentile_np(label, weight, self.alpha)])


class MAPE(Objective):
    name = "mape"
    need_renew = True
    renew_alpha = 0.5

    def grad_hess(self, score, label, weight):
        scale = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        g = jnp.sign(score - label) * scale
        h = scale
        return _apply_weight(g, h, weight)

    def renew_weight(self, label, weight):
        scale = 1.0 / jnp.maximum(1.0, jnp.abs(label))
        return scale if weight is None else weight * scale

    def boost_from_score(self, label, weight):
        w = 1.0 / np.maximum(1.0, np.abs(label))
        if weight is not None:
            w = w * weight
        return np.array([_weighted_percentile_np(label, w, 0.5)])


class Gamma(Objective):
    name = "gamma"

    def grad_hess(self, score, label, weight):
        e = jnp.exp(-score)
        g = 1.0 - label * e
        h = label * e
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def boost_from_score(self, label, weight):
        if weight is None:
            avg = float(np.mean(label))
        else:
            avg = float(np.sum(label * weight) / np.sum(weight))
        return np.array([np.log(max(avg, 1e-20))])


class Tweedie(Objective):
    name = "tweedie"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.rho = cfg.tweedie_variance_power

    def grad_hess(self, score, label, weight):
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        g = -label * e1 + e2
        h = -label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        return jnp.exp(score)

    def boost_from_score(self, label, weight):
        if weight is None:
            avg = float(np.mean(label))
        else:
            avg = float(np.sum(label * weight) / np.sum(weight))
        return np.array([np.log(max(avg, 1e-20))])


# ---------------------------------------------------------------------------
# Binary (binary_objective.hpp)
# ---------------------------------------------------------------------------
class Binary(Objective):
    name = "binary"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.sigmoid = cfg.sigmoid
        self.is_unbalance = cfg.is_unbalance
        self.scale_pos_weight = cfg.scale_pos_weight
        self._label_weights = (1.0, 1.0)  # (neg, pos)

    def init_label_weights(self, label: np.ndarray,
                           weight: Optional[np.ndarray]) -> None:
        """is_unbalance reweighting (binary_objective.hpp Init): scale the
        minority class so pos/neg contribute equally."""
        cnt_pos = float(np.sum(label > 0))
        cnt_neg = float(len(label) - cnt_pos)
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                self._label_weights = (cnt_pos / cnt_neg, 1.0)
            else:
                self._label_weights = (1.0, cnt_neg / cnt_pos)
        else:
            self._label_weights = (1.0, self.scale_pos_weight)

    def grad_hess(self, score, label, weight):
        wneg, wpos = self._label_weights
        sig = self.sigmoid
        p = jax.nn.sigmoid(sig * score)
        is_pos = label > 0
        lw = jnp.where(is_pos, wpos, wneg)
        y = is_pos.astype(score.dtype)
        g = sig * (p - y) * lw
        h = sig * sig * p * (1.0 - p) * lw
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        return jax.nn.sigmoid(self.sigmoid * score)

    def boost_from_score(self, label, weight):
        y = (label > 0).astype(np.float64)
        if weight is None:
            pavg = float(np.mean(y))
        else:
            pavg = float(np.sum(y * weight) / np.sum(weight))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return np.array([np.log(pavg / (1.0 - pavg)) / self.sigmoid])


# ---------------------------------------------------------------------------
# Multiclass (multiclass_objective.hpp)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(Objective):
    name = "multiclass"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.num_class = cfg.num_class
        self.num_model_per_iteration = cfg.num_class

    def grad_hess(self, score, label, weight):
        # score: [K, n]
        p = jax.nn.softmax(score, axis=0)
        K = self.num_class
        y = jax.nn.one_hot(label.astype(jnp.int32), K, axis=0,
                           dtype=score.dtype)
        factor = K / (K - 1.0)
        g = p - y
        h = factor * p * (1.0 - p)
        if weight is not None:
            g = g * weight[None, :]
            h = h * weight[None, :]
        return g, h

    def convert_output(self, score):
        return jax.nn.softmax(score, axis=0)


class MulticlassOVA(Objective):
    name = "multiclassova"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.num_class = cfg.num_class
        self.num_model_per_iteration = cfg.num_class
        self.sigmoid = cfg.sigmoid

    def grad_hess(self, score, label, weight):
        sig = self.sigmoid
        K = self.num_class
        p = jax.nn.sigmoid(sig * score)
        y = jax.nn.one_hot(label.astype(jnp.int32), K, axis=0,
                           dtype=score.dtype)
        g = sig * (p - y)
        h = sig * sig * p * (1.0 - p)
        if weight is not None:
            g = g * weight[None, :]
            h = h * weight[None, :]
        return g, h

    def convert_output(self, score):
        return jax.nn.sigmoid(self.sigmoid * score)


# ---------------------------------------------------------------------------
# Cross-entropy with probabilistic labels (xentropy_objective.hpp)
# ---------------------------------------------------------------------------
class CrossEntropy(Objective):
    name = "cross_entropy"

    def grad_hess(self, score, label, weight):
        p = jax.nn.sigmoid(score)
        g = p - label
        h = p * (1.0 - p)
        return _apply_weight(g, h, weight)

    def convert_output(self, score):
        return jax.nn.sigmoid(score)

    def boost_from_score(self, label, weight):
        if weight is None:
            pavg = float(np.mean(label))
        else:
            pavg = float(np.sum(label * weight) / np.sum(weight))
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return np.array([np.log(pavg / (1.0 - pavg))])


class CrossEntropyLambda(Objective):
    """Alternative parameterization z = log(1 + exp(score))
    (CrossEntropyLambda, xentropy_objective.hpp)."""

    name = "cross_entropy_lambda"

    def grad_hess(self, score, label, weight):
        w = weight if weight is not None else jnp.ones_like(score)
        es = jnp.exp(score)
        log1pes = jnp.log1p(es)
        # z = log1p(exp(s)); dz/ds = sigmoid(s)
        sig = es / (1.0 + es)
        # loss = w * [z - label * log(1 - exp(-z))] with the lambda link;
        # gradients derived analytically:
        emz = jnp.exp(-log1pes)          # exp(-z) = 1/(1+e^s)
        one_memz = 1.0 - emz             # 1 - exp(-z) = sigmoid(s)
        g = sig * (w - label * emz / jnp.maximum(one_memz, 1e-15))
        # Gauss-Newton style positive hessian
        h = sig * (1.0 - sig) * (
            w + label * emz / jnp.maximum(one_memz * one_memz, 1e-15) * sig) \
            + sig * sig * label * emz / jnp.maximum(one_memz, 1e-15)
        h = jnp.maximum(h, 1e-15)
        return g, h

    def convert_output(self, score):
        return jnp.log1p(jnp.exp(score))


# ---------------------------------------------------------------------------
# factory (objective_function.cpp:20-100)
# ---------------------------------------------------------------------------
_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": MAPE,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": Binary,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
}


def create_objective(cfg: Config) -> Optional[Objective]:
    if cfg.objective == "custom":
        return None
    if cfg.objective in ("lambdarank", "rank_xendcg"):
        from .ranking import create_ranking_objective
        return create_ranking_objective(cfg)
    if cfg.objective not in _REGISTRY:
        raise ValueError(f"Unknown objective {cfg.objective}")
    return _REGISTRY[cfg.objective](cfg)
