"""Forest compiler: one trained Booster -> a servable compiled forest.

The Booster keeps trees as per-tree host objects (models/tree.py) and
the library predict path re-stacks them into device tensors on *every*
call — fine for notebooks, fatal for serving. Here the forest is
lowered ONCE into the tensorized SoA layout (ops/predict.py
StackedTrees: level-order feature/threshold/child/leaf-value arrays,
categorical bitsets packed to u32 words, optional linear-tree
coefficients), and batch prediction is a single jitted program over
that layout (the Booster/tensorized-traversal design of
arXiv:2011.02022 applied to this codebase's node-sweep predictor).

Two serving invariants live here:

- **Shape bucketing** (TPL003): the jit cache is keyed on the input
  shape, so arbitrary request sizes would compile forever. Rows are
  padded up to power-of-two buckets between ``min_bucket`` and
  ``max_batch_rows`` — at most ``log2(max/min)+1`` compiles per model,
  all touchable at warmup, and the recompile counter stays flat
  afterwards (contract-tested in tests/test_serve.py).
- **Donated hot swap**: a model swap stages the NEW forest on the host
  (``stack_trees(..., device=False)``) and uploads it FIELD BY FIELD
  through a jitted identity that donates the old field's device buffer
  (``donate_argnums=(0,)``), so the swap's transient HBM overhead is
  one field's staging copy — never a second resident forest. When
  layouts differ (tree count / padded width changed) it falls back to
  a plain whole-forest transfer.
"""

from __future__ import annotations

import hashlib
import warnings
from functools import partial
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import register_jit
from ..ops.predict import StackedTrees, predict_leaf_raw
from ..prediction import convert_raw_scores, stack_trees

__all__ = ["CompiledForest", "compile_forest", "bucket_rows",
           "n_serve_buckets"]


def bucket_rows(n: int, min_bucket: int = 16,
                max_bucket: int = 16384) -> int:
    """Smallest power-of-two >= ``n`` clamped to [min_bucket,
    max_bucket]. Requests larger than ``max_bucket`` are split by the
    caller; everything else pads up, so the jit cache holds at most
    ``log2(max/min) + 1`` entries per model."""
    if n <= 0:
        raise ValueError(f"batch must have at least one row, got {n}")
    b = 1 << (int(n) - 1).bit_length()
    return max(min_bucket, min(b, max_bucket))


def n_serve_buckets(min_bucket: int = 16,
                    max_bucket: int = 16384) -> int:
    """Number of distinct pow2 row buckets ``bucket_rows`` can emit —
    the per-model compile ceiling of the serving program, and the
    floor ``lint --ir`` (TPL014) holds the ``serve/predict``
    ``max_signatures`` declaration against."""
    import math

    return int(math.log2(max_bucket // min_bucket)) + 1


@partial(jax.jit, static_argnums=(2,))
def _predict_scores_padded(stacked: StackedTrees, X: jnp.ndarray,
                           K: int) -> jnp.ndarray:
    """Raw scores [n, K] for a padded batch — the ONE serving program.

    Leaf routing, (linear-)leaf evaluation and the per-class
    scatter-add all trace into a single XLA computation, so a request
    costs one dispatch instead of the library path's stack + three."""
    T = stacked.leaf_value.shape[0]

    def per_tree(ti):
        return predict_leaf_raw(stacked, ti, X)

    leaves = jax.vmap(per_tree)(jnp.arange(T))           # [T, n]
    if stacked.lin_const is not None:
        from ..ops.linear import linear_leaf_values

        def per_tree_vals(ti):
            return linear_leaf_values(
                stacked.lin_const[ti], stacked.lin_coef[ti],
                stacked.lin_feats[ti], stacked.lin_nfeat[ti],
                stacked.leaf_value[ti], X, leaves[ti])

        vals = jax.vmap(per_tree_vals)(jnp.arange(T))
    else:
        vals = jnp.take_along_axis(stacked.leaf_value, leaves, axis=1)
    scores = jnp.zeros((K, X.shape[0]), vals.dtype)
    scores = scores.at[jnp.arange(T) % K].add(vals)
    return scores.T                                      # [n, K]


# the declared recompile surface is the full pow2 bucket ladder twice
# over (two live tree-count/K layouts per process — a hot swap staging
# a differently-shaped forest compiles its own ladder)
_predict_scores_padded = register_jit("serve/predict",
                                      _predict_scores_padded,
                                      max_signatures=2 * n_serve_buckets())


@partial(jax.jit, donate_argnums=(0,))
def _adopt_leaf(old: jnp.ndarray, new: jnp.ndarray) -> jnp.ndarray:
    """Upload ONE field of the new forest into the old field's donated
    buffer. Adoption walks the layout field by field, so the swap's
    transient HBM overhead is a single field's staging copy — never a
    second resident forest. (A whole-tree donating identity would not
    help: every new field would have to be device-resident as an
    input while the full old forest is still alive, i.e. 2x peak.)"""
    return new


def _layouts_match(old: StackedTrees, new: StackedTrees) -> bool:
    old_leaves = jax.tree_util.tree_leaves(old)
    new_leaves = jax.tree_util.tree_leaves(new)
    if len(old_leaves) != len(new_leaves):
        return False
    return all(a.shape == b.shape and a.dtype == b.dtype
               for a, b in zip(old_leaves, new_leaves))


def _model_digest(host_stacked: StackedTrees) -> str:
    """Stable short id of the compiled arrays, for telemetry and the
    daemon protocol ("which model answered this request"). Only the
    prediction-relevant fields are hashed — ``threshold_bin`` is a
    training-side artifact that text-round-tripped models lose, and
    the same forest must keep the same id across a save/load."""
    h = hashlib.sha256()
    for name, leaf in zip(host_stacked._fields, host_stacked):
        if name == "threshold_bin" or leaf is None:
            continue
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.hexdigest()[:16]


class CompiledForest:
    """A forest lowered to device tensors plus its serving metadata.

    Build via :func:`compile_forest` (or ``Booster.compile()``, which
    also routes subsequent ``Booster.predict`` calls through this
    object's shape-bucketed program)."""

    def __init__(self, stacked, *, num_class: int, n_features: int,
                 objective_str: str, avg_output: bool,
                 num_iteration: int, lo: int, hi: int,
                 total_trees: int, model_id: str,
                 min_bucket: int = 16, max_batch_rows: int = 16384):
        self._stacked = stacked           # device StackedTrees (or None)
        self._host = None                 # staged host arrays (stage=True)
        self._dead = False                # buffers donated to a successor
        self.K = int(num_class)
        self.n_features = int(n_features)
        self.objective_str = objective_str
        self.avg_output = bool(avg_output)
        self.num_iteration = int(num_iteration)
        self.lo = int(lo)
        self.hi = int(hi)
        self.total_trees = int(total_trees)
        self.model_id = model_id
        if min_bucket < 1 or (min_bucket & (min_bucket - 1)) != 0:
            raise ValueError(f"min_bucket must be a power of two >= 1, "
                             f"got {min_bucket}")
        if max_batch_rows < min_bucket or \
                (max_batch_rows & (max_batch_rows - 1)) != 0:
            raise ValueError(
                "max_batch_rows must be a power of two >= min_bucket, "
                f"got {max_batch_rows}")
        self.min_bucket = int(min_bucket)
        self.max_batch_rows = int(max_batch_rows)

    @property
    def num_trees(self) -> int:
        return self.hi - self.lo

    def matches(self, lo: int, hi: int, total_trees: int) -> bool:
        """Does this compilation still describe the Booster state a
        predict call wants? (The Booster may have trained more trees,
        or the caller may ask for a different iteration range.) A dead
        forest — one whose buffers a newer compilation took over —
        never matches, so a booster still caching it falls back to the
        eager path instead of serving donated garbage."""
        return not self._dead and \
            (self.lo, self.hi, self.total_trees) == (lo, hi, total_trees)

    def buckets(self) -> List[int]:
        out = []
        b = self.min_bucket
        while b <= self.max_batch_rows:
            out.append(b)
            b *= 2
        return out

    # -- prediction ----------------------------------------------------
    def predict_raw(self, X) -> np.ndarray:
        """Raw scores ``[n, K]`` (f64) for raw-feature rows ``[n, F]``.

        Rows are padded to the enclosing power-of-two bucket (chunked
        at ``max_batch_rows``), so after warmup NO batch size causes a
        compile — the TPL003 invariant the recompile-counter contract
        test pins."""
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        if X.ndim == 1:
            X = X[None, :]
        if X.shape[1] != self.n_features:
            from ..basic import LightGBMError
            raise LightGBMError(
                f"The number of features in data ({X.shape[1]}) is not "
                f"the same as it was in training data "
                f"({self.n_features}).")
        n = X.shape[0]
        if self._dead:
            raise RuntimeError(
                "this forest's device buffers were donated to a newer "
                "compilation (compile_forest(reuse=...)); it must not "
                "predict again")
        if n == 0:
            return np.zeros((0, self.K), np.float64)
        if self._stacked is None:
            if self._host is not None:
                raise RuntimeError(
                    "forest is staged on the host: call attach() "
                    "before predicting")
            return np.zeros((n, self.K), np.float64)  # empty forest
        outs = []
        for lo in range(0, n, self.max_batch_rows):
            chunk = X[lo:lo + self.max_batch_rows]
            rows = chunk.shape[0]
            b = bucket_rows(rows, self.min_bucket, self.max_batch_rows)
            if b > rows:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - rows, X.shape[1]),
                                     np.float32)])
            scores = _predict_scores_padded(self._stacked, chunk, self.K)
            # fetch the PADDED result and slice on the host: a device
            # `scores[:rows]` would trace one lazy-slice executable per
            # (bucket, rows) pair — an unbounded compile-cache leak the
            # bucketing exists to prevent (and invisible to the
            # registered recompile counter)
            outs.append(np.asarray(scores)[:rows].astype(np.float64))
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def finalize(self, raw_scores: np.ndarray,
                 raw_score: bool = False) -> np.ndarray:
        """Objective transform + rf averaging + K==1 squeeze — the
        exact tail of the library predict path, applied host-side."""
        out = raw_scores
        if self.avg_output:
            out = out / max(1, self.num_iteration)
        if not raw_score:
            out = convert_raw_scores(self.objective_str, out)
        return out[:, 0] if self.K == 1 else out

    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        return self.finalize(self.predict_raw(X), raw_score)

    # -- lifecycle -----------------------------------------------------
    def warmup(self, max_rows: Optional[int] = None) -> int:
        """Compile every row bucket up to ``max_rows`` (default: all of
        them) by running zero batches through the program; returns the
        number of buckets touched. After this, serving traffic of ANY
        batch size <= max_rows hits a warm cache."""
        if self._stacked is None:
            return 0
        cap = self.max_batch_rows if max_rows is None \
            else max(self.min_bucket, int(max_rows))
        touched = 0
        for b in self.buckets():
            if b > cap:
                break
            zeros = np.zeros((b, self.n_features), np.float32)
            _predict_scores_padded(self._stacked, zeros,
                                   self.K).block_until_ready()
            touched += 1
        return touched

    def attach(self, reuse: Optional["CompiledForest"] = None) \
            -> "CompiledForest":
        """Upload this forest's STAGED host arrays
        (``compile_forest(..., stage=True)``), donating ``reuse``'s
        device buffers when the layouts match. The daemon's hot-swap
        path runs this on the batcher's worker thread — the one point
        where no batch can still reference the old forest, which is
        what makes the donation safe."""
        if self._host is None:
            return self
        host, self._host = self._host, None
        if reuse is not None:
            reuse.adopt(host)
            self._stacked, reuse._stacked = reuse._stacked, None
            reuse._dead = True
        else:
            self._stacked = jax.tree_util.tree_map(jnp.asarray, host)
        return self

    def adopt(self, host_stacked: Optional[StackedTrees]):
        """Replace the device forest with ``host_stacked`` (host
        arrays), donating the old buffers when the layouts line up.
        Internal: used by :func:`compile_forest` via ``reuse=``."""
        if host_stacked is None:
            self._stacked = None
            return
        old = self._stacked
        if old is not None and _layouts_match(old, host_stacked):
            with warnings.catch_warnings():
                # backends without working donation (CPU on some
                # jaxlibs) warn and copy; the swap is still correct
                warnings.simplefilter("ignore")
                old_leaves, treedef = jax.tree_util.tree_flatten(old)
                new_leaves = jax.tree_util.tree_leaves(host_stacked)
                adopted = [_adopt_leaf(o, n)
                           for o, n in zip(old_leaves, new_leaves)]
                self._stacked = jax.tree_util.tree_unflatten(
                    treedef, adopted)
        else:
            self._stacked = jax.tree_util.tree_map(jnp.asarray,
                                                   host_stacked)


def compile_forest(booster, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   min_bucket: int = 16,
                   max_batch_rows: int = 16384,
                   reuse: Optional[CompiledForest] = None,
                   stage: bool = False) -> CompiledForest:
    """Lower ``booster``'s forest into a :class:`CompiledForest`.

    Tree selection matches ``Booster.predict`` (``start_iteration`` /
    ``num_iteration`` in boosting rounds; <=0 means all remaining).
    ``reuse``: a previous compilation whose device buffers the new
    model may take over (the hot-swap path) — after this call the
    reused forest is dead and must not predict again. ``stage=True``
    keeps the arrays on the HOST (no HBM touched); call
    :meth:`CompiledForest.attach` to upload later — the daemon stages
    on the watcher thread and attaches on the batcher worker.
    """
    trees = booster._models
    K = booster.num_model_per_iteration()
    total_iters = len(trees) // max(K, 1)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    num_iteration = max(0, min(num_iteration,
                               total_iters - start_iteration))
    lo = start_iteration * K
    hi = (start_iteration + num_iteration) * K
    sel = trees[lo:hi]
    host = stack_trees(sel, device=False) if sel else None
    model_id = _model_digest(host) if host is not None else "empty"
    n_features = booster.num_feature()
    if stage:
        stacked = None
    elif reuse is not None:
        reuse.adopt(host)
        stacked = reuse._stacked
        reuse._stacked = None        # ownership moves to the new forest
        reuse._dead = True           # reuse must raise, not serve zeros
    elif host is not None:
        stacked = jax.tree_util.tree_map(jnp.asarray, host)
    else:
        stacked = None
    cf = CompiledForest(
        stacked, num_class=K, n_features=n_features,
        objective_str=booster._objective_str,
        avg_output=booster._avg_output,
        num_iteration=max(1, num_iteration), lo=lo, hi=hi,
        total_trees=len(trees), model_id=model_id,
        min_bucket=min_bucket, max_batch_rows=max_batch_rows)
    if stage:
        cf._host = host
    return cf
