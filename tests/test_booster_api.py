"""Booster API breadth: categorical splits, missing handling, rf,
continued training, refit, plotting (model: reference
tests/python_package_test/test_engine.py / test_basic.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from tests.conftest import make_synthetic_binary


def _logloss(p, y):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))


def test_categorical_feature_roundtrip(tmp_path):
    rs = np.random.RandomState(3)
    n = 600
    X = np.column_stack([rs.randint(0, 8, n).astype(float), rs.randn(n)])
    y = (np.isin(X[:, 0], [1, 3, 5]).astype(float) * 2 + 0.3 * X[:, 1]
         + 0.2 * rs.randn(n) > 1).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y, categorical_feature=[0]),
                    num_boost_round=10)
    pred = bst.predict(X)
    assert ((pred > 0.5) == y).mean() > 0.85
    f = tmp_path / "cat.txt"
    bst.save_model(str(f))
    assert "cat_threshold" in f.read_text()
    pred2 = lgb.Booster(model_file=str(f)).predict(X)
    np.testing.assert_allclose(pred, pred2, atol=1e-6)


def test_zero_as_missing_consistency():
    rs = np.random.RandomState(4)
    X = rs.randn(800, 3)
    mask = rs.rand(800) < 0.4
    X[mask, 0] = 0.0
    y = np.where(mask, 0.0, 3.0 * X[:, 0]) + 0.05 * rs.randn(800)
    bst = lgb.train({"objective": "regression", "num_leaves": 31,
                     "verbose": -1, "zero_as_missing": True},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    pred = bst.predict(X)
    assert np.mean((pred[mask] - y[mask]) ** 2) < 0.1


def test_constant_label_boost_from_average(tmp_path):
    rs = np.random.RandomState(0)
    X = rs.randn(200, 4)
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    lgb.Dataset(X, label=np.full(200, 5.0)),
                    num_boost_round=2)
    np.testing.assert_allclose(bst.predict(X[:5]), 5.0)
    f = tmp_path / "const.txt"
    bst.save_model(str(f))
    np.testing.assert_allclose(
        lgb.Booster(model_file=str(f)).predict(X[:5]), 5.0)


def test_rf_mode_save_load(tmp_path):
    X, y = make_synthetic_binary(n=900, f=8)
    dtrain = lgb.Dataset(X[:700], label=y[:700])
    dvalid = lgb.Dataset(X[700:], label=y[700:], reference=dtrain)
    evals = {}
    bst = lgb.train({"objective": "binary", "boosting": "rf",
                     "bagging_freq": 1, "bagging_fraction": 0.7,
                     "num_leaves": 15, "verbose": -1,
                     "metric": "binary_logloss"},
                    dtrain, num_boost_round=4, valid_sets=[dvalid],
                    callbacks=[lgb.record_evaluation(evals)])
    pred = bst.predict(X[700:])
    # recorded valid metric must match metric recomputed from predict()
    assert abs(evals["valid_0"]["binary_logloss"][-1]
               - _logloss(pred, y[700:])) < 1e-3
    f = tmp_path / "rf.txt"
    bst.save_model(str(f))
    assert "average_output" in f.read_text()
    np.testing.assert_allclose(
        lgb.Booster(model_file=str(f)).predict(X[700:]), pred, atol=1e-6)


def test_continued_training(tmp_path):
    X, y = make_synthetic_binary(n=700, f=6)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1}
    b10 = lgb.train(params, lgb.Dataset(X, label=y), 6)
    f = tmp_path / "m.txt"
    b10.save_model(str(f))
    cont = lgb.train(params, lgb.Dataset(X, label=y), 6,
                     init_model=str(f))
    scratch = lgb.train(params, lgb.Dataset(X, label=y), 12)
    assert cont.num_trees() == 12
    assert abs(_logloss(cont.predict(X), y)
               - _logloss(scratch.predict(X), y)) < 0.02
    # in-memory Booster as init_model
    cont2 = lgb.train(params, lgb.Dataset(X, label=y), 3, init_model=b10)
    assert cont2.num_trees() == 9


def test_refit_adapts_to_new_labels():
    X, y = make_synthetic_binary(n=500, f=5)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, lgb.Dataset(X, label=y), 8)
    flipped = 1.0 - y
    refitted = bst.refit(X, flipped, decay_rate=0.0)
    assert _logloss(refitted.predict(X), flipped) < 0.5
    assert _logloss(bst.predict(X), flipped) > 1.0
    # same-data refit keeps quality
    same = bst.refit(X, y, decay_rate=0.0)
    assert abs(_logloss(same.predict(X), y)
               - _logloss(bst.predict(X), y)) < 1e-3


def test_cv_stratified_seed_changes_folds():
    X, y = make_synthetic_binary(n=600, f=5)
    params = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    r1 = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=3,
                nfold=3, seed=1)
    r2 = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=3,
                nfold=3, seed=2)
    key = list(r1.keys())[0]
    assert r1[key][-1] != r2[key][-1]


def test_plotting_smoke():
    import matplotlib
    matplotlib.use("Agg")
    X, y = make_synthetic_binary(n=300, f=5)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "metric": "auc", "verbose": -1},
                    lgb.Dataset(X, label=y), 5,
                    valid_sets=[lgb.Dataset(X[:100], label=y[:100])],
                    callbacks=[lgb.record_evaluation(evals)])
    assert lgb.plot_importance(bst) is not None
    assert lgb.plot_metric(evals) is not None
    used = int(np.argmax(bst.feature_importance()))
    assert lgb.plot_split_value_histogram(bst, used) is not None


def test_predict_wrong_feature_count_raises():
    X, y = make_synthetic_binary(n=200, f=5)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1}, lgb.Dataset(X, label=y), 2)
    with pytest.raises(lgb.LightGBMError):
        bst.predict(np.zeros((3, 9)))


def test_zero_boost_rounds():
    X, y = make_synthetic_binary(n=200, f=5)
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    lgb.Dataset(X, label=y), 0)
    assert bst.num_trees() == 0
    np.testing.assert_allclose(bst.predict(X[:3]), 0.0)


def test_arrow_table_ingest():
    pa = pytest.importorskip("pyarrow")
    rs = np.random.RandomState(0)
    Xn = rs.randn(600, 3)
    y = (Xn[:, 0] > 0).astype(float)
    table = pa.table({f"f{i}": Xn[:, i] for i in range(3)})
    d = lgb.Dataset(table, label=y)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, d, num_boost_round=4)
    assert bst.feature_name() == ["f0", "f1", "f2"]
    ref = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, lgb.Dataset(Xn, label=y),
                    num_boost_round=4)
    np.testing.assert_allclose(bst.predict(Xn[:50]), ref.predict(Xn[:50]),
                               rtol=1e-6)


def test_sequence_ingest_matches_dense():
    rs = np.random.RandomState(1)
    X = rs.randn(900, 4)
    y = (X[:, 1] > 0).astype(float)

    class ArrSeq(lgb.Sequence):
        batch_size = 128

        def __init__(self, arr):
            self.arr = arr

        def __getitem__(self, idx):
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    d = lgb.Dataset([ArrSeq(X[:400]), ArrSeq(X[400:])], label=y)
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, d, num_boost_round=4)
    ref = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, lgb.Dataset(X, label=y),
                    num_boost_round=4)
    np.testing.assert_allclose(bst.predict(X[:50]), ref.predict(X[:50]),
                               rtol=1e-6)


def test_streaming_push_rows():
    rs = np.random.RandomState(2)
    X = rs.randn(1000, 5)
    y = (X[:, 0] + 0.3 * X[:, 2] > 0).astype(float)
    ds = lgb.Dataset.init_streaming(1000, 5,
                                    params={"verbosity": -1})
    # out-of-order batches with metadata, like the reference's
    # LGBM_DatasetPushRowsWithMetadata streaming tests
    ds.push_rows(X[600:], start_row=600, label=y[600:])
    ds.push_rows(X[:600], start_row=0, label=y[:600])
    ds.mark_finished()
    bst = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, ds, num_boost_round=4)
    ref = lgb.train({"objective": "binary", "verbosity": -1,
                     "num_leaves": 7}, lgb.Dataset(X, label=y),
                    num_boost_round=4)
    np.testing.assert_allclose(bst.predict(X[:50]), ref.predict(X[:50]),
                               rtol=1e-6)


def test_streaming_push_incomplete_raises():
    ds = lgb.Dataset.init_streaming(100, 3, params={"verbosity": -1})
    ds.push_rows(np.zeros((40, 3)), start_row=0)
    with pytest.raises(lgb.LightGBMError, match="unpushed"):
        ds.mark_finished()


def test_single_row_predict_matches_batch():
    """Single-row prediction (the reference's fast single-row path,
    tests/cpp_tests/test_single_row.cpp pattern): a [1, F] predict must
    equal the matching row of a batch predict, for raw score, leaf
    index, and contributions."""
    X, y = make_synthetic_binary(n=1500, f=7, seed=23)
    X[::11, 2] = np.nan
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=8)
    batch = bst.predict(X[:32])
    batch_raw = bst.predict(X[:32], raw_score=True)
    batch_leaf = bst.predict(X[:32], pred_leaf=True)
    batch_contrib = bst.predict(X[:32], pred_contrib=True)
    for i in (0, 7, 11, 31):
        row = X[i:i + 1]
        np.testing.assert_allclose(bst.predict(row), batch[i:i + 1],
                                   rtol=1e-7)
        np.testing.assert_allclose(bst.predict(row, raw_score=True),
                                   batch_raw[i:i + 1], rtol=1e-7)
        np.testing.assert_array_equal(
            bst.predict(row, pred_leaf=True), batch_leaf[i:i + 1])
        np.testing.assert_allclose(
            bst.predict(row, pred_contrib=True),
            batch_contrib[i:i + 1], rtol=1e-6, atol=1e-9)
