# tpulint fixture: TPL001 negative — every lax loop is jit-reachable.
# No EXPECT lines: the engine must report nothing here.
import functools

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def decorated(xs):
    def body(i, acc):
        return acc + xs[i]
    return lax.fori_loop(0, xs.shape[0], body, jnp.float32(0.0))


def _impl(xs):
    """Only entered through the module-level jit wrapper below and the
    decorated function above -> derived jit-reachable."""
    def body(carry, x):
        return carry + x, None
    total, _ = lax.scan(body, jnp.float32(0.0), xs)
    return decorated(xs) + total


wrapped = jax.jit(_impl)


@functools.partial(jax.jit, static_argnames=("n",))
def partial_decorated(xs, n):
    def helper(ys):
        def body(i, acc):
            return acc + ys[i]
        return lax.fori_loop(0, n, body, jnp.float32(0.0))
    # helper is referenced only from this traced body
    return helper(xs)
