// Fast delimited-text parser — the native data-loader component.
//
// Re-design of the reference's C++ parsing stack
// (/root/reference/src/io/parser.cpp CSVParser/TSVParser +
// include/LightGBM/utils/text_reader.h + the vendored
// fast_double_parser): one OpenMP pass over an mmap-style buffer,
// line ranges split per thread, std::from_chars for float decoding.
// Exposed through plain C symbols consumed via ctypes
// (lightgbm_tpu/utils/native.py) — no pybind11 dependency.
//
// Layout contract: the caller allocates out[n_rows * n_cols] float64;
// unparseable / empty cells become NaN (the reference's missing-value
// convention for dense text loads).

#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <limits>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Count data rows and detect the column count + delimiter.
// Returns 0 on success. delim_out: ',', '\t' or ' '.
int ltpu_sniff(const char* buf, int64_t len, int skip_header,
               int64_t* rows_out, int64_t* cols_out, char* delim_out) {
  int64_t pos = 0;
  if (skip_header) {
    while (pos < len && buf[pos] != '\n') pos++;
    if (pos < len) pos++;
  }
  // find first non-empty line for delimiter + column sniffing
  int64_t line_start = pos;
  while (line_start < len) {
    int64_t line_end = line_start;
    while (line_end < len && buf[line_end] != '\n') line_end++;
    if (line_end > line_start + 1) break;
    line_start = line_end + 1;
  }
  if (line_start >= len) return 1;
  int64_t line_end = line_start;
  char delim = ' ';
  while (line_end < len && buf[line_end] != '\n') {
    if (buf[line_end] == '\t') delim = '\t';
    else if (buf[line_end] == ',' && delim != '\t') delim = ',';
    line_end++;
  }
  int64_t cols = 1;
  for (int64_t i = line_start; i < line_end; ++i) {
    if (delim == ' ' ? (buf[i] == ' ' || buf[i] == '\t')
                     : buf[i] == delim) {
      cols++;
      if (delim == ' ')  // collapse runs of whitespace
        while (i + 1 < line_end &&
               (buf[i + 1] == ' ' || buf[i + 1] == '\t')) i++;
    }
  }
  int64_t rows = 0;
  for (int64_t i = pos; i < len; ++i)
    if (buf[i] == '\n' && i > pos && buf[i - 1] != '\n') rows++;
  if (len > pos && buf[len - 1] != '\n') rows++;  // unterminated last line
  *rows_out = rows;
  *cols_out = cols;
  *delim_out = delim;
  return 0;
}

static inline double parse_cell(const char* s, const char* e) {
  while (s < e && (*s == ' ' || *s == '\t')) s++;
  while (e > s && (*(e - 1) == ' ' || *(e - 1) == '\r')) e--;
  if (s >= e) return std::numeric_limits<double>::quiet_NaN();
  double v;
  auto res = std::from_chars(s, e, v);
  if (res.ec != std::errc()) {
    // from_chars rejects leading '+' and inf/nan spellings; fall back
    if ((e - s) >= 3 && (s[0] == 'n' || s[0] == 'N'))
      return std::numeric_limits<double>::quiet_NaN();
    char tmp[64];
    size_t m = static_cast<size_t>(e - s);
    if (m >= sizeof(tmp)) m = sizeof(tmp) - 1;
    std::memcpy(tmp, s, m);
    tmp[m] = 0;
    char* endp = nullptr;
    v = std::strtod(tmp, &endp);
    if (endp == tmp) return std::numeric_limits<double>::quiet_NaN();
  }
  return v;
}

// Parse the whole buffer into out[rows * cols] (row-major). Rows with
// fewer cells get NaN tails; extra cells are ignored.
// Returns the number of parsed rows.
int64_t ltpu_parse_dense(const char* buf, int64_t len, int skip_header,
                         char delim, int64_t rows, int64_t cols,
                         double* out) {
  int64_t pos = 0;
  if (skip_header) {
    while (pos < len && buf[pos] != '\n') pos++;
    if (pos < len) pos++;
  }
  // collect line offsets (serial, cheap) then parse cells in parallel
  std::vector<int64_t> starts;
  starts.reserve(static_cast<size_t>(rows) + 1);
  int64_t i = pos;
  while (i < len && static_cast<int64_t>(starts.size()) < rows) {
    int64_t le = i;
    while (le < len && buf[le] != '\n') le++;
    if (le > i) starts.push_back(i);
    i = le + 1;
  }
  const int64_t n = static_cast<int64_t>(starts.size());
  const bool ws = (delim == ' ');
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t r = 0; r < n; ++r) {
    int64_t s = starts[static_cast<size_t>(r)];
    int64_t e = s;
    while (e < len && buf[e] != '\n') e++;
    double* row = out + r * cols;
    int64_t c = 0;
    int64_t cs = s;
    for (int64_t k = s; k <= e && c < cols; ++k) {
      bool is_delim = (k == e) ||
          (ws ? (buf[k] == ' ' || buf[k] == '\t') : buf[k] == delim);
      if (!is_delim) continue;
      row[c++] = parse_cell(buf + cs, buf + k);
      if (ws)  // collapse whitespace runs
        while (k + 1 <= e && k + 1 < len &&
               (buf[k + 1] == ' ' || buf[k + 1] == '\t')) k++;
      cs = k + 1;
    }
    for (; c < cols; ++c)
      row[c] = std::numeric_limits<double>::quiet_NaN();
  }
  return n;
}

}  // extern "C"
