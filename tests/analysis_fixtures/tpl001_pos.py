# tpulint fixture: TPL001 positive — eager lax loops with no jit entry.
# An `# EXPECT: <RULE>` comment pins a finding (by rule id + line
# number) on the line that FOLLOWS it; tests/test_static_analysis.py
# asserts exact equality. Fixtures are never imported, only parsed.
import jax
import jax.numpy as jnp
from jax import lax


def eager_sum(xs):
    def body(i, acc):
        return acc + xs[i]
    # EXPECT: TPL001
    return lax.fori_loop(0, xs.shape[0], body, jnp.float32(0.0))


def eager_scan(xs):
    def body(carry, x):
        return carry + x, None
    # EXPECT: TPL001
    total, _ = jax.lax.scan(body, jnp.float32(0.0), xs)
    return total


def mixed_entry(xs):
    """Jitted by the wrapper below, but ALSO called eagerly from
    driver() — a mixed-entry function is not jit-only, so its loop can
    still dispatch eagerly."""
    def body(i, acc):
        return acc + xs[i]
    # EXPECT: TPL001
    return lax.fori_loop(0, xs.shape[0], body, jnp.float32(0.0))


mixed_jit = jax.jit(mixed_entry)


def driver(xs):
    return mixed_entry(xs)
