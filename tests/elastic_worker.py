"""Worker for the distributed chaos tests
(test_distributed_resilience.py) and the launch-supervisor end-to-end
proof.

Run as one rank of a ``python -m lightgbm_tpu launch`` world (or
spawned directly by a test): all wiring comes from the environment —

- ``LIGHTGBM_TPU_COORDINATOR`` / ``LIGHTGBM_TPU_NUM_PROCS`` /
  ``LIGHTGBM_TPU_RANK`` — picked up by a bare ``init_distributed()``,
- ``LIGHTGBM_TPU_CHECKPOINT`` — auto-checkpoint + auto-resume,
- ``LIGHTGBM_TPU_TELEMETRY`` — JSONL event stream (rank 0 writes),
- ``LIGHTGBM_TPU_FAULT_INJECT`` (+ ``LIGHTGBM_TPU_FAULT_RANK``) —
  rank_kill / stall_rank / init_refuse chaos,
- ``LIGHTGBM_TPU_COLLECTIVE_TIMEOUT`` — watchdog deadline.

Each rank loads its half of a fixed dataset through
``distributed_dataset`` (bin-mapper sync + row allgather over the host
transport) and trains the replicated model with the serial learner —
each process computes on its own devices, and the cross-rank surface
is exactly the host-level sync points the watchdog guards. Rank 0
saves the model; every rank prints ``INIT_RETRIES=<n>`` after joining
and ``rank <r> DONE`` on success. Any LightGBMError (a watchdog abort)
prints ``WORKER ABORT: <msg>`` and hard-exits 13 — ``os._exit``, so a
hung collective left on a daemon thread can never block process
death.

Usage: python elastic_worker.py <outdir> [num_rounds]
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

outdir = sys.argv[1]
num_rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 8

from lightgbm_tpu.parallel.distributed import init_distributed  # noqa: E402

init_distributed()   # supervisor env (or single-process no-op)

from lightgbm_tpu.obs.registry import registry  # noqa: E402

print(f"INIT_RETRIES={int(registry.counter('init_retries').value)}",
      flush=True)

import jax  # noqa: E402
import numpy as np  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.basic import LightGBMError  # noqa: E402
from lightgbm_tpu.parallel import spmd  # noqa: E402

rank = jax.process_index()
nproc = jax.process_count()

rs = np.random.RandomState(7)
n, f = 600, 5
X = rs.randn(n, f)
y = X @ rs.randn(f) + 0.05 * rs.randn(n)
shard = n // max(nproc, 1)
lo, hi = rank * shard, (rank + 1) * shard

try:
    ds = spmd.distributed_dataset(X[lo:hi], label=y[lo:hi],
                                  params={"verbosity": -1})
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "min_data_in_leaf": 5, "seed": 3,
                     "verbosity": -1}, ds, num_boost_round=num_rounds)
except LightGBMError as e:
    print(f"WORKER ABORT: {e}", flush=True)
    os._exit(13)

if rank == 0:
    bst.save_model(os.path.join(outdir, "model_elastic.txt"))
print(f"rank {rank} DONE iterations={bst.current_iteration()}",
      flush=True)
# skip jax.distributed atexit teardown: with peers already dead it can
# block on the coordination service instead of exiting
sys.stdout.flush()
os._exit(0)
