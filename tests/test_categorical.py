"""Categorical feature training (the reference's categorical split path:
feature_histogram.cpp FindBestThresholdCategoricalInner, tree.h
SplitCategorical; behavioral spec mirrored from
tests/python_package_test/test_engine.py categorical tests)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_data(n=3000, seed=0):
    rs = np.random.RandomState(seed)
    cat = rs.randint(0, 30, n).astype(np.float64)
    num = rs.randn(n)
    y = ((cat < 10).astype(float) * 2.0 + 0.3 * num
         + 0.1 * rs.randn(n) > 1.0).astype(np.float64)
    return np.column_stack([cat, num]), y


def test_categorical_splits_learned():
    X, y = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "verbose": -1},
                    ds, num_boost_round=20)
    model = bst.model_to_string()
    assert "num_cat=1" in model or "num_cat=2" in model
    pred = bst.predict(X)
    assert ((pred > 0.5) == y).mean() > 0.9


def test_categorical_model_roundtrip():
    X, y = _cat_data(seed=1)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-6)


def test_categorical_onehot_path():
    """Features with <= max_cat_to_onehot bins use the one-hot scan."""
    rs = np.random.RandomState(2)
    n = 2000
    cat = rs.randint(0, 4, n).astype(np.float64)
    y = (cat == 2).astype(np.float64)
    ds = lgb.Dataset(cat.reshape(-1, 1), label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbose": -1},
                    ds, num_boost_round=5)
    pred = bst.predict(cat.reshape(-1, 1))
    assert ((pred > 0.5) == y).mean() > 0.99
    # one-hot: the winning left set is a single category
    t0 = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert t0["decision_type"] == "=="


def test_categorical_unseen_category_routes_right():
    X, y = _cat_data(seed=3)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    Xu = X.copy()
    Xu[:5, 0] = 999  # category never seen in training
    pred = bst.predict(Xu)
    assert np.isfinite(pred).all()


def test_categorical_valid_set_scoring_consistent():
    """Binned valid-set scoring must match raw-feature prediction."""
    X, y = _cat_data(seed=4)
    Xv, yv = _cat_data(seed=5)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    dv = ds.create_valid(Xv, label=yv)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "metric": "binary_logloss", "verbose": -1},
                    ds, num_boost_round=10, valid_sets=[dv],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(evals)])
    from lightgbm_tpu.metrics import create_metrics
    pred = bst.predict(Xv)
    eps = 1e-15
    p = np.clip(pred, eps, 1 - eps)
    ll = -np.mean(yv * np.log(p) + (1 - yv) * np.log(1 - p))
    assert abs(evals["v"]["binary_logloss"][-1] - ll) < 1e-5


def test_pandas_categorical_dtype():
    pd = pytest.importorskip("pandas")
    X, y = _cat_data(seed=6)
    df = pd.DataFrame({"c": pd.Categorical([f"g{int(v)}" for v in X[:, 0]]),
                       "x": X[:, 1]})
    ds = lgb.Dataset(df, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    pred = bst.predict(df)
    assert ((pred > 0.5) == y).mean() > 0.85
