# tpulint fixture: TPL008 negative — the same lifecycle load
# generator as pipeline/tpl008_pos.py with every worker/supervisor-
# shared field guarded by one common lock, and the blocking socket
# work outside it. No EXPECT lines.
import threading

_published = []
_published_lock = threading.Lock()


class LoadGenerator:
    def __init__(self):
        self._lock = threading.Lock()
        self.attempts = 0
        self.ok = 0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _send_request(self):
        return True                   # stands in for socket I/O

    def _run(self):
        while True:
            got = self._send_request()   # blocking work OUTSIDE
            with self._lock:
                self.attempts += 1
                if got:
                    self.ok += 1

    def snapshot(self):
        with self._lock:
            return {"attempts": self.attempts, "ok": self.ok}


def _poll_publications():
    with _published_lock:
        _published.append("model.txt")


def watch_publications():
    threading.Thread(target=_poll_publications).start()
    with _published_lock:
        return list(_published)
