# tpulint fixture: TPL008 positive — a telemetry recorder whose drain
# thread mutates fields no lock guards. This is exactly the
# "delete the lock around a thread-shared field" acceptance shape:
# obs/tpl008_neg.py is the same recorder WITH the locks, and removing
# them must re-surface these findings.
import threading

_events = []          # module-global fault queue


class Recorder:
    def __init__(self):
        self.pending = []
        self._drainer = threading.Thread(target=self._drain,
                                         daemon=True)
        self._drainer.start()

    def _drain(self):
        while True:
            # EXPECT: TPL008
            self.pending.clear()

    def snapshot(self):
        return list(self.pending)


def _worker():
    # EXPECT: TPL008
    _events.append({"event": "fault"})


# tpulint: threadsafe
def _pragma_without_reason_is_not_a_justification():
    # EXPECT: TPL008
    _events.append({"event": "fault"})


def start_workers():
    threading.Thread(target=_worker).start()
    threading.Thread(
        target=_pragma_without_reason_is_not_a_justification).start()
    return list(_events)
