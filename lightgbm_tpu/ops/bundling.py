"""Exclusive Feature Bundling (EFB).

Re-design of the reference's FeatureGroup construction
(/root/reference/include/LightGBM/feature_group.h:26; greedy bundling in
src/io/dataset.cpp FindGroups/FastFeatureBundling): mutually-exclusive
sparse features are merged into one physical column so that histogram
construction, the partition stream, and the per-leaf histogram cache all
scale with the number of BUNDLES instead of raw features — the "EFB"
half of what makes LightGBM "light", mapped onto the TPU's rectangular
[G, B] histogram layout.

Bundle layout (matching the shared-zero-bin convention the reference
uses when every member's most-frequent bin is bin 0):
- bundle position 0      = "every member at its default (zero) bin"
- member i with nb_i bins occupies positions [off_i, off_i + nb_i - 2],
  storing its nonzero bins 1..nb_i-1; off accumulates (nb_i - 1).
- a member's bin-0 statistics are reconstructed at search time as
  ``leaf_total - sum(member range)`` — the FixHistogram /
  most_freq_bin reconstruction (dataset.h:760) reborn as pure algebra.

Eligibility: numerical features whose zero maps to bin 0 (the shared
default). Members MAY carry a NaN bin: its mapped position is excluded
from threshold scans and routed by the learned default direction, just
like the plain search's dual missing-direction scan. Merges tolerate up
to ``total_sample_cnt / 10000`` conflicting rows per bundle — the
reference's single_val_max_conflict_cnt budget (src/io/dataset.cpp:115)
— so near-exclusive features (Allstate/Bosch-class sparse one-hots)
still bundle; at zero conflicts the bundled model stays EXACTLY the
unbundled model. Bundling is built host-side once at Dataset
construction (numpy), exactly like the reference's loader-time
grouping. Categorical members remain excluded: their membership-mask
splits would need per-member one-hot semantics in remapped bundle
space, and the reference's accuracy story for EFB is about sparse
numerical one-hots.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

__all__ = ["BundleInfo", "build_bundles"]

# per-bundle conflict budget as a fraction of sampled rows
# (single_val_max_conflict_cnt = total_sample_cnt / 10000,
# src/io/dataset.cpp:115)
MAX_CONFLICT_FRACTION = 1.0 / 10000
class BundleInfo(NamedTuple):
    """Host-side bundling result handed to the grower."""
    groups: List[List[int]]       # member feature ids per bundle
    bundle_of: np.ndarray         # [F] i32 — feature -> bundle
    offset_of: np.ndarray         # [F] i32 — feature -> first position
                                  #   of bin 1 inside its bundle
    is_direct: np.ndarray         # [F] bool — singleton stored verbatim
    bins_bundled: np.ndarray      # [n, G] u8/u16 bundle columns
    num_positions: int            # B: max positions over bundles
    member_at: np.ndarray         # [G, B] i32 — candidate position ->
                                  #   member feature id (-1: none)
    tloc_at: np.ndarray           # [G, B] i32 — position -> member-local
                                  #   threshold bin
    end_at: np.ndarray            # [G, B] i32 — flat [G*B] index of the
                                  #   member's last position (range end)
    nanpos_at: np.ndarray         # [G, B] i32 — flat [G*B] index of the
                                  #   member-at-position's NaN-bin
                                  #   position (-1: member has none)
    nan_at: np.ndarray            # [G, B] bool — position IS a member's
                                  #   NaN bin (excluded from scans)


def _eligible(mappers, bins: np.ndarray,
              max_cat_onehot: int = 4) -> np.ndarray:
    """Features that may enter a multi-member bundle.

    Numerical: zero maps to bin 0 (the shared default); a NaN bin is
    allowed (handled by the dual-direction scan + nanpos/nan_at
    plumbing). MissingType.ZERO members stay excluded: their missing
    bin IS the shared default-0 position, which the per-member
    NaN-position algebra (nan bin = last bin) cannot represent — they
    remain direct singletons with the plain dual scan.

    Categorical (round 5, FindGroups is type-blind — dataset.cpp):
    bin 0 is the most-frequent category by construction
    (_find_bin_categorical sorts by count), so position 0 = "member at
    its dominant category" and the nonzero bins are the tail
    categories. Only features in the ONE-HOT regime
    (num_bins <= max_cat_to_onehot) may join: their bundled candidate
    set (one-hot per category, incl. the reconstructed dominant) is
    EXACTLY the plain search's — wider cats use the sorted-subset scan
    and stay direct singleton columns, where that scan runs verbatim."""
    from .binning import BinType, MissingType
    F = bins.shape[1]
    ok = np.zeros(F, bool)
    for j, m in enumerate(mappers):
        if m.num_bins < 2:
            continue
        if m.bin_type == BinType.CATEGORICAL:
            ok[j] = m.num_bins <= max_cat_onehot
            continue
        if m.missing_type == MissingType.ZERO:
            continue
        if int(m.value_to_bin(np.zeros(1))[0]) != 0:
            continue
        ok[j] = True
    return ok


def build_bundles(bins: np.ndarray, mappers,
                  max_positions: int = 255,
                  sample_rows: int = 200_000,
                  sparse_threshold: float = 0.8,
                  seed: int = 0,
                  max_cat_onehot: int = 4) -> Optional[BundleInfo]:
    """Greedy bundling over the binned matrix.

    Merges tolerate up to ``S * MAX_CONFLICT_FRACTION`` conflicting
    sampled rows per bundle (the reference's
    single_val_max_conflict_cnt, dataset.cpp:115) — the later member's
    value wins on a conflict row, a bounded approximation. With zero
    actual conflicts the bundled model is EXACTLY the unbundled model,
    split for split. Returns None when bundling would not reduce the
    column count.

    Args:
      bins: [n, F] host bin matrix.
      mappers: per-feature BinMappers (eligibility checks).
      max_positions: cap on a bundle's total positions (keeps the
        device matrix in its narrow dtype and the histogram rectangle
        small).
      sparse_threshold: a feature joins a bundle only if at least this
        fraction of sampled rows sits in its zero bin.
    """
    n, F = bins.shape
    if F < 3:
        return None
    rs = np.random.RandomState(seed)
    idx = rs.choice(n, size=min(n, sample_rows), replace=False) \
        if n > sample_rows else np.arange(n)
    # feature-major contiguous nonzero masks: the greedy loop reads
    # per-FEATURE vectors thousands of times, and a column slice of
    # the row-major [S, F] matrix is one cache miss per element — at
    # Allstate width (4228 features) that turned bundling into
    # minutes of pointer-chasing (measured >9 min at S=32K; ~seconds
    # after this transpose). Masks live BIT-PACKED (u8 words +
    # popcount): 8x smaller and AND/OR run on words, which is what
    # makes the larger default sample affordable. The sample must be
    # LARGE because sampled-conflict counts gate merges: at S=32K a
    # truly-conflicting cross-block pair (E[joint] ~ 2 rows) shows
    # zero sampled conflicts ~14% of the time, so every group absorbs
    # foreign members early and the packing shatters (measured 659
    # bundles on Allstate-shaped data vs ~33 at S=200K). 200K matches
    # the reference's bin_construct_sample_cnt default it feeds
    # FindGroups with (dataset_loader.cpp).
    nzT = np.ascontiguousarray((bins[idx] != 0).T)   # [F, S] bool
    density = nzT.mean(axis=1)
    eligible = _eligible(mappers, bins, max_cat_onehot) \
        & (density <= 1 - sparse_threshold)
    S = nzT.shape[1]
    nzP = np.packbits(nzT, axis=1)                   # [F, ceil(S/8)] u8
    del nzT

    from .binning import BinType
    nbins = np.array([m.num_bins for m in mappers], np.int64)
    is_cat = np.array([m.bin_type == BinType.CATEGORICAL
                       for m in mappers], bool)
    # a categorical member reserves ONE extra position: its last
    # category's one-hot candidate is a real split (not the degenerate
    # all-left cut a numeric member parks there), so the next member's
    # shared t=0 slot must not overwrite it
    member_width = nbins - 1 + is_cat.astype(np.int64)
    # per-bundle conflict budget (single_val_max_conflict_cnt,
    # src/io/dataset.cpp:115): rows where two members are both nonzero
    # are tolerated up to this count — the later member's value wins in
    # the shared column, a bounded approximation the reference accepts
    conflict_budget = int(S * MAX_CONFLICT_FRACTION)
    popcounts = np.bitwise_count(nzP).sum(axis=1)
    order = np.argsort(-popcounts)          # dense first (reference)
    groups: List[List[int]] = []
    group_nz: List[np.ndarray] = []         # aggregated nonzero masks
    group_pos: List[int] = []               # occupied positions (1 + ...)
    group_conf: List[int] = []              # conflicts spent so far
    for j in order:
        if not eligible[j]:
            continue
        placed = False
        width = int(member_width[j])
        nz_j = nzP[j]
        # first-fit over ALL groups, zero-conflict placements first.
        # The reference samples at most max_search_group=100 random
        # candidates (dataset.cpp:113) as a 100K+-feature scale
        # heuristic, but sampling can miss the one compatible group
        # and shatter the packing (measured: a 160-block one-hot
        # matrix went 186 -> 1853 columns); the exact scan is cheap
        # because eligibility already filters to sparse features.
        # Zero-conflict-first matters on block-sparse data: a greedy
        # single pass lets a cross-block feature spend a group's tiny
        # conflict budget (S/10000) early, locking out the group's own
        # block and shattering the packing (measured: Allstate-shaped
        # 4228 features packed to 719 bundles single-pass vs ~33 with
        # exclusive-first placement).
        cnts = []
        for gi in range(len(groups)):
            if group_pos[gi] + width > max_positions:
                cnts.append(None)
                continue
            cnt = int(np.bitwise_count(group_nz[gi] & nz_j).sum())
            cnts.append(cnt)
            if cnt == 0:
                placed = True
                break
        if not placed:
            for gi, cnt in enumerate(cnts):
                if cnt is not None and \
                        group_conf[gi] + cnt <= conflict_budget:
                    placed = True
                    break
        if placed:
            groups[gi].append(int(j))
            group_nz[gi] |= nz_j
            group_pos[gi] += width
            group_conf[gi] += (cnt if cnt else 0)
        if not placed and width + 1 <= max_positions:
            groups.append([int(j)])
            group_nz.append(nz_j.copy())
            group_pos.append(1 + width)
            group_conf.append(0)

    # group-consolidation pass: per-feature first-fit still fragments
    # block-sparse data (same-block features scatter into whichever
    # small mixed group shows zero SAMPLED conflicts by luck, and those
    # groups then close to everything as E[conflicts] grows with
    # membership — measured: Allstate-shaped 4228 features ended at
    # 659 groups). Merging whole GROUPS by their aggregated masks
    # collapses same-block fragments (exact zero conflicts), again
    # zero-conflict placements first; merged groups share the zero
    # position, so positions add as (pos - 1).
    cons: List[List[int]] = []
    cons_nz: List[np.ndarray] = []
    cons_pos: List[int] = []
    cons_conf: List[int] = []
    for g, gnz, gpos, gconf in zip(groups, group_nz, group_pos,
                                   group_conf):
        placed = False
        cnts2 = []
        for ci in range(len(cons)):
            if cons_pos[ci] + gpos - 1 > max_positions:
                cnts2.append(None)
                continue
            cnt = int(np.bitwise_count(cons_nz[ci] & gnz).sum())
            cnts2.append(cnt)
            if cnt == 0 and cons_conf[ci] + gconf <= conflict_budget:
                placed = True
                break
        if not placed:
            for ci, cnt in enumerate(cnts2):
                if cnt is not None and \
                        cons_conf[ci] + gconf + cnt <= conflict_budget:
                    placed = True
                    break
        if placed:
            cons[ci].extend(g)
            cons_nz[ci] |= gnz
            cons_pos[ci] += gpos - 1
            cons_conf[ci] += gconf + (cnt if cnt else 0)
        else:
            cons.append(list(g))
            cons_nz.append(gnz.copy())
            cons_pos.append(gpos)
            cons_conf.append(gconf)
    groups = cons

    multi = [g for g in groups if len(g) > 1]
    if not multi:
        return None
    bundled_members = {j for g in multi for j in g}
    # singletons: everything else, stored verbatim ("direct" layout)
    final_groups = multi + [[j] for j in range(F)
                            if j not in bundled_members]
    G = len(final_groups)
    if G >= F:
        return None

    bundle_of = np.zeros(F, np.int32)
    offset_of = np.zeros(F, np.int32)
    is_direct = np.zeros(F, bool)
    widths = []
    for gi, g in enumerate(final_groups):
        if len(g) == 1:
            j = g[0]
            bundle_of[j] = gi
            offset_of[j] = 0
            is_direct[j] = True
            widths.append(int(nbins[j]))
        else:
            off = 1
            for j in g:
                bundle_of[j] = gi
                offset_of[j] = off
                off += int(member_width[j])
            widths.append(off)
    B = max(widths)

    dtype = np.uint8 if B <= 256 else np.uint16
    # one blocked transpose instead of F strided column walks over the
    # row-major [n, F] matrix (each of those is a cache miss per
    # element at Allstate width); outT is also what the engine
    # ultimately wants (it uploads bins_bundled.T)
    binsT = np.ascontiguousarray(bins.T)    # [F, n]
    outT = np.zeros((G, n), dtype)
    for gi, g in enumerate(final_groups):
        if len(g) == 1:
            outT[gi] = binsT[g[0]].astype(dtype)
        else:
            col = np.zeros(n, np.int64)
            for j in g:
                bj = binsT[j].astype(np.int64)
                sel = bj != 0
                col[sel] = offset_of[j] + bj[sel] - 1
            outT[gi] = col.astype(dtype)
    out = outT.T

    from .binning import MissingType
    # cat members carry NO nan metadata: their NaN bin is just another
    # category (the plain cat search has no dual missing-direction
    # scan), routed by the membership mask like any other bin
    nanb = np.array([int(nbins[j]) - 1
                     if (mappers[j].missing_type == MissingType.NAN
                         and not is_cat[j])
                     else -1 for j in range(F)], np.int64)
    member_at = np.full((G, B), -1, np.int32)
    tloc_at = np.zeros((G, B), np.int32)
    end_at = np.zeros((G, B), np.int32)
    nanpos_at = np.full((G, B), -1, np.int32)
    nan_at = np.zeros((G, B), bool)
    for gi, g in enumerate(final_groups):
        if len(g) == 1:
            j = g[0]
            nb = int(nbins[j])
            member_at[gi, :nb] = j
            tloc_at[gi, :nb] = np.arange(nb)
            end_at[gi, :nb] = gi * B + nb - 1
            if nanb[j] >= 0:
                nanpos_at[gi, :nb] = gi * B + int(nanb[j])
                nan_at[gi, int(nanb[j])] = True
        else:
            for j in g:
                off = int(offset_of[j])
                nb = int(nbins[j])
                # candidate positions off-1 .. off+nb-2 carry member
                # thresholds t = 0 .. nb-1 (p = off-1 is the t=0
                # "defaults left, nonzero right" cut; the previous
                # member's own slot there is its degenerate all-left
                # candidate, which validity pruning always discards)
                lo, hi = off - 1, off + nb - 2
                member_at[gi, lo:hi + 1] = j
                tloc_at[gi, lo:hi + 1] = np.arange(nb)
                end_at[gi, lo:hi + 1] = gi * B + off + nb - 2
                # ALWAYS overwrite nanpos over the member's candidate
                # range: position off-1 is shared with the PREVIOUS
                # member's last slot, and if that member carried a NaN
                # bin its stale nanpos/nan metadata would otherwise
                # make this member's t=0 candidate misattribute the
                # neighbor's NaN mass (round-4 review finding)
                if nanb[j] >= 0:
                    # the member's NaN bin maps to its LAST position
                    p_nan = off + int(nanb[j]) - 1
                    nanpos_at[gi, lo:hi + 1] = gi * B + p_nan
                    nan_at[gi, p_nan] = True
                else:
                    nanpos_at[gi, lo:hi + 1] = -1
    return BundleInfo(final_groups, bundle_of, offset_of, is_direct,
                      out, B, member_at, tloc_at, end_at,
                      nanpos_at, nan_at)
