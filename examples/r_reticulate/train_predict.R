# Train/predict from R via reticulate.
#
# The R-package de-scope (docs/PARITY.md §2.7): the reference's
# R-package/ is a 1:1 FFI wrapper over the C API (R-package/src/
# lightgbm_R.cpp), an ABI boundary this framework does not have.
# R users reach the FULL surface through reticulate instead — this
# script is the working recipe.
#
# Requirements: install.packages("reticulate"); a python with jax.
# Run:  LIGHTGBM_TPU_PATH=/root/repo Rscript train_predict.R

library(reticulate)

# point reticulate at the repo (or pip-install the package and skip);
# default = two directories above this script
script_dir <- tryCatch(
  dirname(normalizePath(sys.frame(1)$ofile)),
  error = function(e) dirname(normalizePath(
    sub("--file=", "", grep("--file=", commandArgs(FALSE), value = TRUE)[1]))))
repo <- Sys.getenv("LIGHTGBM_TPU_PATH",
                   unset = normalizePath(file.path(script_dir, "..", "..")))
sys <- import("sys")
sys$path$insert(0L, repo)

# force the host backend when no TPU is attached (optional)
os <- import("os")
os$environ$setdefault("JAX_PLATFORMS", "cpu")

lgb <- import("lightgbm_tpu")
np <- import("numpy")

# -- data: R matrix -> numpy happens automatically ---------------------
set.seed(7)
n <- 2000L; f <- 10L
X <- matrix(rnorm(n * f), nrow = n)
coef <- rnorm(f)
y <- as.numeric((X %*% coef + 0.3 * rnorm(n)) > 0)

X_train <- X[1:1500, ]; y_train <- y[1:1500]
X_valid <- X[1501:n, ]; y_valid <- y[1501:n]

# -- Dataset / train: same API as Python -------------------------------
dtrain <- lgb$Dataset(X_train, label = y_train)
dvalid <- lgb$Dataset(X_valid, label = y_valid, reference = dtrain)

record <- dict()
params <- dict(objective = "binary", metric = "auc",
               num_leaves = 31L, learning_rate = 0.1, verbosity = -1L)
bst <- lgb$train(params, dtrain, num_boost_round = 30L,
                 valid_sets = list(dvalid),
                 callbacks = list(lgb$record_evaluation(record)))

auc <- record[["valid_0"]][["auc"]]
cat(sprintf("final valid AUC: %.4f\n", auc[[length(auc)]]))

# -- predict + save/load round-trip ------------------------------------
pred <- bst$predict(X_valid)
cat(sprintf("pred[1:3]: %s\n", paste(round(pred[1:3], 4), collapse = " ")))

model_path <- file.path(tempdir(), "model.txt")
bst$save_model(model_path)
bst2 <- lgb$Booster(model_file = model_path)
pred2 <- bst2$predict(X_valid)
stopifnot(max(abs(pred - pred2)) < 1e-6)

# -- sklearn-style wrapper also works ----------------------------------
clf <- lgb$LGBMClassifier(n_estimators = 10L, num_leaves = 15L,
                          verbosity = -1L)
clf$fit(X_train, y_train)
acc <- mean((clf$predict(X_valid) > 0.5) == (y_valid > 0.5))
cat(sprintf("sklearn-wrapper accuracy: %.3f\n", acc))

cat("R-reticulate example OK\n")
