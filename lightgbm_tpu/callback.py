"""Training callbacks.

Own design covering the behavioral surface of the reference's callback
module (/root/reference/python-package/lightgbm/callback.py:109,183,254,
278,454): the ``CallbackEnv`` protocol, before/after-iteration ordering,
and ``EarlyStopException`` unwinding are kept contract-compatible so user
callbacks written for the reference port unchanged, but the machinery
here is organized around a per-slot ``_MetricTracker`` instead of the
reference's parallel best_* lists.

Evaluation tuples are ``(dataset_name, metric_name, value,
higher_is_better)`` — or with ``, stdv`` appended for cv aggregates.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from .utils.log import log_info, log_warning

__all__ = ["EarlyStopException", "CallbackEnv", "log_evaluation",
           "record_evaluation", "reset_parameter", "early_stopping",
           "telemetry", "checkpoint"]


class EarlyStopException(Exception):
    """Raised by the early-stopping callback to unwind the train loop."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _render(entry: Sequence, show_stdv: bool = True) -> str:
    """One evaluation tuple -> 'data's metric: value[ + stdv]'."""
    text = f"{entry[0]}'s {entry[1]}: {entry[2]:g}"
    if show_stdv and len(entry) > 4:
        text += f" + {entry[4]:g}"
    return text


def _render_all(entries: Sequence[Sequence], show_stdv: bool = True) -> str:
    return "\t".join(_render(e, show_stdv) for e in entries)


@dataclass(eq=False)
class _LogEvaluation:
    """Print the evaluation line every ``period`` iterations."""
    period: int = 1
    show_stdv: bool = True
    order: int = 10
    before_iteration: bool = False

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        if (env.iteration + 1) % self.period == 0:
            log_info(f"[{env.iteration + 1}]\t"
                     f"{_render_all(env.evaluation_result_list, self.show_stdv)}")


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    return _LogEvaluation(period=period, show_stdv=show_stdv)


@dataclass(eq=False)
class _RecordEvaluation:
    """Append every metric value into a user-provided nested dict."""
    eval_result: Dict
    order: int = 20
    before_iteration: bool = False

    def __post_init__(self):
        if not isinstance(self.eval_result, dict):
            raise TypeError("eval_result should be a dictionary")

    def __call__(self, env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            self.eval_result.clear()
        for entry in env.evaluation_result_list:
            data_slot = self.eval_result.setdefault(
                entry[0], collections.OrderedDict())
            data_slot.setdefault(entry[1], []).append(entry[2])
            if len(entry) > 4:
                data_slot.setdefault(f"{entry[1]}-stdv", []).append(entry[4])


def record_evaluation(eval_result: Dict) -> Callable:
    return _RecordEvaluation(eval_result)


@dataclass(eq=False)
class _ResetParameter:
    """Per-iteration parameter schedule: list lookup or callable."""
    schedule: Dict[str, Any]
    order: int = 10
    before_iteration: bool = True

    def __call__(self, env: CallbackEnv) -> None:
        step = env.iteration - env.begin_iteration
        changed: Dict[str, Any] = {}
        for name, spec in self.schedule.items():
            if isinstance(spec, list):
                if len(spec) != env.end_iteration - env.begin_iteration:
                    raise ValueError(
                        f"Length of list {name!r} has to equal to "
                        "'num_boost_round'.")
                value = spec[step]
            elif callable(spec):
                value = spec(step)
            else:
                raise ValueError(
                    "Only list and callable values are supported as a "
                    "mapping from boosting round index to new parameter "
                    "value.")
            if value != env.params.get(name, None):
                changed[name] = value
        if changed:
            if "learning_rate" in changed and env.model is not None:
                env.model._engine._shrinkage = changed["learning_rate"]
            env.params.update(changed)


def reset_parameter(**kwargs) -> Callable:
    return _ResetParameter(kwargs)


@dataclass(eq=False)
class _MetricTracker:
    """Best-so-far state for one (dataset, metric) evaluation slot."""
    higher_is_better: bool
    min_delta: float
    best_value: float = 0.0
    best_iteration: int = 0
    best_entries: Optional[List] = None

    def __post_init__(self):
        self.best_value = float("-inf") if self.higher_is_better \
            else float("inf")

    def improved(self, value: float) -> bool:
        if self.higher_is_better:
            return value > self.best_value + self.min_delta
        return value < self.best_value - self.min_delta


@dataclass(eq=False)
class _EarlyStopping:
    """Stop when no tracked slot improves for ``stopping_rounds`` rounds.

    Train-set slots (the Booster's own train data, and cv train-fold
    aggregates) update their trackers but never trigger a stop — only
    held-out data counts, matching the reference's gating.
    """
    stopping_rounds: int
    first_metric_only: bool = False
    verbose: bool = True
    min_delta: Union[float, List[float]] = 0.0
    order: int = 30
    before_iteration: bool = False
    enabled: bool = True
    trackers: List[_MetricTracker] = field(default_factory=list)
    _primary_metric: str = ""

    def __post_init__(self):
        if self.stopping_rounds <= 0:
            raise ValueError("stopping_rounds should be greater than zero.")

    def _deltas_per_slot(self, entries: Sequence) -> List[float]:
        metric_count = len({e[1] for e in entries})
        dataset_count = len(entries) // max(metric_count, 1)
        if isinstance(self.min_delta, list):
            if len(self.min_delta) != metric_count:
                raise ValueError(
                    "Must provide a single value for min_delta or as many "
                    "as metrics.")
            if self.first_metric_only and self.verbose:
                log_info(f"Using only {self.min_delta[0]} as early "
                         "stopping min_delta.")
            return self.min_delta * dataset_count
        if self.min_delta < 0:
            raise ValueError("Early stopping min_delta must be "
                             "non-negative.")
        return [self.min_delta] * (dataset_count * metric_count)

    def _start(self, env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        deltas = self._deltas_per_slot(env.evaluation_result_list)
        self.trackers = [
            _MetricTracker(higher_is_better=bool(entry[3]), min_delta=d)
            for entry, d in zip(env.evaluation_result_list, deltas)]
        self._primary_metric = \
            env.evaluation_result_list[0][1].split(" ")[-1]

    def _is_train_slot(self, env: CallbackEnv, entry: Sequence) -> bool:
        metric_tail = entry[1].split(" ")
        if entry[0] == "cv_agg" and metric_tail[0] == "train":
            return True
        if env.model is not None and entry[0] == env.model._train_data_name:
            return True
        return False

    def _stop(self, tracker: _MetricTracker, reason: str) -> None:
        if self.verbose:
            log_info(f"{reason}, best iteration is:\n"
                     f"[{tracker.best_iteration + 1}]\t"
                     f"{_render_all(tracker.best_entries)}")
            if self.first_metric_only:
                log_info(f"Evaluated only: {self._primary_metric}")
        raise EarlyStopException(tracker.best_iteration,
                                 tracker.best_entries)

    def __call__(self, env: CallbackEnv) -> None:
        if env.iteration == env.begin_iteration:
            self._start(env)
        if not self.enabled:
            return
        last_round = env.iteration == env.end_iteration - 1
        for tracker, entry in zip(self.trackers,
                                  env.evaluation_result_list):
            if tracker.best_entries is None \
                    or tracker.improved(entry[2]):
                tracker.best_value = entry[2]
                tracker.best_iteration = env.iteration
                tracker.best_entries = list(env.evaluation_result_list)
            if self.first_metric_only \
                    and entry[1].split(" ")[-1] != self._primary_metric:
                continue
            if self._is_train_slot(env, entry):
                continue
            if env.iteration - tracker.best_iteration \
                    >= self.stopping_rounds:
                self._stop(tracker, "Early stopping")
            if last_round:
                self._stop(tracker, "Did not meet early stopping")


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True,
                   min_delta: Union[float, List[float]] = 0.0) -> Callable:
    return _EarlyStopping(stopping_rounds=stopping_rounds,
                          first_metric_only=first_metric_only,
                          verbose=verbose, min_delta=min_delta)


@dataclass(eq=False)
class _Telemetry:
    """Stream one JSONL telemetry event per iteration (obs/recorder.py).

    Runs after evaluation/logging (order 40) so the event carries the
    iteration's eval results. The train loop calls ``attach`` before the
    first iteration and ``finish`` on exit (including the early-stop
    unwind, where an after-callback raising means this one may never
    fire for the final iteration).
    """
    recorder: Any
    order: int = 40
    before_iteration: bool = False

    def attach(self, model) -> None:
        self.recorder.attach(model)

    def finish(self) -> None:
        self.recorder.close()

    def __call__(self, env: CallbackEnv) -> None:
        if env.model is not None:
            self.recorder.attach(env.model)
        self.recorder.record_iteration(env.iteration,
                                       env.evaluation_result_list)


def telemetry(path: str, registry=None) -> Callable:
    """Record per-iteration run telemetry to ``path`` (JSONL).

    Equivalent to setting ``LIGHTGBM_TPU_TELEMETRY=<path>``; summarize
    the output with ``python -m lightgbm_tpu stats <path>``.
    """
    from .obs import TelemetryRecorder
    return _Telemetry(TelemetryRecorder(path, registry=registry))


def checkpoint(directory: str, every_n_iters: int = 1,
               keep: int = 3) -> Callable:
    """Atomic periodic training snapshots into ``directory`` with
    auto-resume via ``train(..., resume_from=directory)`` — the
    fault-tolerance callback (resilience/checkpoint.py). Equivalent to
    setting ``LIGHTGBM_TPU_CHECKPOINT=<directory>``; inspect snapshots
    with ``python -m lightgbm_tpu checkpoints <directory>``."""
    from .resilience.checkpoint import checkpoint as _checkpoint
    return _checkpoint(directory, every_n_iters=every_n_iters, keep=keep)
