"""Phase timing — the USE_TIMETAG subsystem re-imagined for JAX.

The reference compiles a global ``Common::Timer`` + RAII ``FunctionTimer``
into every hot-path phase and logs a sorted per-label wall-time table at
process exit (/root/reference/include/LightGBM/utils/common.h:973-1057,
instrumentation points listed in SURVEY.md §5). On TPU the device runs
asynchronously from Python, so two complementary mechanisms are provided:

- ``Timer`` / ``timed(label)``: host wall-clock aggregation per label.
  Because dispatch is async, a label's time only reflects device work if
  the section itself synchronizes (the train loop's per-iteration sync
  points do). Enabled with env ``LIGHTGBM_TPU_TIMETAG=1`` or
  ``Timer.enable()``; ``Timer.log_summary()`` prints the sorted table and
  ``Timer.snapshot()`` returns it machine-readable (the telemetry
  recorder diffs consecutive snapshots into per-iteration phase times).
- inside an active ``trace_to`` capture, every timed section also enters
  a ``jax.profiler.TraceAnnotation`` so the phases show up as named
  spans in the tensorboard/xplane view even when host timing is off.

When neither timing nor tracing is active, ``timed`` yields immediately:
no jax import, no TraceAnnotation construction, no clock reads — the
instrumented loop must cost nothing with telemetry off.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager, nullcontext
from typing import Dict, Iterator

from .log import log_info

__all__ = ["Timer", "timed", "trace_to"]

# number of live trace_to() captures; touched under Timer._lock
_tracing = 0


class Timer:
    """Process-global label -> accumulated wall seconds."""

    _acc: Dict[str, float] = defaultdict(float)
    _cnt: Dict[str, int] = defaultdict(int)
    _enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")
    # callbacks can fire from user threads and the recorder snapshots
    # concurrently with additions
    _lock = threading.Lock()

    @classmethod
    def enable(cls, on: bool = True) -> None:
        cls._enabled = on

    @classmethod
    def enabled(cls) -> bool:
        return cls._enabled

    @classmethod
    def add(cls, label: str, seconds: float) -> None:
        with cls._lock:
            cls._acc[label] += seconds
            cls._cnt[label] += 1

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._acc.clear()
            cls._cnt.clear()

    @classmethod
    def summary(cls) -> Dict[str, float]:
        with cls._lock:
            return dict(cls._acc)

    @classmethod
    def snapshot(cls) -> Dict[str, Dict[str, float]]:
        """Consistent ``{label: {"total": seconds, "count": n}}`` copy."""
        with cls._lock:
            return {label: {"total": sec, "count": cls._cnt[label]}
                    for label, sec in cls._acc.items()}

    @classmethod
    def log_summary(cls) -> None:
        snap = cls.snapshot()
        if not snap:
            return
        grand = sum(v["total"] for v in snap.values()) or 1.0
        log_info("lightgbm_tpu phase timings (host wall):")
        log_info(f"  {'label':32s} {'total s':>10s} {'count':>8s} "
                 f"{'mean ms':>10s} {'%':>6s}")
        for label, v in sorted(snap.items(), key=lambda kv: -kv[1]["total"]):
            sec, cnt = v["total"], int(v["count"])
            mean_ms = sec / cnt * 1e3 if cnt else 0.0
            log_info(f"  {label:32s} {sec:10.3f} {cnt:8d} "
                     f"{mean_ms:10.3f} {100.0 * sec / grand:6.1f}")


# shared no-op context: the disabled cost of a timed() section is one
# flag check + returning this singleton, against the seed's per-call
# jax import + TraceAnnotation + generator frame
_NULL = nullcontext()

# jax resolved once on first active use — not at module import (utils
# load before the backend is configured) and not per call
_jax = None


def _get_jax():
    global _jax
    if _jax is None:
        import jax
        _jax = jax
    return _jax


@contextmanager
def _timed_active(label: str) -> Iterator[None]:
    jax = _get_jax()

    with jax.profiler.TraceAnnotation(label):
        if not Timer._enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            Timer.add(label, time.perf_counter() - t0)


# resolved lazily: the jax profiler's session slot, so timed() also
# annotates traces started OUTSIDE trace_to() via the Python API
# (jax.profiler.start_trace / jax.profiler.trace). Captures triggered
# against jax.profiler.start_server happen in C++ and are NOT visible
# here — use trace_to() or LIGHTGBM_TPU_TIMETAG=1 for those. False-y
# sentinel until jax is imported; None forever if the private attr
# moved (degrade to library-only detection, never break).
_profile_state = False


def _external_trace_active() -> bool:
    global _profile_state
    if _profile_state is False:
        import sys
        if "jax" not in sys.modules:
            return False
        try:
            from jax._src.profiler import _profile_state as st
            _profile_state = st
        except Exception:
            _profile_state = None
    if _profile_state is None:
        return False
    try:
        return _profile_state.profile_session is not None
    except Exception:
        return False


def timed(label: str):
    """Time a phase and, inside a trace capture (ours or an externally
    started jax profiler session), annotate it. A strict no-op (shared
    null context) when neither timing nor tracing is active."""
    if not Timer._enabled and not _tracing \
            and not _external_trace_active():
        return _NULL
    return _timed_active(label)


@contextmanager
def trace_to(log_dir: str) -> Iterator[None]:
    """Capture a full device trace (jax.profiler.trace wrapper) — view
    with tensorboard's profile plugin, or any xplane.pb reader. While a
    capture is live, ``timed`` sections emit TraceAnnotation spans even
    with host timing off."""
    global _tracing
    jax = _get_jax()

    with Timer._lock:
        _tracing += 1
    try:
        with jax.profiler.trace(log_dir):
            yield
    finally:
        with Timer._lock:
            _tracing -= 1
