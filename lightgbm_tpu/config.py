"""Parameter/config system for the TPU-native GBDT framework.

Mirrors the semantics of the reference's annotated ``struct Config``
(/root/reference/include/LightGBM/config.h, src/io/config.cpp): a single flat
parameter namespace with ~150 aliases, bounds checks, and a canonical string
form — re-designed as a Python dataclass that is the single source of truth
for parameter names, aliases, defaults and constraints.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

__all__ = ["Config", "ALIASES", "resolve_params", "choose_param_value"]


# ---------------------------------------------------------------------------
# Alias table: alias -> canonical name.
# Mirrors the alias map generated into config_auto.cpp in the reference
# (and _ConfigAliases in python-package/lightgbm/basic.py).
# ---------------------------------------------------------------------------
ALIASES: Dict[str, str] = {
    # core
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective",
    "app": "objective",
    "application": "objective",
    "loss": "objective",
    "boosting_type": "boosting",
    "boost": "boosting",
    "train": "data",
    "train_data": "data",
    "train_data_file": "data",
    "data_filename": "data",
    "test": "valid",
    "valid_data": "valid",
    "valid_data_file": "valid",
    "test_data": "valid",
    "test_data_file": "valid",
    "valid_filenames": "valid",
    "num_trees": "num_iterations",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_rounds": "num_iterations",
    "nrounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "n_iter": "num_iterations",
    "n_estimators": "num_iterations",
    "max_iter": "num_iterations",
    "shrinkage_rate": "learning_rate",
    "eta": "learning_rate",
    "num_leaf": "num_leaves",
    "max_leaves": "num_leaves",
    "max_leaf": "num_leaves",
    "max_leaf_nodes": "num_leaves",
    "tree": "tree_learner",
    "tree_type": "tree_learner",
    "tree_learner_type": "tree_learner",
    "num_thread": "num_threads",
    "nthread": "num_threads",
    "nthreads": "num_threads",
    "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed",
    "random_state": "seed",
    # learning control
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_samples_leaf": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction",
    "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction",
    "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "sub_feature_bynode": "feature_fraction_bynode",
    "colsample_bynode": "feature_fraction_bynode",
    "extra_tree": "extra_trees",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "n_iter_no_change": "early_stopping_round",
    "max_tree_output": "max_delta_step",
    "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "l1_regularization": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "lambda": "lambda_l2",
    "l2_regularization": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints",
    "monotone_constraint": "monotone_constraints",
    "monotonic_cst": "monotone_constraints",
    "monotone_constraining_method": "monotone_constraints_method",
    "mc_method": "monotone_constraints_method",
    "monotone_splits_penalty": "monotone_penalty",
    "ms_penalty": "monotone_penalty",
    "mc_penalty": "monotone_penalty",
    "feature_contrib": "feature_contri",
    "fc": "feature_contri",
    "fp": "feature_contri",
    "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename",
    "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename",
    "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    # dataset
    "linear_trees": "linear_tree",
    "max_bins": "max_bin",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "data_seed": "data_random_seed",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "sparse": "is_enable_sparse",
    "is_enable_bundle": "enable_bundle",
    "bundle": "enable_bundle",
    "is_pre_partition": "pre_partition",
    "two_round_loading": "two_round",
    "use_two_round_loading": "two_round",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "group_id": "group_column",
    "query_column": "group_column",
    "query": "group_column",
    "query_id": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "cat_feature": "categorical_feature",
    "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_save_binary": "save_binary",
    "is_save_binary_file": "save_binary",
    # predict
    "is_predict_raw_score": "predict_raw_score",
    "predict_rawscore": "predict_raw_score",
    "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index",
    "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib",
    "contrib": "predict_contrib",
    # objective
    "num_classes": "num_class",
    "unbalance": "is_unbalance",
    "unbalanced_sets": "is_unbalance",
    "objective_seed": "seed",
    "ndcg_eval_at": "eval_at",
    "ndcg_at": "eval_at",
    "map_eval_at": "eval_at",
    "map_at": "eval_at",
    # metric
    "metrics": "metric",
    "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric",
    "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    # network
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "port": "local_listen_port",
    "machine_list_filename": "machine_list_file",
    "machine_list": "machine_list_file",
    "mlist": "machine_list_file",
    "workers": "machines",
    "nodes": "machines",
    # io
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "predict_name": "output_result",
    "prediction_name": "output_result",
    "pred_name": "output_result",
    "name_pred": "output_result",
    "init_score_filename": "initscore_filename",
    "init_score_file": "initscore_filename",
    "init_score": "initscore_filename",
    "input_init_score": "initscore_filename",
}

_OBJECTIVE_ALIASES = {
    "regression": "regression",
    "regression_l2": "regression",
    "l2": "regression",
    "mean_squared_error": "regression",
    "mse": "regression",
    "l2_root": "regression",
    "root_mean_squared_error": "regression",
    "rmse": "regression",
    "regression_l1": "regression_l1",
    "l1": "regression_l1",
    "mean_absolute_error": "regression_l1",
    "mae": "regression_l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass",
    "softmax": "multiclass",
    "multiclassova": "multiclassova",
    "multiclass_ova": "multiclassova",
    "ova": "multiclassova",
    "ovr": "multiclassova",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "rank_xendcg": "rank_xendcg",
    "xendcg": "rank_xendcg",
    "xe_ndcg": "rank_xendcg",
    "xe_ndcg_mart": "rank_xendcg",
    "xendcg_mart": "rank_xendcg",
    "none": "custom",
    "null": "custom",
    "custom": "custom",
    "na": "custom",
}


def canonical_objective(name: str) -> str:
    key = name.strip().lower()
    if key not in _OBJECTIVE_ALIASES:
        raise ValueError(f"Unknown objective: {name}")
    return _OBJECTIVE_ALIASES[key]


def choose_param_value(main_param_name: str, params: Dict[str, Any],
                       default_value: Any = None) -> Dict[str, Any]:
    """Resolve aliases for one parameter in-place-ish (returns a copy).

    Mirrors ``_choose_param_value`` (reference python-package basic.py:612).
    Precedence: the canonical name wins; otherwise first alias found.
    """
    params = dict(params)
    if main_param_name in params:
        pass
    else:
        for alias, main in ALIASES.items():
            if main == main_param_name and alias in params:
                params[main_param_name] = params.pop(alias)
                break
        else:
            if default_value is not None:
                params[main_param_name] = default_value
    # drop remaining aliases for this param
    for alias, main in list(ALIASES.items()):
        if main == main_param_name and alias in params:
            params.pop(alias)
    return params


def resolve_params(params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Map every aliased key to its canonical name. Canonical keys win."""
    out: Dict[str, Any] = {}
    if not params:
        return out
    aliased: Dict[str, Any] = {}
    for k, v in params.items():
        canon = ALIASES.get(k, k)
        if canon == k:
            out[k] = v
        else:
            aliased.setdefault(canon, v)
    for k, v in aliased.items():
        out.setdefault(k, v)
    return out


def _parse_list(v: Any, typ) -> list:
    if v is None:
        return []
    if isinstance(v, str):
        v = v.replace(";", ",")
        return [typ(x) for x in v.split(",") if x.strip() != ""]
    if isinstance(v, (list, tuple)):
        return [typ(x) for x in v]
    return [typ(v)]


_TRUE = {"true", "1", "yes", "on", "+", "t", "y"}
_FALSE = {"false", "0", "no", "off", "-", "f", "n"}


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return bool(v)
    s = str(v).strip().lower()
    if s in _TRUE:
        return True
    if s in _FALSE:
        return False
    raise ValueError(f"Cannot parse boolean from {v!r}")


@dataclass
class Config:
    """Canonical training configuration.

    Field set mirrors the reference's ``Config`` struct (config.h:39-1322);
    bounds (``check`` annotations in the reference) are enforced in
    ``__post_init__``.
    """

    # ---- core ----
    task: str = "train"
    objective: str = "regression"
    boosting: str = "gbdt"
    data_sample_strategy: str = "bagging"  # bagging | goss
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    # serial | feature | data | voting | auto. "auto" replaces the
    # static flag with the payload-model decision (parallel/comms.py
    # choose_parallel_mode): feature-parallel for replicable data,
    # data-parallel while one histogram reduction stays cheap at the
    # chosen hist_comm wire dtype, voting beyond (the reference's
    # Parallel-Learning-Guide table, measured instead of adjectival).
    tree_learner: str = "serial"
    num_threads: int = 0
    device_type: str = "tpu"  # cpu | tpu
    seed: Optional[int] = None
    deterministic: bool = False

    # ---- learning control ----
    force_col_wise: bool = False
    force_row_wise: bool = False
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    bagging_by_query: bool = False
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    extra_trees: bool = False
    extra_seed: int = 6
    early_stopping_round: int = 0
    early_stopping_min_delta: float = 0.0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    linear_lambda: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1  # dart
    max_drop: int = 50  # dart
    skip_drop: float = 0.5  # dart
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2  # goss
    other_rate: float = 0.1  # goss
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20  # voting parallel
    monotone_constraints: List[int] = field(default_factory=list)
    monotone_constraints_method: str = "basic"
    monotone_penalty: float = 0.0
    feature_contri: List[float] = field(default_factory=list)
    forcedsplits_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    path_smooth: float = 0.0
    interaction_constraints: Any = ""
    verbosity: int = 1
    input_model: str = ""
    output_model: str = "LightGBM_model.txt"
    saved_feature_importance_type: int = 0
    snapshot_freq: int = -1
    use_quantized_grad: bool = False
    num_grad_quant_bins: int = 4
    quant_train_renew_leaf: bool = False
    stochastic_rounding: bool = True
    # non-finite guard on gradients/hessians/fitted leaf values, fused
    # into the jitted boosting step (resilience/): "raise" fails fast
    # with a LightGBMError, "skip_tree" drops the poisoned iteration's
    # trees (they become no-op constants) and keeps training, "clamp"
    # replaces NaN/Inf with finite values and keeps the trees
    nonfinite_policy: str = "raise"
    # multi-iteration fused scan (docs/FUSED.md): trace N boosting
    # iterations into ONE lax.scan program with donated score/bagging
    # carries and a window-batched tree-pack fetch, deleting the
    # per-iteration dispatch + host round-trip from the hot loop.
    # "auto" (default) stays per-iteration until the Higgs-shaped
    # fused_iter_bench scan arm measures a win on chip
    # (LIGHTGBM_TPU_AUTO_SCAN_ITERS=N opts auto in for measurement;
    # LIGHTGBM_TPU_DISABLE_SCAN=1 is the kill switch). An explicit
    # integer N>1 enables windows of up to N iterations; the engine
    # shrinks windows to the next checkpoint/end-of-training boundary
    # and falls back to the per-iteration fused path for configs the
    # scan cannot carry (feature_fraction host RNG, GOSS/DART, valid
    # sets — see GBDTBooster._scan_ok)
    fused_scan_iters: Any = "auto"

    # ---- dataset ----
    linear_tree: bool = False
    max_bin: int = 255
    max_bin_by_feature: List[int] = field(default_factory=list)
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    data_random_seed: int = 1
    is_enable_sparse: bool = True
    enable_bundle: bool = True
    use_missing: bool = True
    zero_as_missing: bool = False
    feature_pre_filter: bool = True
    pre_partition: bool = False
    two_round: bool = False
    # out-of-core streaming ingestion (lightgbm_tpu/data/, docs/DATA.md):
    # rows per ingest chunk for the two-pass construct. 0 (default)
    # keeps in-memory inputs eager; chunked sources (RowChunkSource /
    # Sequence / generator factories) always stream and use this as
    # their chunk size when set. > 0 additionally streams CSV/TSV and
    # parquet paths chunk-by-chunk, so the dense float matrix never
    # exists and peak host memory scales with ingest_chunk_rows x
    # n_features (plus the bin_construct_sample_cnt sample), not with
    # dataset rows
    ingest_chunk_rows: int = 0
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: Any = ""
    forcedbins_filename: str = ""
    save_binary: bool = False
    precise_float_parser: bool = False
    parser_config_file: str = ""

    # ---- predict ----
    start_iteration_predict: int = 0
    num_iteration_predict: int = -1
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    predict_disable_shape_check: bool = False
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    output_result: str = "LightGBM_predict_result.txt"

    # ---- serve ----
    # production inference daemon (lightgbm_tpu/serve/,
    # docs/SERVING.md): micro-batching window in milliseconds — how
    # long the batcher waits for more requests before dispatching a
    # partial batch (0 = dispatch immediately)
    serve_batch_window_ms: float = 2.0
    # largest device batch (power of two); bigger requests are split,
    # smaller ones pad up to their power-of-two bucket so arbitrary
    # request sizes never recompile the predict program
    serve_max_batch_rows: int = 16384
    # smallest row bucket (power of two): requests below it pad to it,
    # bounding the jit cache at log2(max/min)+1 entries per model
    serve_min_bucket_rows: int = 16
    # pending-row budget: a submit that would exceed it is rejected
    # (backpressure) instead of growing an unbounded queue
    serve_queue_rows: int = 131072
    # seconds between {"event": "serve"} telemetry lines
    serve_stats_interval_sec: float = 10.0
    # seconds between polls of the hot-swap watch directory
    serve_watch_interval_sec: float = 1.0
    # load shedding (docs/SERVING.md "Overload policy"): soft backlog
    # threshold in pending rows — above it the batcher worker sheds
    # its OLDEST queued requests with a typed {"shed": true} reply
    # until the backlog is back under the threshold, so fresh arrivals
    # keep bounded latency instead of every caller timing out
    # together. 0 (default) disables; must stay below serve_queue_rows
    # (the hard admission wall) to ever fire
    serve_shed_queue_rows: int = 0
    # per-request latency budget in milliseconds: a queued request
    # that already waited longer is shed at dequeue time (its deadline
    # is blown; serving it would only steal capacity from requests
    # that can still meet theirs). 0 (default) disables
    serve_shed_p99_ms: float = 0.0
    # graceful-shutdown deadline in seconds: on SIGTERM or the
    # protocol `shutdown` command the daemon stops accepting, drains
    # already-accepted requests for up to this long, waits for the
    # replies to reach the wire, and only then closes the socket — a
    # supervised restart never drops an accepted request
    serve_shutdown_grace_sec: float = 15.0
    # replica autoscaling floor (resilience/autoscale.py,
    # docs/RESILIENCE.md "Autoscaling policy"): the fleet supervisor
    # never retires below this many replicas
    serve_min_replicas: int = 1
    # autoscaling ceiling: the fleet supervisor spawns replicas up to
    # this count on load (fleet QPS / p99 / shed signals) and retires
    # them — graceful drain, zero dropped in-flight requests — when
    # the load subsides. 0 (default) disables autoscaling (fixed
    # fleet)
    serve_max_replicas: int = 0
    # scale-up QPS threshold: scale up when the fleet-total QPS
    # exceeds this per active replica (0 disables the QPS signal)
    autoscale_up_qps: float = 0.0
    # scale-down QPS threshold: scale down only when the fleet-total
    # QPS would still stay under this per replica with one replica
    # FEWER. Keep it strictly below autoscale_up_qps — that gap is
    # the hysteresis band that stops the fleet flapping (0 disables
    # scale-down)
    autoscale_down_qps: float = 0.0
    # scale-up latency threshold: scale up when any replica's p99
    # exceeds this many milliseconds (0 disables the latency signal)
    autoscale_up_p99_ms: float = 0.0
    # cooldown seconds after ANY scaling action before the next
    # scale-up / scale-down may fire (the other half of hysteresis:
    # one load spike cannot double-scale between scrapes)
    autoscale_up_cooldown_sec: float = 5.0
    autoscale_down_cooldown_sec: float = 15.0

    # ---- observability (lightgbm_tpu/obs/; docs/OBSERVABILITY.md) ----
    # base port of the OpenMetrics /metrics HTTP endpoint
    # (obs/export.py): every process of a fleet exports its
    # MetricsRegistry at metrics_port + its rank (trainer ranks under
    # `launch`, serve replicas via `serve --metrics-port`, the
    # supervisors at the base port). 0 (default) disables the
    # endpoint; the LIGHTGBM_TPU_METRICS_PORT env var (exported by
    # the supervisors) overrides
    metrics_port: int = 0
    # seconds between fleet metric scrapes: the cadence at which the
    # `launch` fleet supervisor and the `pipeline` driver poll their
    # children's stats into {"event": "fleet"} telemetry records
    # (docs/OBSERVABILITY.md "Fleet events"). 0 disables scraping
    metrics_scrape_interval_sec: float = 5.0
    # distributed-tracing sample rate (obs/trace.py, docs/
    # OBSERVABILITY.md "Tracing"): the pipeline's load generator
    # originates a trace on every Nth request — the traced request
    # carries a {"trace": ...} protocol field and the serve replica
    # answers it with queue-wait / batch-window / dispatch / reply
    # spans, merged by `python -m lightgbm_tpu trace <dir>`.
    # 0 disables request-trace sampling (train/publish/swap spans are
    # always on — they cost one clock pair per iteration/publication)
    trace_sample_every: int = 16

    # ---- publish (resilience/publisher.py; docs/PIPELINE.md) ----
    # retry budget for one atomic model publication into the serve
    # watch directory (transient failures: full disk, slow rename,
    # injected publish_torn chaos)
    publish_retries: int = 5
    # base of the jittered exponential backoff between publish
    # retries (doubles per attempt, capped at 15 s, x[0.5, 1.5)
    # jitter — the init_distributed retry shape)
    publish_backoff_sec: float = 0.25
    # retention: after a successful publish, prune publications
    # beyond this many newest VALID manifests from the publish target
    # (atomic through the store; the currently-served and
    # last-known-good models are never pruned). 0 (default) keeps
    # everything
    publish_keep: int = 0
    # canary validation batch (docs/SERVING.md "Canary gate"): rows
    # embedded in each publication manifest together with the raw
    # scores the publishing model produced for them; a serve replica
    # scores them through its real compiled forest BEFORE swapping
    # and refuses the publication on mismatch. 0 disables the gate
    canary_rows: int = 8
    # absolute tolerance for canary raw-score agreement between the
    # publisher's booster and the replica's compiled forest
    canary_tol: float = 1e-3
    # publish transport target (resilience/store.py): "" (default)
    # publishes into the pipeline's local publish/ directory; a
    # "mem://<name>" spec (tests) or any ArtifactStore-shaped target
    # rides the same manifest-first protocol without a shared
    # filesystem
    publish_store: str = ""

    # ---- convert ----
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # ---- objective ----
    objective_seed: int = 5
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9  # huber / quantile
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    lambdarank_truncation_level: int = 30
    lambdarank_norm: bool = True
    label_gain: List[float] = field(default_factory=list)
    lambdarank_position_bias_regularization: float = 0.0

    # ---- metric ----
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # ---- network ----
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    machines: str = ""
    # deadline (seconds) for every host-level collective of a
    # multi-process run (resilience/watchdog.py): a rank that dies or
    # stalls mid-sync surfaces as a LightGBMError naming the stuck
    # collective instead of an infinite hang. 0 disables; the
    # LIGHTGBM_TPU_COLLECTIVE_TIMEOUT env var overrides
    collective_timeout_sec: float = 300.0

    # ---- tpu-specific (new; no reference analog) ----
    num_devices: int = 0  # 0 = use all visible devices for data-parallel
    hist_dtype: str = "float32"  # histogram accumulator dtype
    # histogram allreduce wire format for distributed training
    # (parallel/comms.py; docs/COLLECTIVES.md): f32 = exact psum |
    # int16 / int8 = EQuARX-style blockwise-quantized allreduce with
    # per-block f32 scales and an error-feedback residual carried
    # through the growth loop (split decisions stay bit-identical
    # across ranks; int8 cuts the dominant data-parallel histogram
    # payload ~4x) | auto = int16 once one f32 histogram reduction
    # crosses ~1 MiB, exact f32 below. Ignored by serial training,
    # feature-parallel (no histogram reduction) and quantized-gradient
    # histograms (already exact int32).
    hist_comm: str = "f32"
    # where the binned training matrix lives (parallel/placement.py;
    # docs/SHARDING.md): "host" keeps the classic host numpy copy and
    # uploads a device copy; "device" lays each rank's binned shard
    # DIRECTLY into its NamedSharding mesh slice
    # (jax.make_array_from_single_device_arrays) and frees the host
    # copy after the upload — no host ever holds the global binned
    # matrix (the gate on datasets whose binned form exceeds one
    # host). "auto" = device when a multi-device mesh is active on an
    # accelerator backend, host otherwise (CPU virtual-device worlds
    # keep host so eager consumers stay cheap; tests opt in
    # explicitly).
    shard_residency: str = "auto"
    # data-parallel split search (ops/grow.py GrowConfig.split_search;
    # docs/SHARDING.md): "gathered" allreduces the full [F, B, 2]
    # histogram and every device searches all features; "sharded"
    # reduce-scatters it so each device searches only its owned F/D
    # feature chunk and the per-device best SplitInfo records are
    # allreduced (the reference DataParallelTreeLearner's
    # ReduceScatter + SyncUpGlobalBestSplit) — post-reduction traffic
    # drops to a 1/D chunk + O(D) split records while split decisions
    # stay byte-identical. Applies to tree_learner=data meshes;
    # feature/voting already shard their searches. EFB-bundled runs
    # fall back to gathered (not covered yet).
    split_search: str = "gathered"
    sharding_axis: str = "data"  # mesh axis name for row sharding
    # histogram build strategy: auto|scatter|mxu|pallas. auto: nibble
    # matmul (MXU) on TPU and scatter-add on CPU; pallas: hand-tiled
    # TPU kernel accumulating the [F, B, 2] histogram in VMEM
    # (ops/pallas_hist.py; runs under the Pallas interpreter on CPU).
    # Flipping auto to pallas on TPU is gated on a measured iters/sec
    # win on the Higgs-shaped bench (LIGHTGBM_TPU_AUTO_PALLAS=1 opts
    # in; see docs/PALLAS.md). Falls back mxu -> scatter under the OOM
    # degradation ladder or when Pallas is unavailable.
    hist_method: str = "auto"
    # MXU histogram accumulation passes: default (single-pass bf16 input /
    # f32 accumulation — the reference GPU learner's single-precision
    # histogram choice, docs/GPU-Performance.rst:134-158) | high (3-pass)
    # | highest (6-pass f32 emulation)
    hist_precision: str = "default"
    # tree grower: compact (the flagship: leaf-wise, rows grouped by
    # leaf, per-split work ~ leaf size) | level (DEPTH-wise: the whole
    # frontier splits per step, histograms built in one batched
    # sibling-subtracting pass per level — O(rows) histogram work per
    # LEVEL instead of per split; trees are balanced-by-policy, so
    # they differ from leaf-wise trees whenever the leaf budget binds)
    # | masked (full-row masked histogram passes). "masked" is a
    # deliberately simple CORRECTNESS ORACLE
    # kept for differential testing (tests/test_grower_equivalence.py),
    # not a performance choice: every split pays O(n) histogram work,
    # and it lacks EFB / CEGB / interaction / forced splits /
    # path-smooth / bynode / quantized — configs needing those either
    # auto-upgrade to compact (quantized, forced, bynode, path-smooth;
    # see GBDTBooster.__init__) or raise NotImplementedError
    # (grow_tree_impl), and >50M row*leaf products raise outright.
    # "level" shares masked's feature gating (core set only).
    grower: str = "compact"
    # rows per streaming chunk in the compact grower's partition pass
    # (perf knob; power of two. Larger chunks amortize per-chunk fixed
    # costs but pay more window-tail padding and higher per-row sort
    # depth — 16384 measured best on v5e, benchmarks/PROFILE.md)
    chunk_rows: int = 16384
    # bulk-batching chunk size: the partition streams floor(cnt/
    # big_chunk_rows) big bodies per leaf window before the chunk_rows
    # tail (GrowConfig.big_chunk). Measured neutral-to-negative on v5e
    # (the body is throughput- not dispatch-bound); 0 (default) off.
    big_chunk_rows: int = 0

    # Unrecognized parameters are kept here (warned about, not fatal).
    extra: Dict[str, Any] = field(default_factory=dict)

    _BOUNDS = {
        "num_iterations": (0, None),
        "learning_rate": (0.0, None, "gt"),
        "num_leaves": (2, 131072),
        "max_bin": (2, None),
        "min_data_in_bin": (1, None),
        "bin_construct_sample_cnt": (1, None),
        "ingest_chunk_rows": (0, None),
        "min_data_in_leaf": (0, None),
        "min_sum_hessian_in_leaf": (0.0, None),
        "bagging_fraction": (0.0, 1.0, "gt"),
        "pos_bagging_fraction": (0.0, 1.0, "gt"),
        "neg_bagging_fraction": (0.0, 1.0, "gt"),
        "feature_fraction": (0.0, 1.0, "gt"),
        "feature_fraction_bynode": (0.0, 1.0, "gt"),
        "max_delta_step": (None, None),
        "lambda_l1": (0.0, None),
        "lambda_l2": (0.0, None),
        "linear_lambda": (0.0, None),
        "min_gain_to_split": (0.0, None),
        "drop_rate": (0.0, 1.0),
        "skip_drop": (0.0, 1.0),
        "top_rate": (0.0, 1.0),
        "other_rate": (0.0, 1.0),
        "max_cat_threshold": (1, None),
        "cat_l2": (0.0, None),
        "cat_smooth": (0.0, None),
        "max_cat_to_onehot": (1, None),
        "top_k": (1, None),
        "monotone_penalty": (0.0, None),
        "refit_decay_rate": (0.0, 1.0),
        "path_smooth": (0.0, None),
        "sigmoid": (0.0, None, "gt"),
        "alpha": (0.0, None, "gt"),
        "fair_c": (0.0, None, "gt"),
        "poisson_max_delta_step": (0.0, None, "gt"),
        "tweedie_variance_power": (1.0, 2.0),
        "lambdarank_truncation_level": (1, None),
        "num_class": (1, None),
        "scale_pos_weight": (0.0, None, "gt"),
        "num_grad_quant_bins": (2, None),
        "num_machines": (1, None),
        "collective_timeout_sec": (0.0, None),
        "serve_batch_window_ms": (0.0, None),
        "serve_max_batch_rows": (1, None),
        "serve_min_bucket_rows": (1, None),
        "serve_queue_rows": (1, None),
        "serve_stats_interval_sec": (0.0, None, "gt"),
        "serve_watch_interval_sec": (0.0, None, "gt"),
        "serve_shed_queue_rows": (0, None),
        "serve_shed_p99_ms": (0.0, None),
        "serve_shutdown_grace_sec": (0.0, None),
        "serve_min_replicas": (1, None),
        "serve_max_replicas": (0, None),
        "autoscale_up_qps": (0.0, None),
        "autoscale_down_qps": (0.0, None),
        "autoscale_up_p99_ms": (0.0, None),
        "autoscale_up_cooldown_sec": (0.0, None, "gt"),
        "autoscale_down_cooldown_sec": (0.0, None, "gt"),
        "publish_retries": (0, None),
        "publish_backoff_sec": (0.0, None),
        "publish_keep": (0, None),
        "canary_rows": (0, None),
        "canary_tol": (0.0, None, "gt"),
        "metrics_port": (0, 65535),
        "metrics_scrape_interval_sec": (0.0, None),
        "trace_sample_every": (0, None),
        "metric_freq": (1, None),
        "multi_error_top_k": (1, None),
    }

    def __post_init__(self) -> None:
        self.objective = canonical_objective(self.objective)
        if self.boosting in ("gbrt",):
            self.boosting = "gbdt"
        if self.boosting == "goss":
            # legacy spelling: boosting=goss means gbdt + goss sampling
            self.boosting = "gbdt"
            self.data_sample_strategy = "goss"
        if self.boosting == "random_forest":
            self.boosting = "rf"
        if self.boosting not in ("gbdt", "dart", "rf"):
            raise ValueError(f"Unknown boosting type: {self.boosting}")
        if self.data_sample_strategy not in ("bagging", "goss"):
            raise ValueError(
                f"Unknown data_sample_strategy: {self.data_sample_strategy}")
        if self.tree_learner not in ("serial", "feature", "data",
                                     "voting", "auto"):
            raise ValueError(f"Unknown tree_learner: {self.tree_learner}")
        if self.hist_comm not in ("f32", "int16", "int8", "auto"):
            raise ValueError(f"Unknown hist_comm: {self.hist_comm} "
                             "(expected f32, int16, int8 or auto)")
        if self.shard_residency not in ("auto", "host", "device"):
            raise ValueError(
                f"Unknown shard_residency: {self.shard_residency} "
                "(expected auto, host or device)")
        if self.split_search not in ("gathered", "sharded"):
            raise ValueError(
                f"Unknown split_search: {self.split_search} "
                "(expected gathered or sharded)")
        if self.monotone_constraints_method not in (
                "basic", "intermediate", "advanced"):
            raise ValueError(
                f"Unknown monotone_constraints_method: "
                f"{self.monotone_constraints_method}")
        if self.hist_method not in ("auto", "scatter", "mxu", "pallas"):
            raise ValueError(f"Unknown hist_method: {self.hist_method}")
        if self.grower not in ("compact", "masked", "level"):
            raise ValueError(f"Unknown grower: {self.grower}")
        if self.chunk_rows < 256 or (self.chunk_rows
                                     & (self.chunk_rows - 1)) != 0:
            raise ValueError("chunk_rows must be a power of two >= 256, "
                             f"got {self.chunk_rows}")
        if self.big_chunk_rows != 0 and (
                self.big_chunk_rows < self.chunk_rows
                or (self.big_chunk_rows & (self.big_chunk_rows - 1)) != 0):
            raise ValueError(
                "big_chunk_rows must be 0 or a power of two >= "
                f"chunk_rows, got {self.big_chunk_rows}")
        if self.hist_precision not in ("default", "high", "highest"):
            raise ValueError(
                f"Unknown hist_precision: {self.hist_precision}")
        if self.nonfinite_policy not in ("raise", "skip_tree", "clamp"):
            raise ValueError(
                f"Unknown nonfinite_policy: {self.nonfinite_policy} "
                "(expected raise, skip_tree or clamp)")
        if self.fused_scan_iters != "auto":
            try:
                self.fused_scan_iters = int(self.fused_scan_iters)
            except (TypeError, ValueError):
                raise ValueError(
                    "fused_scan_iters must be 'auto' or an integer >= 1, "
                    f"got {self.fused_scan_iters!r}") from None
            if not 1 <= self.fused_scan_iters <= 1024:
                raise ValueError(
                    "fused_scan_iters must be in [1, 1024] (one scan "
                    "window is one XLA program; larger windows only "
                    "grow trace time), got "
                    f"{self.fused_scan_iters}")
        for name in ("serve_max_batch_rows", "serve_min_bucket_rows"):
            v = getattr(self, name)
            if v < 1 or (v & (v - 1)) != 0:
                raise ValueError(f"{name} must be a power of two >= 1, "
                                 f"got {v}")
        if self.serve_min_bucket_rows > self.serve_max_batch_rows:
            raise ValueError(
                "serve_min_bucket_rows must be <= serve_max_batch_rows "
                f"({self.serve_min_bucket_rows} > "
                f"{self.serve_max_batch_rows})")
        if self.serve_max_replicas \
                and self.serve_min_replicas > self.serve_max_replicas:
            raise ValueError(
                "serve_min_replicas must be <= serve_max_replicas "
                f"({self.serve_min_replicas} > "
                f"{self.serve_max_replicas})")
        if self.autoscale_up_qps > 0 and self.autoscale_down_qps > 0 \
                and self.autoscale_down_qps >= self.autoscale_up_qps:
            raise ValueError(
                "autoscale_down_qps must stay strictly below "
                "autoscale_up_qps — that gap is the hysteresis band "
                "that stops the fleet flapping "
                f"({self.autoscale_down_qps} >= "
                f"{self.autoscale_up_qps})")
        if self.serve_shed_queue_rows \
                and self.serve_shed_queue_rows >= self.serve_queue_rows:
            raise ValueError(
                "serve_shed_queue_rows (soft shed threshold) must stay "
                "below serve_queue_rows (hard admission wall) to ever "
                f"fire ({self.serve_shed_queue_rows} >= "
                f"{self.serve_queue_rows})")
        for name, spec in self._BOUNDS.items():
            lo, hi = spec[0], spec[1]
            strict = len(spec) > 2 and spec[2] == "gt"
            v = getattr(self, name)
            if v is None:
                continue
            if lo is not None and (v <= lo if strict else v < lo):
                op = ">" if strict else ">="
                raise ValueError(f"{name} = {v} should be {op} {lo}")
            if hi is not None and v > hi:
                raise ValueError(f"{name} = {v} should be <= {hi}")
        if self.objective in ("multiclass", "multiclassova"):
            if self.num_class < 2:
                raise ValueError(
                    "num_class must be >= 2 for multiclass objectives")
        elif self.objective != "custom" and self.num_class != 1:
            raise ValueError(
                f"num_class must be 1 for objective {self.objective}")
        if self.boosting == "rf":
            if not (self.bagging_freq > 0 and 0.0 < self.bagging_fraction < 1.0):
                raise ValueError(
                    "Random forest needs bagging_freq > 0 and "
                    "0 < bagging_fraction < 1")
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            raise ValueError(
                "Cannot set is_unbalance and scale_pos_weight at the same time")

    # -- construction ----------------------------------------------------
    _LIST_INT = {"eval_at", "max_bin_by_feature", "monotone_constraints"}
    _LIST_FLOAT = {"feature_contri", "label_gain", "auc_mu_weights",
                   "cegb_penalty_feature_lazy", "cegb_penalty_feature_coupled"}
    _LIST_STR = {"valid", "metric"}

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        raw = resolve_params(params)
        fields = {f.name: f for f in dataclasses.fields(cls)}
        kwargs: Dict[str, Any] = {}
        extra: Dict[str, Any] = {}
        for k, v in raw.items():
            if k not in fields or k == "extra":
                extra[k] = v
                continue
            f = fields[k]
            try:
                if k in cls._LIST_INT:
                    kwargs[k] = _parse_list(v, int)
                elif k in cls._LIST_FLOAT:
                    kwargs[k] = _parse_list(v, float)
                elif k in cls._LIST_STR:
                    kwargs[k] = _parse_list(v, str)
                elif f.type in ("bool", bool):
                    kwargs[k] = _parse_bool(v)
                elif f.type in ("int", int):
                    kwargs[k] = int(v)
                elif f.type in ("float", float):
                    kwargs[k] = float(v)
                elif f.type in ("Optional[int]",):
                    kwargs[k] = None if v is None else int(v)
                elif k == "categorical_feature" or k == "interaction_constraints":
                    kwargs[k] = v
                else:
                    kwargs[k] = str(v)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"Bad value for parameter {k}: {v!r}") from exc
        cfg = cls(**kwargs)
        cfg.extra = extra
        return cfg

    def to_params(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name == "extra":
                continue
            out[f.name] = getattr(self, f.name)
        out.update(self.extra)
        return out

    def update(self, params: Dict[str, Any]) -> "Config":
        merged = self.to_params()
        merged.update(resolve_params(params))
        return Config.from_params(merged)

    def to_string(self) -> str:
        parts = []
        for f in dataclasses.fields(self):
            if f.name == "extra":
                continue
            v = getattr(self, f.name)
            if isinstance(v, list):
                v = ",".join(str(x) for x in v)
            parts.append(f"[{f.name}: {v}]")
        return "\n".join(parts)
