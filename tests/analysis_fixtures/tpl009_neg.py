# tpulint fixture: TPL009 negative — float32 tables at the jit
# boundary, and host-only float64 that never enters traced code. No
# EXPECT lines.
import jax
import numpy as np


@jax.jit
def traced(x):
    return x * 2.0


def f32_table(n):
    return traced(np.zeros((n,), np.float32))


def explicit_f32_asarray(values):
    return traced(np.asarray(values, dtype=np.float32))


def rebound_to_f32_before_the_call(n):
    table = np.zeros((n,))             # f64, but...
    table = table.astype(np.float32)   # ...rebound before use
    return traced(table)


def host_only_f64(n):
    stats = np.zeros((n,))             # f64 stays on the host
    return stats.sum()


def int_arange(n):
    return traced(np.arange(n))        # int64, not float
