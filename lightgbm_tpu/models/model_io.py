"""Model text / JSON serialization.

Re-design of /root/reference/src/boosting/gbdt_model_text.cpp
(SaveModelToString :~300, LoadModelFromString :421, DumpModel). The text
format is kept LightGBM-compatible (``tree`` header, ``Tree=i`` blocks,
``end of trees``) so models round-trip with the reference ecosystem and
conformance can be eyeballed directly against reference output.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from .tree import Tree

__all__ = ["model_to_string", "load_model_string", "dump_model_dict",
           "trees_to_dataframe"]


def model_to_string(booster, num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    importance_type: str = "split") -> str:
    K = booster.num_model_per_iteration()
    trees = booster._models
    total_iters = len(trees) // max(K, 1)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    lo = start_iteration * K
    hi = min(len(trees), (start_iteration + num_iteration) * K)
    sel = trees[lo:hi]

    nf = booster.num_feature()
    feature_names = booster._feature_names or \
        [f"Column_{i}" for i in range(nf)]
    feature_infos = booster._feature_infos or ["none"] * nf

    out = ["tree", "version=v4"]
    out.append(f"num_class={max(1, booster._num_class)}")
    out.append(f"num_tree_per_iteration={K}")
    out.append("label_index=0")
    out.append(f"max_feature_idx={nf - 1}")
    out.append(f"objective={booster._objective_str}")
    if booster._avg_output:
        out.append("average_output")
    out.append("feature_names=" + " ".join(feature_names))
    out.append("feature_infos=" + " ".join(feature_infos))

    tree_strs = [t.to_string(i) for i, t in enumerate(sel)]
    out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    out.append("")
    out.extend(s.rstrip("\n") + "\n" for s in tree_strs)
    out.append("end of trees")
    out.append("")

    imp = booster.feature_importance(importance_type)
    pairs = [(feature_names[i], imp[i]) for i in np.argsort(-np.asarray(imp))
             if imp[i] > 0]
    out.append("feature_importances:")
    for name, v in pairs:
        out.append(f"{name}={v:g}" if importance_type == "gain"
                   else f"{name}={int(v)}")
    out.append("")
    out.append("parameters:")
    if booster._cfg is not None:
        out.append(booster._cfg.to_string())
    out.append("end of parameters")
    out.append("")
    pc = booster.pandas_categorical
    out.append("pandas_categorical:" +
               json.dumps(pc) if pc is not None else
               "pandas_categorical:null")
    return "\n".join(out) + "\n"


def load_model_string(booster, s: str) -> None:
    """Populate a Booster from model text (LoadModelFromString analog)."""
    lines = s.split("\n")
    header: Dict[str, str] = {}
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            break
        if "=" in line:
            k, v = line.split("=", 1)
            header[k] = v
        elif line == "average_output":
            header["average_output"] = "1"
        i += 1

    trees: List[Tree] = []
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            kv: Dict[str, str] = {}
            i += 1
            while i < len(lines):
                tl = lines[i].strip()
                if tl == "" or tl.startswith("Tree=") or \
                        tl.startswith("end of trees"):
                    break
                if "=" in tl:
                    k, v = tl.split("=", 1)
                    kv[k] = v
                i += 1
            trees.append(Tree.from_lines(kv))
        elif line.startswith("end of trees"):
            break
        else:
            i += 1

    booster._trees = trees
    booster._num_class = int(header.get("num_class", "1"))
    booster._objective_str = header.get("objective", "none")
    booster._avg_output = "average_output" in header
    booster._feature_names = header.get("feature_names", "").split()
    booster._feature_infos = header.get("feature_infos", "").split()
    pc_line = next((ln for ln in reversed(lines)
                    if ln.startswith("pandas_categorical:")), None)
    if pc_line is not None:
        try:
            booster.pandas_categorical = json.loads(
                pc_line.split(":", 1)[1])
        except json.JSONDecodeError:
            booster.pandas_categorical = None


def _node_to_dict(t: Tree, node: int) -> Dict:
    if node < 0:
        leaf = ~node
        return {
            "leaf_index": int(leaf),
            "leaf_value": float(t.leaf_value[leaf]),
            "leaf_weight": float(t.leaf_weight[leaf]),
            "leaf_count": int(t.leaf_count[leaf]),
        }
    d = {
        "split_index": int(node),
        "split_feature": int(t.split_feature[node]),
        "split_gain": float(t.split_gain[node]),
        "threshold": float(t.threshold[node]),
        "decision_type": "==" if t.is_categorical_node(node) else "<=",
        "default_left": t.default_left(node),
        "missing_type": ["None", "Zero", "NaN"][t.missing_type(node)],
        "internal_value": float(t.internal_value[node]),
        "internal_weight": float(t.internal_weight[node]),
        "internal_count": int(t.internal_count[node]),
        "left_child": _node_to_dict(t, t.left_child[node]),
        "right_child": _node_to_dict(t, t.right_child[node]),
    }
    return d


def dump_model_dict(booster, num_iteration: Optional[int] = None,
                    start_iteration: int = 0,
                    importance_type: str = "split") -> Dict:
    """JSON model dump (GBDT::DumpModel analog, boosting.h:182)."""
    K = booster.num_model_per_iteration()
    trees = booster._models
    total_iters = len(trees) // max(K, 1)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    lo = start_iteration * K
    hi = min(len(trees), (start_iteration + num_iteration) * K)
    nf = booster.num_feature()
    return {
        "name": "tree",
        "version": "v4",
        "num_class": max(1, booster._num_class),
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": nf - 1,
        "objective": booster._objective_str,
        "average_output": booster._avg_output,
        "feature_names": booster._feature_names,
        "feature_infos": booster._feature_infos,
        "tree_info": [
            {
                "tree_index": i,
                "num_leaves": int(t.num_leaves),
                "num_cat": int(t.num_cat),
                "shrinkage": float(t.shrinkage),
                "tree_structure": _node_to_dict(
                    t, 0 if t.num_leaves > 1 else -1),
            }
            for i, t in enumerate(trees[lo:hi])
        ],
        "feature_importances": {
            booster._feature_names[i] if i < len(booster._feature_names)
            else f"Column_{i}": float(v)
            for i, v in enumerate(booster.feature_importance(importance_type))
            if v > 0
        },
    }


def trees_to_dataframe(booster):
    """Flatten the forest into a pandas DataFrame
    (basic.py trees_to_dataframe analog)."""
    import pandas as pd
    rows = []
    fnames = booster._feature_names

    for ti, t in enumerate(booster._models):
        def walk(node, parent_idx=None, depth=0):
            if node < 0:
                leaf = ~node
                rows.append({
                    "tree_index": ti,
                    "node_depth": depth + 1,
                    "node_index": f"{ti}-L{leaf}",
                    "left_child": None, "right_child": None,
                    "parent_index": parent_idx,
                    "split_feature": None, "split_gain": None,
                    "threshold": None, "decision_type": None,
                    "missing_direction": None, "missing_type": None,
                    "value": float(t.leaf_value[leaf]),
                    "weight": float(t.leaf_weight[leaf]),
                    "count": int(t.leaf_count[leaf]),
                })
                return
            idx = f"{ti}-S{node}"
            f = int(t.split_feature[node])
            rows.append({
                "tree_index": ti,
                "node_depth": depth + 1,
                "node_index": idx,
                "left_child": (f"{ti}-S{t.left_child[node]}"
                               if t.left_child[node] >= 0
                               else f"{ti}-L{~t.left_child[node]}"),
                "right_child": (f"{ti}-S{t.right_child[node]}"
                                if t.right_child[node] >= 0
                                else f"{ti}-L{~t.right_child[node]}"),
                "parent_index": parent_idx,
                "split_feature": fnames[f] if f < len(fnames) else str(f),
                "split_gain": float(t.split_gain[node]),
                "threshold": float(t.threshold[node]),
                "decision_type": "==" if t.is_categorical_node(node)
                else "<=",
                "missing_direction": "left" if t.default_left(node)
                else "right",
                "missing_type": ["None", "Zero", "NaN"][t.missing_type(node)],
                "value": float(t.internal_value[node]),
                "weight": float(t.internal_weight[node]),
                "count": int(t.internal_count[node]),
            })
            walk(t.left_child[node], idx, depth + 1)
            walk(t.right_child[node], idx, depth + 1)

        walk(0 if t.num_leaves > 1 else -1)
    return pd.DataFrame(rows)
