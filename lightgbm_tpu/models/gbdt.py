"""GBDT boosting driver.

Re-design of /root/reference/src/boosting/gbdt.cpp (Init :53, Train :237,
TrainOneIter :344, UpdateScore :491, BoostFromAverage :319), dart.hpp,
rf.hpp, bagging.hpp and goss.hpp for TPU:

- The binned matrix, scores, gradients and the growth loop all live in HBM;
  only the finished (small) tree arrays cross back to the host per
  iteration (the CUDA learner's host<->device contract, SURVEY.md §3.5).
- Bagging and GOSS are expressed as a per-row *weight vector* instead of
  index compaction (bagging.hpp:30 builds bag_data_indices_): a row's
  weight multiplies (g, h) and is the unit counted by min_data_in_leaf, so
  out-of-bag rows simply weigh 0. This keeps every shape static and is
  mathematically identical to training on the subset.
- Sampling uses jax.random with a per-iteration folded key -> deterministic
  and device-resident (no host RNG transfer per iteration).
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, \
    Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..obs import register_jit
from ..obs.trace import FUSED_SCAN_PHASE
from ..objectives import Objective
from ..resilience.faults import FaultPlan, is_resource_exhausted
from ..ops.gather import gather_small
from ..ops.grow import GrowConfig, TreeArrays, grow_tree, grow_tree_impl
from ..ops.predict import predict_leaf_binned
from ..ops.renew import renew_leaf_values
from ..ops.split import SplitParams
from .tree import (Tree, pack_tree_device, tree_from_arrays,
                   unpack_tree_host)

__all__ = ["GBDTBooster", "resolve_hist_method", "resolve_scan_iters"]


def _donate(*argnums: int):
    """Donation argnums for the fused step/scan wrappers.

    On CPU XLA ignores donation and warns per dispatch, so the
    wrappers normally declare none there — but ``lint --ir`` (TPL013,
    analysis/ircheck.py) must lower the SAME donation contract the TPU
    path runs with to verify input→output aliasing on a CPU-only CI
    host: LIGHTGBM_TPU_FORCE_DONATE=1 keeps the declaration on any
    backend (lowering only — nothing executes under the lint)."""
    import os

    if jax.default_backend() == "cpu" \
            and os.environ.get("LIGHTGBM_TPU_FORCE_DONATE") != "1":
        return ()
    return argnums


def resolve_scan_iters(requested) -> int:
    """Concrete scan-window budget from ``Config.fused_scan_iters``.

    Returns the max number of boosting iterations one fused
    ``lax.scan`` program may cover (1 = stay on the per-iteration
    fused path). Like the pallas flip (``resolve_hist_method``),
    ``auto`` stays at 1 until the Higgs-shaped
    ``benchmarks/fused_iter_bench.py`` scan arm measures an iters/sec
    win on chip — ``LIGHTGBM_TPU_AUTO_SCAN_ITERS=N`` opts auto in for
    that measurement, and ``LIGHTGBM_TPU_DISABLE_SCAN=1`` is the kill
    switch that pins everything (including explicit integers) back to
    per-iteration dispatch."""
    import os

    if os.environ.get("LIGHTGBM_TPU_DISABLE_SCAN") == "1":
        return 1
    if requested == "auto":
        env = os.environ.get("LIGHTGBM_TPU_AUTO_SCAN_ITERS", "")
        if env:
            try:
                # same [1, 1024] ceiling Config validation enforces
                # for an explicit fused_scan_iters (a 100k-slot scan
                # only grows trace time)
                return min(1024, max(1, int(env)))
            except ValueError:
                from ..utils.log import log_warning
                log_warning(
                    f"LIGHTGBM_TPU_AUTO_SCAN_ITERS={env!r} is not an "
                    "integer; keeping the per-iteration fused path")
        return 1
    return max(1, int(requested))


def resolve_hist_method(requested: str, backend: Optional[str] = None,
                        pallas_ok: Optional[bool] = None) -> str:
    """Concrete histogram method from the Config value.

    ``auto`` resolves to scatter on CPU and the MXU nibble matmul on
    accelerators. The Pallas kernel (ops/pallas_hist.py) is preferred
    by ``auto`` on TPU only when ``LIGHTGBM_TPU_AUTO_PALLAS=1``: the
    flip is gated on a measured iters/sec win on the Higgs-shaped
    bench at 255 leaves/255 bins (benchmarks/fused_iter_bench.py grows
    the pallas arm; docs/PALLAS.md records the gate) — interpret-mode
    parity alone does not flip the default. An explicit
    ``hist_method="pallas"`` on an environment where Pallas is
    unavailable falls back to the ``auto`` resolution with a warning
    instead of failing the run.
    """
    import os

    if backend is None:
        # tpu may surface as platform "tpu" or a tunneled plugin name
        backend = jax.default_backend()

    def _pallas_ok():
        # probed lazily: the default scatter/mxu resolutions must not
        # pay the jax.experimental.pallas import at engine init
        nonlocal pallas_ok
        if pallas_ok is None:
            from ..ops.pallas_hist import pallas_available
            pallas_ok = pallas_available()
        return pallas_ok

    if requested == "pallas" and not _pallas_ok():
        from ..utils.log import log_warning
        log_warning("hist_method='pallas' requested but Pallas is "
                    "unavailable; falling back to the auto resolution")
        requested = "auto"
    if requested != "auto":
        return requested
    if backend == "cpu":
        return "scatter"
    if os.environ.get("LIGHTGBM_TPU_AUTO_PALLAS") == "1" \
            and _pallas_ok():
        return "pallas"
    return "mxu"

# non-finite guard (resilience): flag bits and the clamp ceiling
# (well inside float32 range so downstream sums stay finite)
_NF_GRAD, _NF_HESS, _NF_LEAF = 1, 2, 4
_NF_CLAMP = 1e30


def _nf_clamp(a, lo, hi):
    """NaN -> 0, +/-Inf -> the finite bounds (nonfinite_policy=clamp)."""
    return jnp.clip(jnp.nan_to_num(a, nan=0.0, posinf=hi, neginf=lo),
                    lo, hi)


def _gh_flag_clamp(g, h, policy):
    """Gradient/hessian finiteness flag + clamp policy — pure jnp, so
    the eager guard and the fused step trace the SAME implementation
    (like _leaf_guard; any drift between the two paths would break
    their documented bit-equality)."""
    flag = (jnp.where(jnp.all(jnp.isfinite(g)), 0, _NF_GRAD)
            | jnp.where(jnp.all(jnp.isfinite(h)), 0, _NF_HESS)
            ).astype(jnp.int32)
    if policy == "clamp":
        g = _nf_clamp(g, -_NF_CLAMP, _NF_CLAMP)
        h = _nf_clamp(h, 0.0, _NF_CLAMP)
    return g, h, flag


def _leaf_value_guard(dev_tree, gh_flag, policy):
    """Fitted-leaf-value guard (pure jnp, shared verbatim by the eager
    path, the fused step and the scan body): extend the iteration flag
    with the leaf bit and apply the policy on device — clamp rewrites
    the leaf table, skip_tree demotes the tree to a no-op constant
    (the AsConstantTree path downstream)."""
    lv = dev_tree.leaf_value
    flag = gh_flag | jnp.where(jnp.all(jnp.isfinite(lv)), 0,
                               _NF_LEAF).astype(jnp.int32)
    if policy == "clamp":
        dev_tree = dev_tree._replace(
            leaf_value=_nf_clamp(lv, -_NF_CLAMP, _NF_CLAMP))
    elif policy == "skip_tree":
        ok = flag == 0
        dev_tree = dev_tree._replace(
            num_leaves=jnp.where(ok, dev_tree.num_leaves, 1),
            leaf_value=jnp.where(ok, lv, jnp.zeros_like(lv)))
    return dev_tree, flag


class _StepCtx(NamedTuple):
    """Static context of one fused boosting iteration — everything
    :func:`_fused_iter_step` needs beyond its traced operands. Built
    once per engine state (``GBDTBooster._step_ctx``) and closed over
    by BOTH the per-iteration jitted step and the multi-iteration scan
    body, so the two programs trace the identical ops by
    construction."""
    gcfg: GrowConfig
    K: int
    obj: object
    nf_policy: str
    quant: bool
    bynode: bool
    base_key: object
    bynode_key: object
    inj_grad: object      # fault-injection iteration arrays (or None):
    inj_hess: object      # traced as where(it == N) — zero recompiles


def _fused_iter_step(ctx: _StepCtx, score, it, shrink, row_w, fmask,
                     bins_T, fnb, fnan, label, weight, monotone,
                     feat_is_cat, igroups, forced, bundle):
    """One boosting iteration as pure traced ops: gradients -> guard ->
    K tree grows -> pack -> contrib -> score update. Returns
    ``(new_score, [(vec, cmask, num_leaves)] * K, flags[K])``. The
    per-iteration fused program jits a thin wrapper over this
    (``_get_fused_fn.step``) and the multi-iteration scan
    (``_get_scan_fn``) calls it per window slot — one implementation,
    every fused path."""
    obj, K = ctx.obj, ctx.K
    g, h = obj.grad_hess(score if K > 1 else score[0], label, weight)
    if K == 1:
        g, h = g[None, :], h[None, :]
    if ctx.inj_grad is not None:
        g = jnp.where(jnp.any(it == ctx.inj_grad),
                      jnp.float32(jnp.nan), g)
    if ctx.inj_hess is not None:
        h = jnp.where(jnp.any(it == ctx.inj_hess),
                      jnp.float32(jnp.nan), h)
    # non-finite guard, fused into this one program via the same
    # pure-jnp helper the eager path uses: the isfinite reductions cost
    # a single pass; the resulting flag rides back with the tree
    # outputs and is checked one iteration late on the host (no
    # per-iteration device sync)
    g, h, gh_flag = _gh_flag_clamp(g, h, ctx.nf_policy)
    # identical key schedule to the eager path (fold_in is a pure
    # device op, so tracing it keeps streams bit-equal)
    qk_it = jax.random.fold_in(ctx.base_key, it) if ctx.quant else None
    nk_it = jax.random.fold_in(ctx.bynode_key, it) if ctx.bynode \
        else None
    new_score = score
    outs = []
    flags = []
    for k in range(K):
        qk = jax.random.fold_in(qk_it, k) if ctx.quant else None
        nk = jax.random.fold_in(nk_it, k) if ctx.bynode else None
        dev_tree, row_leaf = grow_tree_impl(
            ctx.gcfg, bins_T, g[k], h[k], row_w, fmask, fnb, fnan,
            monotone, feat_is_cat, qk, igroups, forced, None, nk,
            bundle)
        dev_tree, flag_k = _leaf_value_guard(dev_tree, gh_flag,
                                             ctx.nf_policy)
        vec, cmask = pack_tree_device(dev_tree)
        contrib = gather_small(dev_tree.leaf_value, row_leaf)
        # a no-growth tree is replaced by a constant at flush
        # (AsConstantTree): contribute nothing now
        contrib = jnp.where(dev_tree.num_leaves > 1, contrib, 0.0)
        new_score = new_score.at[k].add(contrib * shrink)
        outs.append((vec, cmask, dev_tree.num_leaves))
        flags.append(flag_k)
    return new_score, outs, jnp.stack(flags)


@jax.jit
def _tree_values_binned(split_feature, threshold_bin, default_left,
                        left_child, right_child, leaf_value,
                        feat_nan_bin, bins_T, is_cat=None, cat_masks=None):
    """Jitted per-row tree output over binned data (compiled once per
    (num_leaves, n) shape — trees are padded to the configured size)."""
    leaves = predict_leaf_binned(split_feature, threshold_bin, default_left,
                                 left_child, right_child, feat_nan_bin,
                                 bins_T, is_cat, cat_masks)
    # gather_small, not leaf_value[leaves]: the [n]-sized small-table
    # gather costs ~8.6 ms/M rows on TPU (benchmarks/PROFILE.md) and
    # valid-set scoring pays it every iteration
    return gather_small(leaf_value, leaves)


@jax.jit
def _tree_leaves_binned(split_feature, threshold_bin, default_left,
                        left_child, right_child,
                        feat_nan_bin, bins_T, is_cat=None, cat_masks=None):
    return predict_leaf_binned(split_feature, threshold_bin, default_left,
                               left_child, right_child, feat_nan_bin,
                               bins_T, is_cat, cat_masks)


@jax.jit
def _linear_eval(const, coef, feats, nfeat, leaf_value, raw, leaves):
    from ..ops.linear import linear_leaf_values
    return linear_leaf_values(const, coef, feats, nfeat, leaf_value, raw,
                              leaves)


# recompile telemetry (obs/jit_tracker.py): a cache miss on any of these
# mid-training is the 530 ms/iter regression class from PROFILE.md.
# Rebinding routes calls through the cost-attribution wrapper
# (obs/cost.py: one {"event": "compile"} record per first compile per
# signature)
_tree_values_binned = register_jit("gbdt/tree_values_binned",
                                   _tree_values_binned,
                                   max_signatures=8)
_tree_leaves_binned = register_jit("gbdt/tree_leaves_binned",
                                   _tree_leaves_binned,
                                   max_signatures=8)
_linear_eval = register_jit("gbdt/linear_eval", _linear_eval,
                            max_signatures=8)


class _ValidData:
    def __init__(self, dataset, score: jnp.ndarray, name: str):
        self.dataset = dataset
        self.score = score
        self.name = name


class GBDTBooster:
    """The boosting engine behind the public Booster (basic.py)."""

    def __init__(self, cfg: Config, train_set, objective: Optional[Objective],
                 num_model_per_iter: int = 1):
        self.cfg = cfg
        self.train_set = train_set
        self.objective = objective
        self.K = (objective.num_model_per_iteration
                  if objective is not None else num_model_per_iter)
        self._models_store: List[Tree] = []
        self._pending_dev: List[tuple] = []
        self._nl_async: List = []
        self.iter_ = 0
        # iterations contributed by an adopted init_model (the
        # reference's num_init_iteration): continued training adds
        # num_boost_round iterations ON TOP of these, and a
        # checkpoint-resumed continued run needs the offset to know
        # its true end iteration (engine.py, docs/PIPELINE.md)
        self.init_iteration = 0
        self.valid_sets: List[_ValidData] = []
        self._shrinkage = cfg.learning_rate

        # -- resilience state (resilience/): the non-finite guard
        # policy, the deterministic fault-injection plan (test harness;
        # inert without LIGHTGBM_TPU_FAULT_INJECT), guard flags in
        # flight from async device programs, and the fault event log
        # the telemetry recorder drains --
        self._nf_policy = cfg.nonfinite_policy
        self._fault_plan = FaultPlan.from_env()
        self._guard_async: List[tuple] = []
        self._fault_recent = False
        self._resume_stalled = False
        self._finished_natural = False
        self.fault_log: List[dict] = []

        ds = train_set
        self.n = ds.num_data()
        self.F = ds.num_features()
        # NOTE: the [F, n] device upload is deferred until after the
        # EFB bundling decision below — uploading first would pin the
        # full unbundled matrix in HBM alongside the bundled one
        self.bins_T = None
        self.feat_num_bins = ds.device_feat_num_bins()
        self.feat_nan_bin = ds.device_feat_nan_bin()
        self.feat_is_cat = ds.device_feat_is_cat()
        self.label = jnp.asarray(ds.get_label(), jnp.float32)
        w = ds.get_weight()
        self.weight = None if w is None else jnp.asarray(w, jnp.float32)
        mono = ds.monotone_array(cfg)
        self.monotone = None if mono is None else jnp.asarray(mono, jnp.int8)
        self.interaction_groups = self._parse_interaction_constraints(cfg)
        self.forced = self._load_forced_splits(cfg)
        self._init_cegb(cfg)

        # linear trees (LinearTreeLearner): fit leaf-wise linear models on
        # raw numerical values after growth
        self.raw = None
        if cfg.linear_tree:
            if self.monotone is not None:
                raise ValueError(
                    "linear_tree does not support monotone constraints "
                    "(reference config check)")
            rn = ds.raw_numeric()
            if rn is None:
                raise ValueError(
                    "linear_tree requires the Dataset to be constructed "
                    "with the linear_tree parameter (raw data retained)")
            self.raw = jnp.asarray(rn)

        # boost_from_average (gbdt.cpp:319). The average is folded into the
        # first iteration's trees as a leaf-value bias (TrainOneIter's
        # AddBias path) so saved models are self-contained.
        # rf: the prior is folded into EVERY tree (rf.hpp AddBias) and the
        # score is a running average; gbdt/dart: folded into the first
        # iteration's trees only.
        init_score = np.zeros((self.K,), np.float64)
        self._fold_bias = False
        if objective is not None and cfg.boost_from_average \
                and ds.get_init_score() is None:
            self._fold_bias = cfg.boosting != "rf"
            if hasattr(objective, "init_label_weights"):
                objective.init_label_weights(np.asarray(ds.get_label()),
                                             None if w is None
                                             else np.asarray(w))
            init_score = np.asarray(
                objective.boost_from_score(np.asarray(ds.get_label()),
                                           None if w is None
                                           else np.asarray(w)),
                np.float64).reshape(self.K)
        elif objective is not None and hasattr(objective,
                                               "init_label_weights"):
            objective.init_label_weights(np.asarray(ds.get_label()),
                                         None if w is None else np.asarray(w))
        self.init_score = init_score

        score0 = jnp.tile(jnp.asarray(init_score, jnp.float32)[:, None],
                          (1, self.n))
        user_init = ds.get_init_score()
        if user_init is not None:
            score0 = score0 + jnp.asarray(user_init, jnp.float32).reshape(
                self.K, self.n)
        self.score = score0

        hist_method = resolve_hist_method(cfg.hist_method)
        if hist_method == "pallas" and cfg.hist_precision != "default":
            # the multi-pass f32 emulation is MXU-path machinery; the
            # Pallas kernel always runs its single-pass f32-accumulate
            # numerics (docs/PALLAS.md) — say so instead of silently
            # ignoring the knob
            from ..utils.log import log_warning
            log_warning(
                f"hist_precision='{cfg.hist_precision}' applies to "
                "hist_method='mxu' only; the pallas kernel runs its "
                "single-pass f32-accumulation numerics (and an OOM "
                "degradation to mxu would re-enable the multi-pass "
                "emulation mid-run)")
        grower = cfg.grower
        if cfg.use_quantized_grad and grower != "compact":
            grower = "compact"  # quantized histograms are compact-only
        if self.interaction_groups is not None or self.forced is not None \
                or self.cegb_enabled:
            grower = "compact"  # per-leaf masks / forced splits need it
        if cfg.path_smooth > 0.0 or cfg.feature_fraction_bynode < 1.0 \
                or self.monotone is not None:
            # path smoothing, per-node column sampling and monotone
            # output-bound entries live on the compact grower
            grower = "compact"
        if grower == "masked" and self.n * cfg.num_leaves > 50_000_000:
            from ..utils.log import log_warning
            log_warning(
                "grower=masked rebuilds every histogram with a full-row "
                "pass: O(num_leaves * rows * features) per tree "
                f"(~{self.n * cfg.num_leaves / 1e9:.1f}B row-visits "
                "here). Use grower=compact (the default) for data of "
                "this size.")
        self.grow_cfg_extra = {}
        self.grow_cfg = GrowConfig(
            num_leaves=cfg.num_leaves,
            num_bins=ds.num_total_bins(),
            max_depth=cfg.max_depth,
            grower=grower,
            chunk=cfg.chunk_rows,
            big_chunk=cfg.big_chunk_rows,
            hist_method=hist_method,
            hist_precision=cfg.hist_precision,
            quantized=cfg.use_quantized_grad,
            quant_bins=cfg.num_grad_quant_bins,
            renew_leaf=cfg.quant_train_renew_leaf,
            stochastic=cfg.stochastic_rounding,
            cegb=self.cegb_enabled,
            cegb_lazy=self.cegb_lazy,
            cegb_coupled=len(cfg.cegb_penalty_feature_coupled) > 0,
            cegb_tradeoff=cfg.cegb_tradeoff,
            cegb_split=cfg.cegb_penalty_split,
            monotone_method=cfg.monotone_constraints_method,
            bynode=cfg.feature_fraction_bynode,
            split=SplitParams(
                lambda_l1=cfg.lambda_l1,
                lambda_l2=cfg.lambda_l2,
                max_delta_step=cfg.max_delta_step,
                min_data_in_leaf=float(cfg.min_data_in_leaf),
                min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
                min_gain_to_split=cfg.min_gain_to_split,
                cat_smooth=cfg.cat_smooth,
                cat_l2=cfg.cat_l2,
                max_cat_threshold=cfg.max_cat_threshold,
                max_cat_to_onehot=cfg.max_cat_to_onehot,
                min_data_per_group=float(cfg.min_data_per_group),
                path_smooth=cfg.path_smooth,
                monotone_penalty=(cfg.monotone_penalty
                                  if self.monotone is not None else 0.0),
            ),
        )
        # -- Exclusive Feature Bundling (FeatureGroup / EFB,
        # feature_group.h:26): merge mutually-exclusive sparse features
        # into bundle columns so the bin matrix, the histogram work and
        # the per-leaf histogram cache all scale with #bundles ---------
        self.bundle = None
        self._bundle_dev = None
        # single source for the distributed dispatch decision — the
        # EFB gate below and the mesh setup further down must agree.
        # tree_learner="auto" resolves to a concrete mode inside the
        # dp_active block (it needs the post-bundle column count and
        # the world size; parallel/comms.py choose_parallel_mode).
        want_dp = (cfg.tree_learner in ("data", "feature", "voting",
                                        "auto")
                   or cfg.num_devices > 1)
        dp_active = want_dp and len(jax.devices()) > 1
        dp_mode = {"feature": "feature",
                   "voting": "voting"}.get(cfg.tree_learner, "data")
        # bundling is a dataset property that sits below the parallel
        # layer (feature_group.h:26): data-parallel shards bundle
        # columns by rows and psums their histograms; feature-parallel
        # windows/owns bundle columns like plain columns; voting runs
        # its ballot/election/exchange in bundle-column space.
        plain = (not cfg.linear_tree and grower == "compact"
                 # a locally-sharded dataset (distributed_dataset
                 # device residency on a pod) holds only this rank's
                 # rows — per-rank bundle decisions would diverge
                 and getattr(ds, "_local_row_offset", None) is None)
        if cfg.enable_bundle and plain:
            binfo = ds.bundles(cfg)
            if binfo is not None:
                self.bundle = binfo
                self._bundle_dev = (
                    jnp.asarray(binfo.bundle_of),
                    jnp.asarray(binfo.offset_of),
                    jnp.asarray(binfo.is_direct),
                    jnp.asarray(binfo.member_at),
                    jnp.asarray(binfo.tloc_at),
                    jnp.asarray(binfo.end_at),
                    jnp.asarray(binfo.nanpos_at),
                    jnp.asarray(binfo.nan_at))
                self.grow_cfg = self.grow_cfg._replace(
                    bundled=True, num_bins=binfo.num_positions)
        # per-row id/in-bag tracking through the partition is only
        # needed by bagging/GOSS (weight-0 rows), CEGB, or the bundled
        # merge; plain full-data training drops the ord2 sort column
        bag_active = cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0
            or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0)
        goss_active = (cfg.data_sample_strategy == "goss"
                       or cfg.boosting == "goss")
        self.grow_cfg = self.grow_cfg._replace(track_rows=(
            bag_active or goss_active or self.cegb_enabled
            or self.bundle is not None))
        self._bag_active = bag_active
        self._goss_active = goss_active
        # fused-iteration fast path state (built lazily; see
        # _train_one_iter_fused)
        self._fused_fn = None
        self._fused_proto = None
        self._row_w_ones = None
        self._fmask_cached = None
        # multi-iteration scan state (docs/FUSED.md): compiled window
        # programs by (W, bag_live), the pending precomputed window,
        # the last committed iteration's window position (telemetry),
        # and the engine-driven lookahead horizon — 1 (scan off) until
        # the train() loop proves how far ahead the window may run
        # without a callback observing mid-window state
        self._scan_fns: Dict[tuple, Callable] = {}
        self._scan_pend: Optional[dict] = None
        self._scan_last: Optional[dict] = None
        self._scan_horizon = 1

        # only ONE training matrix ever reaches HBM: bundled when EFB
        # engaged, the plain [F, n] matrix otherwise. Materialization
        # is DEFERRED below the mesh decision so shard_residency=device
        # can lay each row shard directly into its NamedSharding mesh
        # slice (parallel/placement.py) without first pinning an
        # unsharded device copy — and free the host copy after upload.
        ncols = int(self.bundle.bins_bundled.shape[1]) \
            if self.bundle is not None else self.F

        # -- histogram cache budget (HistogramPool analog;
        # histogram_pool_size in MB, -1 = unlimited like the reference,
        # config.h:301). Slots sized by the post-bundle column count.
        # CEGB / intermediate monotone / forced splits are served by the
        # pooled re-search (recompute-on-miss), like the reference pool
        # serves all consumers. --
        if cfg.histogram_pool_size > 0 and grower == "compact":
            per_leaf = ncols * self.grow_cfg.num_bins * 2 * 4
            slots = int(cfg.histogram_pool_size * 2 ** 20 // per_leaf)
            slots = max(2, slots)
            if slots < cfg.num_leaves:
                self.grow_cfg = self.grow_cfg._replace(
                    hist_pool_slots=slots)

        # -- distributed setup: mesh instead of Network::Init ------------
        # (SURVEY.md §2.6: the socket/MPI linker layer disappears; rows
        # are sharded over a jax Mesh and XLA emits the collectives)
        self.mesh = None
        self._pad = 0
        self._grow_fn = None
        if dp_active and self.cegb_enabled:
            raise ValueError("CEGB is not supported with multi-device "
                             "training yet")
        if dp_active:
            from ..parallel import comms
            from ..parallel.data_parallel import make_dp_grow_fn
            from ..parallel.mesh import make_mesh, pad_rows
            self.mesh = make_mesh(cfg.num_devices)
            D = int(self.mesh.devices.size)
            mode = dp_mode
            if cfg.tree_learner == "auto":
                # payload-adaptive choice (ROADMAP item 2): re-derived
                # per tree from (F, B, rows, world, wire dtype) — all
                # static for a given training run, so the per-tree
                # evaluation constant-folds to one mode; it moves only
                # when the run's shape does (e.g. a reset_parameter
                # rebuild). Forced splits exclude voting before
                # costing (CEGB never reaches here: any multi-device
                # CEGB run raised above).
                mode = comms.choose_parallel_mode(
                    ncols, self.grow_cfg.num_bins, self.n, D,
                    cfg.hist_comm, cfg.top_k)
                if mode == "voting" and self.forced is not None:
                    mode = "data"
                if mode != "data" and self.grow_cfg.grower != "compact":
                    # feature/voting replicate rows and gate their
                    # reductions per-search — only the compact grower
                    # implements that; level raises and masked would
                    # psum D identical replicated histograms
                    mode = "data"
                from ..utils.log import log_info
                log_info(
                    f"tree_learner=auto -> {mode}-parallel "
                    f"(F={ncols}, B={self.grow_cfg.num_bins}, "
                    f"rows={self.n}, world={D}, "
                    f"hist_comm={cfg.hist_comm})")
            # quantized histogram wire (docs/COLLECTIVES.md): resolve
            # "auto" against the histogram payload the CHOSEN mode
            # actually reduces (voting moves the small elected buffer,
            # not the full [F, B, 2] histogram)
            wire = comms.resolve_hist_comm(
                cfg.hist_comm, ncols, self.grow_cfg.num_bins,
                mode, cfg.top_k)
            if cfg.use_quantized_grad or mode == "feature":
                # quantized-gradient training reduces exact int32
                # histograms and feature-parallel reduces no histogram
                # at all — the wire never quantizes (the grower pins
                # it via make_hist_psum_ef(quantize=False)); record
                # f32 so telemetry reports the wire actually used
                wire = "f32"
            self.grow_cfg = self.grow_cfg._replace(hist_comm=wire)
            if mode == "voting" and (self.forced is not None
                                     or self.cegb_enabled):
                raise ValueError(
                    "tree_learner=voting does not support forced splits "
                    "or CEGB (their gathers read the local histogram "
                    "cache as if it were global)")
            if mode == "voting" and self.monotone is not None \
                    and cfg.monotone_constraints_method != "basic":
                # intermediate's all-leaves re-search reads the LOCAL
                # histogram cache; the reference likewise forces basic
                # in distributed mode (config.cpp:443-446)
                from ..utils.log import log_warning
                log_warning(
                    "tree_learner=voting forces "
                    "monotone_constraints_method=basic")
                self.grow_cfg = self.grow_cfg._replace(
                    monotone_method="basic")
            # reduce-scatter sharded split search (docs/SHARDING.md):
            # data-parallel meshes only — feature/voting already shard
            # their searches; EFB-bundled matrices keep the gathered
            # search (grow_tree_impl would raise)
            ss = cfg.split_search
            if ss == "sharded" and (mode != "data"
                                    or self.bundle is not None):
                if self.bundle is not None and mode == "data":
                    from ..utils.log import log_warning
                    log_warning(
                        "split_search=sharded does not cover EFB-"
                        "bundled matrices yet; using the gathered "
                        "search")
                ss = "gathered"
            self.grow_cfg = self.grow_cfg._replace(
                parallel_mode=mode, voting_top_k=cfg.top_k,
                split_search=ss)
            # feature-parallel replicates rows; no shard padding needed
            self._pad = 0 if mode == "feature" else pad_rows(self.n, D)
            self._grow_fn = self._build_grow_fn()

        # -- training-matrix materialization + shard residency ---------
        # (parallel/placement.py, docs/SHARDING.md): "device" lays each
        # mesh slice's rows directly into its device and FREES the host
        # binned matrix afterwards — no host holds the global matrix;
        # "host" keeps the classic host copy + device upload. auto =
        # device only on accelerator meshes (CPU virtual-device worlds
        # keep host so eager consumers stay cheap).
        residency = cfg.shard_residency
        if residency == "auto":
            residency = ("device" if self.mesh is not None
                         and jax.default_backend() != "cpu" else "host")
        local_off = getattr(ds, "_local_row_offset", None)
        if local_off is not None:
            # distributed_dataset kept only this rank's binned shard —
            # the dataset is device-destined by construction
            residency = "device"
            if self.mesh is not None \
                    and self.grow_cfg.parallel_mode == "feature":
                from ..basic import LightGBMError
                raise LightGBMError(
                    "feature-parallel growth replicates the full row "
                    "set on every device, but this rank holds only its "
                    "binned shard (shard_residency=device kept the "
                    "allgather from running) — use tree_learner=data "
                    "or shard_residency=host for feature-parallel")
        if residency == "device" and self.mesh is not None \
                and self.grow_cfg.parallel_mode == "feature":
            # feature-parallel replicates rows on every device — there
            # is no mesh slice to own; keep the host path
            residency = "host"
        self._residency = residency
        host_mat = (self.bundle.bins_bundled if self.bundle is not None
                    else ds.host_bins())             # [n, C] row-major
        from ..parallel import placement
        if residency == "device":
            if self.mesh is not None:
                # per-device slices cut straight from the host rows —
                # the unsharded [C, n] device copy never exists
                if local_off is None:
                    self.bins_T = placement.place_rows(
                        self.mesh, host_mat.T, row_axis=1,
                        pad=self._pad)
                else:
                    plan = placement.ShardPlan(self.mesh,
                                               self.n + self._pad)
                    self.bins_T = plan.place(host_mat.T, row_axis=1,
                                             local_offset=int(local_off),
                                             exclusive_rows=True)
                placement.upload_barrier()
            else:
                self.bins_T = jnp.asarray(host_mat.T)
            ds.free_host_bins()
            if self.bundle is None:
                if not self._pad:
                    # the placed matrix doubles as the dataset's device
                    # view, so binned-traversal consumers (init_model
                    # preload, OOM score rebuild) keep working without
                    # a host copy; with row padding the shapes differ
                    # and those rare paths raise free_host_bins' clear
                    # error instead of silently mixing padded rows in
                    ds._device_bins = self.bins_T
            else:
                # EFB keeps its (post-bundle) host matrix for now —
                # the Dataset-level [n, F] copy (the larger one) is
                # freed above; docs/SHARDING.md records the gap
                placement.host_bytes_gauge(host_mat.nbytes)
        else:
            self.bins_T = jnp.asarray(host_mat.T) \
                if self.bundle is not None else ds.device_bins()
            if self._pad:
                self.bins_T = jnp.pad(self.bins_T,
                                      ((0, 0), (0, self._pad)))
            placement.host_bytes_gauge(host_mat.nbytes)

        # score matrix follows the residency (sharded checkpoint
        # save/restore goes through placement.fetch_global)
        self.score = self._place_score(self.score)

        seed = cfg.seed if cfg.seed is not None else 0
        self._base_key = jax.random.PRNGKey(seed)
        self._init_keys_and_rngs(cfg)

    def _build_grow_fn(self):
        """Distributed grow fn from the CURRENT grow_cfg + capability
        flags — the single source for both engine init and
        reset_parameter rebuilds (the flag list must match the grow
        call's argument assembly in train_one_iter)."""
        from ..parallel.data_parallel import make_dp_grow_fn

        cfg = self.cfg
        return register_jit("parallel/dp_grow", make_dp_grow_fn(
            self.grow_cfg, self.mesh, self.monotone is not None,
            self.feat_is_cat is not None,
            cfg.use_quantized_grad and cfg.stochastic_rounding,
            self.interaction_groups is not None,
            self.forced is not None,
            self.grow_cfg.bynode < 1.0,
            has_bundle=self.bundle is not None), max_signatures=8)

    def _init_keys_and_rngs(self, cfg):
        # distinct stream for per-node column sampling (ColSampler's
        # feature_fraction_seed, col_sampler.hpp)
        self._bynode_key = jax.random.PRNGKey(cfg.feature_fraction_seed)
        self._feature_rng = np.random.RandomState(cfg.feature_fraction_seed)
        # DART state (dart.hpp)
        self._dart_rng = np.random.RandomState(cfg.drop_seed)
        self._tree_weights: List[float] = []  # per-model weight (DART/RF)

    # ------------------------------------------------------------------
    @property
    def models(self) -> List[Tree]:
        """Host Tree objects. Training defers device->host tree
        materialization (per-iteration fetches would stall the device
        pipeline; the copies run async) — first access flushes the
        queue."""
        self._flush_pending()
        return self._models_store

    @models.setter
    def models(self, v) -> None:
        self._pending_dev = []
        self._nl_async = []
        self._guard_async = []
        self._fault_recent = False
        self._finished_natural = False
        # precomputed scan lookahead belongs to the replaced model;
        # callers (preload_models / checkpoint restore) install the
        # matching score right after, so no rebuild here
        self._scan_pend = None
        self._scan_last = None
        self._models_store = list(v)

    # ------------------------------------------------------------------
    # resilience: non-finite guard, OOM degradation, fault events
    # (docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def _record_fault(self, kind: str, iteration: int, action: str,
                      detail: str) -> None:
        """Append one fault event to this booster's ``fault_log``
        (drained into the telemetry JSONL stream by obs/recorder.py)
        via the shared writer in resilience/faults.py — one schema,
        one cap, one registry counter for both the per-engine and the
        process-level logs."""
        from ..resilience.faults import append_fault_event
        append_fault_event(self.fault_log, kind, iteration, action,
                           detail)

    def _gh_guard(self, it: int, grad, hess):
        """Eager-path gradient/hessian guard: fault injection, one
        fused finiteness reduction -> flag bits, and the clamp policy
        applied in place. The fused fast path traces the identical ops
        inside its single program (_get_fused_fn)."""
        if self._fault_plan.fires("nan_grad", it):
            grad = jnp.full_like(grad, jnp.nan)
        if self._fault_plan.fires("nan_hess", it):
            hess = jnp.full_like(hess, jnp.nan)
        return _gh_flag_clamp(grad, hess, self._nf_policy)

    def _leaf_guard(self, dev_tree, gh_flag):
        """Fitted-leaf-value guard — delegates to the module-level
        pure-jnp :func:`_leaf_value_guard` so the eager path, the fused
        step and the scan body apply the one implementation."""
        return _leaf_value_guard(dev_tree, gh_flag, self._nf_policy)

    # tpulint: hot
    def _push_guard_flags(self, it: int, flags) -> None:
        """Queue a guard flag for the one-iteration-late async check
        (same non-stalling contract as the _nl_async tree queue)."""
        try:
            flags.copy_to_host_async()
        except AttributeError:  # non-jax arrays (tests/cpu)
            pass
        self._guard_async.append((it, flags))

    def _apply_guard_flag(self, it: int, flag: int) -> None:
        """Record + enforce the configured policy for one iteration's
        non-finite guard flag."""
        if not flag:
            return
        kinds = [name for bit, name in ((_NF_GRAD, "gradients"),
                                        (_NF_HESS, "hessians"),
                                        (_NF_LEAF, "leaf values"))
                 if flag & bit]
        detail = "non-finite " + ", ".join(kinds)
        self._record_fault("nonfinite", it, self._nf_policy, detail)
        if self._nf_policy == "raise":
            from ..basic import LightGBMError
            raise LightGBMError(
                f"{detail} detected at iteration {it} "
                "(nonfinite_policy=raise; use skip_tree or clamp to "
                "train through transient numerical faults)")

    # tpulint: hot
    def _drain_guard_flags(self) -> bool:
        """Resolve guard flags from previous async programs. A fired
        fault also sets the STICKY ``_fault_recent`` marker: callers
        other than the train step drain too (checkpoint writes, the
        end-of-training flush), and the next train step must still know
        not to interpret a 1-leaf tree in ``_nl_async`` as natural
        end-of-training — skip_tree demotions look identical to
        no-growth. The train step clears the marker when it consumes
        the matching ``_nl_async`` entries."""
        fired = False
        pending, self._guard_async = self._guard_async, []
        for it, flags in pending:
            fl = int(np.bitwise_or.reduce(
                np.atleast_1d(np.asarray(flags)).ravel()))
            if fl:
                fired = True
                self._apply_guard_flag(it, fl)
        if fired:
            self._fault_recent = True
        return fired

    def finish_faults(self) -> None:
        """Drain guard flags still in flight after the final iteration
        (the fused path checks one iteration late); called by the train
        loop before returning the booster."""
        self._drain_guard_flags()

    def _run_with_oom_degrade(self, thunk, what: str):
        """Run a grow/fused dispatch with graceful OOM degradation:
        on RESOURCE_EXHAUSTED, downgrade the histogram strategy
        (Pallas kernel -> MXU matmul -> scatter, then histogram-pool
        halving), rebuild the affected jitted programs and retry;
        re-raise as a clear LightGBMError once nothing is left to
        shed."""
        while True:
            try:
                self._fault_plan.maybe_oom(self.iter_)
                return thunk()
            except Exception as e:
                if not is_resource_exhausted(e):
                    raise
                if not self._degrade_after_oom(e, what):
                    from ..basic import LightGBMError
                    raise LightGBMError(
                        f"device RESOURCE_EXHAUSTED in {what} at "
                        f"iteration {self.iter_} and no degradation "
                        f"left to try: {e}") from e

    def _degrade_after_oom(self, exc, what: str) -> bool:
        """Apply one degradation step; False when exhausted."""
        gcfg = self.grow_cfg
        if gcfg.hist_method == "pallas":
            # first rung of the ladder: shed the VMEM-resident kernel
            # (its one-hot scratch block is the newest allocation) and
            # fall back to the XLA-generated MXU path
            self.grow_cfg = gcfg._replace(hist_method="mxu")
            action = "hist_method pallas -> mxu"
        elif gcfg.hist_method == "mxu":
            self.grow_cfg = gcfg._replace(hist_method="scatter")
            action = "hist_method mxu -> scatter"
        else:
            cur = gcfg.hist_pool_slots if gcfg.hist_pool_slots > 0 \
                else gcfg.num_leaves
            slots = max(2, cur // 2)
            if slots >= cur:
                return False
            self.grow_cfg = gcfg._replace(hist_pool_slots=slots)
            action = f"histogram pool -> {slots} slots"
        # drop every cached program that baked the old grow_cfg in
        self._fused_fn = None
        self._fused_proto = None
        self._scan_fns = {}
        if self.mesh is not None and self._grow_fn is not None:
            self._grow_fn = self._build_grow_fn()
        detail = f"RESOURCE_EXHAUSTED in {what}; retrying after downgrade"
        # the fused program DONATES the score buffer (donate_argnums):
        # a real mid-execution OOM on TPU/GPU leaves self.score deleted
        # and the retry would die on "Array has been deleted" instead
        # of the degraded program. Rebuild the score from the
        # materialized trees — last-ulp different from the incremental
        # accumulation (bit-exact resume vs an uninterrupted run is
        # forfeited past this point, which an OOM'd run already is).
        if getattr(self.score, "is_deleted", lambda: False)():
            self.score = self._place_score(
                self._score_dataset_binned(self.train_set))
            detail += "; score buffer was donated to the failed " \
                      "dispatch — rebuilt from trees"
        # the scan program donates the bagging carry too: a consumed
        # cache is dropped and re-drawn at the next refresh check
        if self._cached_bag is not None and getattr(
                self._cached_bag, "is_deleted", lambda: False)():
            self._cached_bag = None
        self._record_fault("oom", self.iter_, action, detail)
        return True

    def _flush_pending(self) -> None:
        if not self._pending_dev:
            return
        pending, self._pending_dev = self._pending_dev, []
        mappers = self.train_set.mappers
        used = self.train_set.used_feature_indices()
        for vec, cmask, proto, shrink, bias in pending:
            host = unpack_tree_host(vec, cmask, proto)
            tree = tree_from_arrays(host, mappers, used)
            if int(host.num_leaves) <= 1:
                # AsConstantTree (gbdt.cpp): a no-growth tree keeps only
                # the folded bias, unshrunk
                tree.leaf_value[:] = bias
            else:
                tree.apply_shrinkage(shrink)
                if bias:
                    tree.leaf_value = tree.leaf_value + bias
                    tree.internal_value = tree.internal_value + bias
            self._models_store.append(tree)

    def telemetry_tree_stats(self) -> Optional[Dict[str, float]]:
        """Leaves grown + split-gain sum of the LAST iteration's trees,
        for the telemetry recorder (obs/recorder.py). Reads the pending
        async device copies when trees are deferred — a small host fetch
        that only happens with telemetry active; the hot path never
        calls this. Returns None before the first iteration."""
        if self.iter_ <= 0:
            return None
        K = self.K
        leaves = 0
        gain = 0.0
        if len(self._pending_dev) >= K:
            for vec, cmask, proto, _, _ in self._pending_dev[-K:]:
                host = unpack_tree_host(np.asarray(vec), cmask, proto)
                nl = int(host.num_leaves)
                leaves += nl
                gain += float(np.sum(
                    np.asarray(host.split_gain)[: max(nl - 1, 0)]))
        elif len(self._models_store) >= K:
            for tree in self._models_store[-K:]:
                nl = int(tree.num_leaves)
                leaves += nl
                gain += float(np.sum(
                    np.asarray(tree.split_gain)[: max(nl - 1, 0)]))
        else:
            return None
        return {"trees": K, "leaves": leaves, "split_gain_sum": gain}

    def telemetry_comm_stats(self,
                             leaves: Optional[int] = None
                             ) -> Optional[Dict[str, object]]:
        """Per-iteration collective-payload accounting for the
        telemetry recorder (obs/recorder.py): bytes MODELED from the
        dtype-aware payload model (parallel/comms.py — the same model
        ``dryrun_multichip`` validates against the lowered StableHLO),
        not a wire measurement: one histogram reduction per split plus
        the root, so reductions == leaves grown — except the level
        grower's scatter path, which reduces the whole ``[L, F, B, 2]``
        level batch once per frontier level (modeled as ~log2 levels of
        a balanced tree, x L slots each). None when training is
        single-device (no collectives). ``leaves`` lets the recorder
        reuse the tree stats it already fetched; defaults to the
        num_leaves budget."""
        if self.mesh is None:
            return None
        from ..parallel import comms
        g = self.grow_cfg
        ncols = int(self.bins_T.shape[0])
        per_reduction = comms.payload_bytes(
            g.parallel_mode, ncols, g.num_bins, g.hist_comm,
            g.voting_top_k)
        if leaves is None:
            leaves = self.cfg.num_leaves * self.K
        if g.grower == "level" and g.hist_method == "scatter" \
                and g.parallel_mode == "data":
            import math
            per_tree = max(int(leaves) // max(self.K, 1), 2)
            levels = max(1, math.ceil(math.log2(per_tree)))
            n_reductions = self.K * levels * self.cfg.num_leaves
        else:
            n_reductions = int(leaves)
        world = int(self.mesh.devices.size)
        # the comm model's reduce-scatter arm: what each device
        # RECEIVES after the reduce phase (full broadcast when
        # gathered, 1/D chunk + O(D) SplitInfo records when sharded)
        post = comms.post_reduction_bytes(
            g.parallel_mode, ncols, g.num_bins, world, g.split_search,
            g.hist_comm, g.voting_top_k)
        return {
            "payload_bytes": int(per_reduction) * n_reductions,
            "post_reduction_bytes": int(post) * n_reductions,
            "hist_comm": g.hist_comm,
            "parallel_mode": g.parallel_mode,
            "split_search": g.split_search,
            "world": world,
        }

    def preload_models(self, trees: List[Tree],
                       score: Optional[np.ndarray] = None) -> None:
        """Continue training from an existing model (the reference's
        init_model / num_init_iteration path, gbdt.cpp Init +
        boosting.h:307): adopt the trees and rebuild the train score by
        binned traversal. boost_from_average stays un-refolded because
        iteration indices continue past 0.

        ``score``: install this [K, n] raw-score matrix verbatim
        instead of re-traversing the trees — the checkpoint-resume path
        (resilience/checkpoint.py) uses it because the incrementally
        accumulated f32 score and a fresh traversal can differ in the
        last ulp, which would break bit-exact resume."""
        self.models = list(trees)
        self._tree_weights = [1.0] * len(self.models)
        self.iter_ = len(self.models) // self.K
        if score is not None:
            self.score = self._place_score(
                np.asarray(score, np.float32).reshape(self.K, self.n))
        else:
            self.score = self._place_score(
                self._score_dataset_binned(self.train_set))

    def _place_score(self, score):
        """Install a [K, n] raw-score matrix per the shard residency:
        column-sharded over the mesh's data axis under device
        residency (a single-controller mesh — every eager consumer
        stays valid; the checkpoint layer saves/restores it through
        placement.fetch_global with per-shard fingerprints), a plain
        device array otherwise."""
        if getattr(self, "_residency", "host") != "device" \
                or self.mesh is None:
            return jnp.asarray(score)
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.device_put(
            jnp.asarray(score),
            NamedSharding(self.mesh, P(None, self.mesh.axis_names[0])))

    # ------------------------------------------------------------------
    def add_valid(self, dataset, name: str) -> None:
        score = self._score_dataset_binned(dataset)
        self.valid_sets.append(_ValidData(dataset, score, name))

    def _score_dataset_binned(self, dataset) -> jnp.ndarray:
        nv = dataset.num_data()
        is_rf = self.cfg.boosting == "rf"
        if self._fold_bias or is_rf:
            # bias lives inside tree leaf values (first iteration's trees
            # for gbdt/dart; every tree for rf)
            score = jnp.zeros((self.K, nv), jnp.float32)
        else:
            score = jnp.tile(jnp.asarray(self.init_score,
                                         jnp.float32)[:, None], (1, nv))
        ui = dataset.get_init_score()
        if ui is not None:
            score = score + jnp.asarray(ui, jnp.float32).reshape(self.K, nv)
        for i, tree in enumerate(self.models):
            k = i % self.K
            score = score.at[k].add(self._predict_tree_binned_host(
                tree, dataset))
        if is_rf and self.iter_ > 0:
            # rf scores are the running average of unscaled tree outputs
            score = score / self.iter_
        return score

    def _binned_node_arrays(self, tree: Tree):
        """Per-node (threshold_bin, is_cat, cat_bin_mask) in the train
        set's bin space. Numerical nodes loaded from a model file map the
        real threshold onto the current binning; categorical nodes
        reconstruct exact bin membership from the category bitset
        (the inverse of tree_from_arrays' bitset emission). Cached on the
        tree — node structure is immutable after growth."""
        cached = getattr(tree, "_binned_cache", None)
        if cached is not None and cached[0] is self.train_set:
            return cached[1]
        inner = self.train_set.inner_feature_index(tree.split_feature)
        nn = tree.num_nodes
        B = int(self.grow_cfg.num_bins)
        tb = np.zeros(nn, np.int32)
        isc = np.zeros(nn, bool)
        cmask = np.zeros((nn, B), bool)
        for i in range(nn):
            m = self.train_set.mappers[inner[i]]
            if tree.is_categorical_node(i):
                isc[i] = True
                nb = min(len(m.bin_to_cat), B)
                for b in range(nb):
                    cmask[i, b] = tree._cat_decision(
                        i, float(m.bin_to_cat[b]))
            elif tree.threshold_bin[i] >= 0:
                tb[i] = tree.threshold_bin[i]
            else:
                tb[i] = int(np.searchsorted(m.upper_bounds,
                                            tree.threshold[i], side="left"))
        out = (tb, isc, cmask)
        tree._binned_cache = (self.train_set, out)
        return out

    def _predict_tree_binned_host(self, tree: Tree,
                                  dataset) -> jnp.ndarray:
        bins_T = dataset.device_bins()
        if tree.num_leaves <= 1:
            base = float(tree.leaf_const[0]) if tree.is_linear \
                and getattr(tree, "leaf_const", None) is not None \
                else float(tree.leaf_value[0])
            return jnp.full((bins_T.shape[1],), base, jnp.float32)
        # map real feature index back to inner (used-feature) index
        inner = self.train_set.inner_feature_index(tree.split_feature)
        tb, isc, cmask = self._binned_node_arrays(tree)
        # pad to the configured num_leaves so the jitted traversal
        # compiles once per dataset, not once per tree
        L = max(self.cfg.num_leaves, tree.num_leaves)
        nn = L - 1

        def pad(a, size, fill, dt):
            out = np.full((size,), fill, dt)
            out[: len(a)] = a
            return out

        if self.feat_is_cat is not None:
            B = cmask.shape[1]
            cm_pad = np.zeros((nn, B), bool)
            cm_pad[: len(cmask)] = cmask
            cat_args = (jnp.asarray(pad(isc, nn, False, bool)),
                        jnp.asarray(cm_pad))
        else:
            cat_args = (None, None)
        node_args = (
            jnp.asarray(pad(inner, nn, 0, np.int32)),
            jnp.asarray(pad(tb, nn, 0, np.int32)),
            jnp.asarray(pad((tree.decision_type & 2) != 0, nn, False, bool)),
            jnp.asarray(pad(tree.left_child, nn, -1, np.int32)),
            jnp.asarray(pad(tree.right_child, nn, -1, np.int32)))
        if tree.is_linear and getattr(tree, "leaf_const", None) is not None:
            leaves = _tree_leaves_binned(*node_args, self.feat_nan_bin,
                                         bins_T, *cat_args)
            return self._linear_values_binned(tree, dataset, leaves)
        return _tree_values_binned(
            *node_args,
            jnp.asarray(pad(tree.leaf_value, L, 0.0, np.float32)),
            self.feat_nan_bin, bins_T, *cat_args)

    def _init_cegb(self, cfg) -> None:
        """CEGB state (cost_effective_gradient_boosting.hpp IsEnable):
        model-level feature-use flags and per-(row, feature) acquisition
        bits persist across trees."""
        enabled = (cfg.cegb_tradeoff < 1.0 or cfg.cegb_penalty_split > 0.0
                   or len(cfg.cegb_penalty_feature_coupled) > 0
                   or len(cfg.cegb_penalty_feature_lazy) > 0)
        self.cegb_enabled = enabled
        self.cegb_lazy = len(cfg.cegb_penalty_feature_lazy) > 0
        if not enabled:
            return
        used = self.train_set.used_feature_indices()

        def per_feature(lst):
            out = np.zeros((self.F,), np.float32)
            for i, r in enumerate(used):
                if int(r) < len(lst):
                    out[i] = lst[int(r)]
            return jnp.asarray(out)

        self._cegb_pen_coupled = per_feature(
            cfg.cegb_penalty_feature_coupled)
        self._cegb_pen_lazy = per_feature(cfg.cegb_penalty_feature_lazy)
        self._cegb_coupled = jnp.zeros((self.F,), jnp.bool_)
        self._cegb_lazy_used = (
            jnp.zeros((self.n, self.F), jnp.bool_) if self.cegb_lazy
            else None)

    def _load_forced_splits(self, cfg) -> Optional[tuple]:
        """forcedsplits_filename JSON -> BFS-ordered (leaf_slot, feature,
        bin) arrays (ForceSplits, serial_tree_learner.cpp:620). Leaf slots
        are precomputable because forced splits run first and in order:
        the split at sequence index i sends its right child to slot
        i + 1."""
        fn = cfg.forcedsplits_filename
        if not fn:
            return None
        import json as _json
        from collections import deque
        with open(fn) as fh:
            root = _json.load(fh)
        if not root:
            return None
        used = self.train_set.used_feature_indices()
        inner_of = {int(r): i for i, r in enumerate(used)}
        from ..ops.binning import BinType
        leafs, feats, bins_ = [], [], []
        q = deque([(root, 0)])
        while q:
            node, slot = q.popleft()
            real = int(node["feature"])
            inner = inner_of.get(real)
            if inner is None or \
                    self.train_set.mappers[inner].bin_type != \
                    BinType.NUMERICAL:
                import warnings
                warnings.warn(
                    f"forced split on unusable/categorical feature {real} "
                    "ignored (with its subtree)")
                continue
            thr = float(node["threshold"])
            t = int(self.train_set.mappers[inner].value_to_bin(
                np.asarray([thr]))[0])
            leafs.append(slot)
            feats.append(inner)
            bins_.append(t)
            right_slot = len(leafs)
            if node.get("left"):
                q.append((node["left"], slot))
            if node.get("right"):
                q.append((node["right"], right_slot))
        if not leafs:
            return None
        return (jnp.asarray(leafs, jnp.int32),
                jnp.asarray(feats, jnp.int32),
                jnp.asarray(bins_, jnp.int32))

    def _parse_interaction_constraints(self, cfg) -> Optional[jnp.ndarray]:
        """interaction_constraints -> [G, F_used] bool group masks
        (config.h interaction_constraints; features outside every group
        are unusable, col_sampler.hpp)."""
        ic = cfg.interaction_constraints
        if ic is None or ic == "" or ic == []:
            return None
        if isinstance(ic, str):
            import ast
            ic = list(ast.literal_eval(ic if ic.startswith("[[")
                                       else "[" + ic + "]"))
        names = list(getattr(self.train_set, "_feature_names", []) or [])
        used = self.train_set.used_feature_indices()
        inner_of = {int(r): i for i, r in enumerate(used)}
        G = np.zeros((len(ic), self.F), bool)
        for gi, grp in enumerate(ic):
            for item in grp:
                real = names.index(item) if isinstance(item, str) \
                    else int(item)
                if real in inner_of:
                    G[gi, inner_of[real]] = True
        return jnp.asarray(G)

    # ------------------------------------------------------------------
    # linear leaves (LinearTreeLearner::CalculateLinear analog)
    # ------------------------------------------------------------------
    def _fit_linear(self, dev_tree, row_leaf, grad, hess, row_w,
                    is_first: bool):
        """Fit per-leaf linear models. Returns (const_dev, coeff_dev,
        pred_dev, feats_inner: list, kmax)."""
        from ..ops.linear import branch_features_per_leaf, fit_leaf_linear
        from ..ops.binning import BinType
        L = self.cfg.num_leaves
        num_leaves = int(np.asarray(dev_tree.num_leaves))
        mappers = self.train_set.mappers

        def is_num(f):
            return mappers[f].bin_type == BinType.NUMERICAL

        if is_first or num_leaves <= 1:
            # first iteration's trees stay constant
            # (linear_tree_learner.cpp:185-190 is_first_tree path)
            return (dev_tree.leaf_value, None,
                    gather_small(dev_tree.leaf_value, row_leaf),
                    [[] for _ in range(L)], 0)
        feats = branch_features_per_leaf(
            np.asarray(dev_tree.split_feature),
            np.asarray(dev_tree.left_child),
            np.asarray(dev_tree.right_child),
            np.asarray(dev_tree.leaf_parent), num_leaves, is_num)
        feats += [[] for _ in range(L - num_leaves)]
        kmax = max((len(f) for f in feats), default=0)
        if kmax == 0:
            return (dev_tree.leaf_value, None,
                    gather_small(dev_tree.leaf_value, row_leaf), feats, 0)
        lf = np.zeros((L, kmax), np.int32)
        nf = np.zeros((L,), np.int32)
        for i, f in enumerate(feats):
            lf[i, : len(f)] = f
            nf[i] = len(f)
        const, coeff, pred = fit_leaf_linear(
            self.raw, row_leaf, grad, hess, row_w,
            jnp.asarray(lf), jnp.asarray(nf), dev_tree.leaf_value,
            self.cfg.linear_lambda)
        return (const, coeff, pred, feats, kmax)

    def _attach_linear(self, tree, lin, shrinkage: float) -> None:
        """Move the device fit into the host Tree (real feature ids;
        near-zero coefficients dropped like the kZeroThreshold filter)."""
        const, coeff, _, feats, kmax = lin
        used = self.train_set.used_feature_indices()
        Lr = tree.num_leaves
        tree.is_linear = True
        tree.leaf_const = np.asarray(const, np.float64)[:Lr] * shrinkage
        coeff_np = None if coeff is None else np.asarray(coeff, np.float64)
        leaf_features, leaf_coeff = [], []
        for i in range(Lr):
            fs, cs = [], []
            for j, f in enumerate(feats[i]):
                c = 0.0 if coeff_np is None else coeff_np[i, j]
                if abs(c) > 1e-35:
                    fs.append(int(used[f]))
                    cs.append(c * shrinkage)
            leaf_features.append(fs)
            leaf_coeff.append(cs)
        tree.leaf_features = leaf_features
        tree.leaf_coeff = leaf_coeff

    def _linear_values_binned(self, tree, dataset, leaves):
        """Per-row outputs of a linear tree over binned leaf assignment
        (AddPredictionToScore's linear path, tree.cpp:120-150). Arrays
        are padded to (cfg.num_leaves, pow2 feature count) so the jitted
        evaluator compiles a handful of shapes, not one per tree."""
        Lr = tree.num_leaves
        L = max(self.cfg.num_leaves, Lr)
        km = max((len(f) for f in tree.leaf_features), default=0)
        const = np.zeros((L,), np.float64)
        const[:Lr] = tree.leaf_const[:Lr]
        if km == 0:
            return jnp.asarray(const, jnp.float32)[leaves]
        kp = 1
        while kp < km:
            kp *= 2
        raw = dataset.device_raw()
        lf = np.zeros((L, kp), np.int32)
        nf = np.zeros((L,), np.int32)
        cf = np.zeros((L, kp), np.float64)
        lv = np.zeros((L,), np.float64)
        lv[:Lr] = tree.leaf_value[:Lr]
        for i in range(Lr):
            inner = dataset.inner_feature_index(
                np.asarray(tree.leaf_features[i], np.int32))
            lf[i, : len(inner)] = inner
            nf[i] = len(inner)
            cf[i, : len(inner)] = tree.leaf_coeff[i]
        return _linear_eval(
            jnp.asarray(const, jnp.float32), jnp.asarray(cf, jnp.float32),
            jnp.asarray(lf), jnp.asarray(nf),
            jnp.asarray(lv, jnp.float32), raw, leaves)

    # ------------------------------------------------------------------
    # sampling strategies (bagging.hpp / goss.hpp analogs)
    # ------------------------------------------------------------------
    def _row_weights(self, it: int, grad: jnp.ndarray,
                     hess: jnp.ndarray) -> jnp.ndarray:
        cfg = self.cfg
        n = self.n
        if cfg.data_sample_strategy == "goss":
            # GOSS (goss.hpp:30): keep top |g*h|, sample + amplify the rest
            if it < max(1, int(1.0 / cfg.learning_rate)):
                return jnp.ones((n,), jnp.float32)
            key = jax.random.fold_in(
                jax.random.PRNGKey(cfg.bagging_seed), it)
            metric = jnp.abs(grad) * hess if grad.ndim == 1 else \
                jnp.sum(jnp.abs(grad) * hess, axis=0)
            thresh = jnp.quantile(metric, 1.0 - cfg.top_rate)
            top = metric >= thresh
            rest_prob = cfg.other_rate / max(1e-12, 1.0 - cfg.top_rate)
            amplify = (1.0 - cfg.top_rate) / max(1e-12, cfg.other_rate)
            u = jax.random.uniform(key, (n,))
            other = (~top) & (u < rest_prob)
            return top.astype(jnp.float32) + \
                other.astype(jnp.float32) * amplify
        if cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0
                                     or cfg.pos_bagging_fraction < 1.0
                                     or cfg.neg_bagging_fraction < 1.0):
            if it % cfg.bagging_freq != 0 and self._cached_bag is not None:
                return self._cached_bag
            key = jax.random.fold_in(
                jax.random.PRNGKey(cfg.bagging_seed), it)
            u = jax.random.uniform(key, (n,))
            if (cfg.pos_bagging_fraction < 1.0
                    or cfg.neg_bagging_fraction < 1.0):
                is_pos = self.label > 0
                frac = jnp.where(is_pos, cfg.pos_bagging_fraction,
                                 cfg.neg_bagging_fraction)
                bag = (u < frac).astype(jnp.float32)
            else:
                bag = (u < cfg.bagging_fraction).astype(jnp.float32)
            self._cached_bag = bag
            return bag
        return jnp.ones((n,), jnp.float32)

    _cached_bag: Optional[jnp.ndarray] = None

    def _bag_live(self) -> bool:
        """Live bagging gate, re-read from cfg on every call
        (reset_parameter may toggle bagging mid-training): the ONE
        definition of ``_row_weights``' bagging branch condition,
        shared by the fused driver, the scan dispatch and the scan
        abort so the gates can never drift apart."""
        cfg = self.cfg
        return cfg.bagging_freq > 0 and (
            cfg.bagging_fraction < 1.0
            or cfg.pos_bagging_fraction < 1.0
            or cfg.neg_bagging_fraction < 1.0)

    def _feature_mask(self) -> jnp.ndarray:
        """Per-tree column sampling (ColSampler::ResetByTree analog)."""
        cfg = self.cfg
        usable = self.train_set.usable_feature_mask()
        if cfg.feature_fraction >= 1.0:
            return jnp.asarray(usable)
        idx = np.where(usable)[0]
        k = max(1, int(round(len(idx) * cfg.feature_fraction)))
        chosen = self._feature_rng.choice(idx, size=k, replace=False)
        mask = np.zeros((self.F,), bool)
        mask[chosen] = True
        return jnp.asarray(mask)

    # ------------------------------------------------------------------
    def _gradients(self, score: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        g, h = self.objective.grad_hess(
            score if self.K > 1 else score[0], self.label, self.weight)
        if self.K == 1:
            g, h = g[None, :], h[None, :]
        return g, h

    # ------------------------------------------------------------------
    # fused-iteration fast path: one XLA program per boosting iteration
    # ------------------------------------------------------------------
    def _fused_ok(self) -> bool:
        """The fused step covers exactly the deferred-materialization
        configs (plain gbdt, no valid sets, single mesh-less device) —
        the same gate as ``defer`` in the eager path — minus the
        features whose host-side control flow is data-dependent (CEGB's
        cost-state carry, RenewTreeOutput objectives, GOSS's
        gradient-dependent sampling, linear leaves)."""
        cfg = self.cfg
        return (self.mesh is None
                and cfg.boosting == "gbdt"
                and not self.valid_sets
                and not cfg.linear_tree
                and not self.cegb_enabled
                and not self._goss_active
                and self.objective is not None
                and not getattr(self.objective, "need_renew", False)
                # ranking objectives carry host-side per-iteration state
                # (lambdarank position biases, xendcg's key counter) —
                # inside a traced program those updates would run once
                # at trace time and then freeze
                and not getattr(self.objective, "is_ranking", False))

    def _step_ctx(self) -> _StepCtx:
        """The static per-iteration context both fused programs close
        over (see :class:`_StepCtx`). Rebuilt per program build so an
        OOM downgrade's new ``grow_cfg`` is picked up."""
        gcfg = self.grow_cfg
        # fault injection (test harness): the schedule is static per
        # engine, so the poisoning folds into the traced program as a
        # where(it == N) — zero recompiles, exact device-side replay
        inj_grad = jnp.asarray(self._fault_plan.iters("nan_grad"),
                               jnp.int32) \
            if self._fault_plan.iters("nan_grad") else None
        inj_hess = jnp.asarray(self._fault_plan.iters("nan_hess"),
                               jnp.int32) \
            if self._fault_plan.iters("nan_hess") else None
        return _StepCtx(
            gcfg=gcfg, K=self.K, obj=self.objective,
            nf_policy=self._nf_policy,
            quant=gcfg.quantized and gcfg.stochastic,
            bynode=gcfg.bynode < 1.0,
            base_key=self._base_key, bynode_key=self._bynode_key,
            inj_grad=inj_grad, inj_hess=inj_hess)

    def _fused_tree_proto(self):
        """The pending-tree proto (ShapeDtypeStructs for unpack at
        flush) is config-static: derive it once by abstract eval
        instead of returning the whole dev_tree pytree every call."""
        if self._fused_proto is not None:
            return self._fused_proto
        gcfg = self.grow_cfg
        quant = gcfg.quantized and gcfg.stochastic
        bynode = gcfg.bynode < 1.0
        sds = jax.ShapeDtypeStruct((self.n,), jnp.float32)
        key_sds = jax.ShapeDtypeStruct(self._base_key.shape,
                                       self._base_key.dtype)
        # NB: abstract stand-ins only — _feature_mask() here would
        # consume a host-RNG draw and desync the stream vs eager
        fmask_sds = jax.ShapeDtypeStruct((self.F,), jnp.bool_)
        proto, _ = jax.eval_shape(
            functools.partial(grow_tree_impl, gcfg),
            self.bins_T, sds, sds, sds,
            fmask_sds, self.feat_num_bins, self.feat_nan_bin,
            self.monotone, self.feat_is_cat,
            key_sds if quant else None,
            self.interaction_groups, self.forced, None,
            key_sds if bynode else None, self._bundle_dev)
        self._fused_proto = proto
        return proto

    def _get_fused_fn(self):
        if self._fused_fn is not None:
            return self._fused_fn
        self._fused_tree_proto()
        ctx = self._step_ctx()

        def step(score, it, shrink, row_w, fmask, bins_T, fnb, fnan,
                 label, weight, monotone, feat_is_cat, igroups, forced,
                 bundle):
            # the whole iteration body lives in the module-level
            # _fused_iter_step — the scan path traces the same ops
            return _fused_iter_step(ctx, score, it, shrink, row_w,
                                    fmask, bins_T, fnb, fnan, label,
                                    weight, monotone, feat_is_cat,
                                    igroups, forced, bundle)

        # donate the old score buffer (it is consumed) — except on CPU,
        # where XLA ignores donation and warns
        self._fused_fn = register_jit(
            "gbdt/fused_iter",
            jax.jit(step, donate_argnums=_donate(0)),
            max_signatures=4)
        return self._fused_fn

    # ------------------------------------------------------------------
    # multi-iteration fused scan: a whole window of boosting iterations
    # as ONE lax.scan program with donated carries (docs/FUSED.md)
    # ------------------------------------------------------------------
    def _scan_ok(self) -> bool:
        """Refinement of ``_fused_ok``: configs whose per-iteration
        host work the scan body can carry on device. Host-RNG
        consumers (``feature_fraction`` draws a np.RandomState mask per
        tree) and mid-window host injections (``oom@N``) fall back to
        the per-iteration fused path; bagging (device fold_in keys),
        pos/neg bagging, bynode sampling, quantized training and every
        grower ride the carry."""
        cfg = self.cfg
        return (cfg.feature_fraction >= 1.0
                and cfg.boosting == "gbdt"
                and not self._fault_plan.iters("oom"))

    def _scan_window(self) -> int:
        """Iterations the next dispatch may cover: the configured
        budget, clamped to the engine-provided lookahead horizon (the
        distance to the next point an outside consumer — checkpoint
        cadence, end of training, an unknown callback — reads
        per-iteration state the window would skate past)."""
        budget = resolve_scan_iters(self.cfg.fused_scan_iters)
        if budget <= 1 or not self._scan_ok():
            return 1
        return max(1, min(budget, self._scan_horizon))

    def _make_bag_refresh(self):
        """Traced twin of ``_row_weights``' bagging branch: draw the
        in-bag weight vector for iteration ``it`` from the identical
        fold_in key schedule, so carry-resident bagging is bit-equal
        to the host-side draws of the eager/fused paths."""
        cfg = self.cfg
        n = self.n
        seed_key = jax.random.PRNGKey(cfg.bagging_seed)
        pos, neg = cfg.pos_bagging_fraction, cfg.neg_bagging_fraction
        frac = cfg.bagging_fraction
        posneg = pos < 1.0 or neg < 1.0

        def fresh(it, label):
            key = jax.random.fold_in(seed_key, it)
            u = jax.random.uniform(key, (n,))
            if posneg:
                is_pos = label > 0
                fr = jnp.where(is_pos, pos, neg)
                return (u < fr).astype(jnp.float32)
            return (u < frac).astype(jnp.float32)

        return fresh

    def _get_scan_fn(self, W: int, bag_live: bool):
        """Build (and cache) the W-iteration scan program: carries are
        the donated score matrix, the bagging weight vector and the
        natural-stop flag; the stacked per-iteration tree packs, leaf
        counts and guard flags come back as the scan's ys — one
        N-slot output buffer fetched per window, not per iteration."""
        key = (W, bag_live)
        fn = self._scan_fns.get(key)
        if fn is not None:
            return fn
        self._fused_tree_proto()
        ctx = self._step_ctx()
        freq = max(1, self.cfg.bagging_freq)
        fresh_bag = self._make_bag_refresh() if bag_live else None
        from jax import lax

        def scan_fn(score, bag, it0, shrink, fmask, bins_T, fnb, fnan,
                    label, weight, monotone, feat_is_cat, igroups,
                    forced, bundle):
            def body(carry, it):
                score, bag, stop = carry
                if bag_live:
                    # refresh cadence traced from the absolute
                    # iteration — identical to _row_weights' host
                    # check; a stopped window never consumes draws
                    refresh = jnp.logical_and(it % freq == 0,
                                              jnp.logical_not(stop))
                    bag = lax.cond(refresh,
                                   lambda b: fresh_bag(it, label),
                                   lambda b: b, bag)
                new_score, outs, flags = _fused_iter_step(
                    ctx, score, it, shrink, bag, fmask, bins_T, fnb,
                    fnan, label, weight, monotone, feat_is_cat,
                    igroups, forced, bundle)
                vecs = jnp.stack([o[0] for o in outs])
                cmasks = jnp.stack([o[1] for o in outs])
                nls = jnp.stack([o[2] for o in outs])
                # natural-stop gating: once an iteration grows nothing
                # (and no fault demoted it — skip_tree leaves look
                # identical), later slots become score no-ops, exactly
                # where the per-iteration driver would have stopped;
                # the host drain discards their emitted trees
                new_score = jnp.where(stop, score, new_score)
                stalled = jnp.logical_and(jnp.all(nls <= 1),
                                          jnp.all(flags == 0))
                return ((new_score, bag, jnp.logical_or(stop, stalled)),
                        (vecs, cmasks, nls, flags))

            its = it0 + jnp.arange(W, dtype=jnp.int32)
            carry0 = (score, bag, jnp.asarray(False))
            (score, bag, _), ys = lax.scan(body, carry0, its)
            return (score, bag) + ys

        # donate the score AND bagging carries (both are consumed) —
        # except on CPU, where XLA ignores donation and warns
        fn = register_jit("gbdt/fused_scan",
                          jax.jit(scan_fn, donate_argnums=_donate(0, 1)),
                          max_signatures=4)
        self._scan_fns[key] = fn
        return fn

    # tpulint: hot
    def _dispatch_scan_window(self, W: int) -> bool:
        """Run the next ``W`` boosting iterations as one scan program
        and queue the results; pops hand them to the driver one
        iteration at a time so callbacks/telemetry keep their
        per-iteration cadence. The batched ``jax.device_get`` below is
        the scan pipeline's ONE window-boundary sync point (tpulint
        TPL002 baseline): every per-iteration fetch, dispatch and
        driver pass between window edges is gone."""
        from ..utils.timer import timed

        cfg = self.cfg
        it0 = self.iter_
        bag_live = self._bag_live()
        with timed("boosting/bagging"):
            if bag_live:
                freq = max(1, cfg.bagging_freq)
                if it0 % freq == 0:
                    # refresh-aligned entry: the body's first slot
                    # redraws the carry unconditionally, so the host
                    # draw would be discarded — donate a placeholder
                    # instead of a wasted [n] uniform pass
                    bag_key_it = None
                    bag0 = jnp.zeros((self.n,), jnp.float32)
                else:
                    # the WINDOW-ENTRY bag follows the eager rule at
                    # it0 (reuse the cache, else draw fresh at it0).
                    # Remember which iteration it was KEYED at — a
                    # sequential cache always came from the last
                    # refresh (checkpoint restore re-derives it there
                    # too) — so the OOM-retry path below can reproduce
                    # the exact draw after a failed dispatch consumed
                    # (donated) it.
                    bag_key_it = (it0 // freq) * freq \
                        if self._cached_bag is not None else it0
                    bag0 = self._row_weights(it0, None, None)
            else:
                # a fresh ones buffer per window: the carry is donated,
                # so the shared _row_w_ones must not be consumed
                bag0 = jnp.ones((self.n,), jnp.float32)
            if self._fmask_cached is None:
                self._fmask_cached = self._feature_mask()
            fmask = self._fmask_cached
        # label defined in obs/trace.py (FUSED_SCAN_PHASE): the
        # jax-free tracing layer, the bench and the per-iteration
        # host-gap derivation all key on this exact phase name
        with timed(FUSED_SCAN_PHASE):
            def dispatch():
                # re-reads _get_scan_fn so an OOM downgrade's rebuilt
                # program is picked up on the retry — and re-derives
                # the bagging carry if the failed dispatch already
                # consumed (donated) it: re-drawn at the iteration the
                # entry bag was KEYED at (not it0 — a cache-served
                # entry bag came from the last refresh iteration, and
                # _row_weights(it0) on the now-empty cache would draw
                # a fresh vector no other path ever uses)
                nonlocal bag0
                if getattr(bag0, "is_deleted", lambda: False)():
                    if not bag_live:
                        bag0 = jnp.ones((self.n,), jnp.float32)
                    elif bag_key_it is None:
                        # refresh-aligned placeholder (overwritten by
                        # the body's first slot)
                        bag0 = jnp.zeros((self.n,), jnp.float32)
                    else:
                        self._cached_bag = None
                        bag0 = self._row_weights(bag_key_it, None,
                                                 None)
                return self._get_scan_fn(W, bag_live)(
                    self.score, bag0, jnp.asarray(it0, jnp.int32),
                    jnp.asarray(self._shrinkage, jnp.float32), fmask,
                    self.bins_T, self.feat_num_bins, self.feat_nan_bin,
                    self.label, self.weight, self.monotone,
                    self.feat_is_cat, self.interaction_groups,
                    self.forced, self._bundle_dev)

            out = self._run_with_oom_degrade(dispatch,
                                             "fused scan window")
            new_score, new_bag, vecs, cmasks, nls, flags = out
            # the ONE legal sync of the window: the whole window's tree
            # packs, leaf counts and guard flags cross device->host as
            # a single batched fetch (docs/FUSED.md)
            vecs_h, cmasks_h, nls_h, flags_h = jax.device_get(
                (vecs, cmasks, nls, flags))
        self.score = new_score
        if bag_live:
            self._cached_bag = new_bag
        # the dispatch-time shrinkage is stamped into the pend: the
        # traced window already scored contrib * THIS value, so pops
        # must flush trees with it even if _shrinkage moves later
        # (a learning_rate reset additionally aborts the pend —
        # basic.py reset_parameter — so the new rate takes effect at
        # the very next iteration like the per-iteration path)
        self._scan_pend = {"it0": it0, "W": W, "pos": 0,
                           "shrink": self._shrinkage,
                           "vec": vecs_h, "cmask": cmasks_h,
                           "nl": nls_h, "flags": flags_h}
        from ..obs.registry import registry as _registry
        _registry.counter("fused_scan_windows").inc()
        return self._pop_scan_iter()

    # tpulint: hot
    def _pop_scan_iter(self) -> bool:
        """Commit ONE precomputed window iteration to the driver state:
        defer its K trees (host numpy slices of the batched pack — no
        device traffic), queue its guard flags for the one-late drain,
        and advance the iteration counter. The no-growth / fault-raise
        decisions stay in ``train_one_iter``'s existing host logic,
        which sees exactly the per-iteration stream it always saw."""
        p = self._scan_pend
        j = p["pos"]
        it = p["it0"] + j
        self._push_guard_flags(it, p["flags"][j])
        fold_now = it == 0 and self._fold_bias
        for k in range(self.K):
            bias = float(self.init_score[k]) if fold_now else 0.0
            self._defer_tree(p["vec"][j, k], p["cmask"][j, k],
                             self._fused_proto, p["nl"][j, k],
                             p["shrink"], bias)
        p["pos"] += 1
        self._scan_last = {"window": int(p["W"]), "pos": int(j),
                           "dispatch": j == 0}
        if p["pos"] >= p["W"]:
            self._scan_pend = None
        self.iter_ += 1
        return False

    def _abort_scan_window(self,
                           next_iter: Optional[int] = None) -> None:
        """Discard precomputed lookahead iterations (rollback, model
        replacement, a custom-gradient update arriving mid-window).
        The window's final score includes the discarded slots, so the
        score is rebuilt from the materialized trees — last-ulp
        different from incremental accumulation, the same forfeit as
        the OOM donation rebuild.

        ``next_iter``: the iteration that will train next —
        ``iter_`` by default, but ``rollback_one_iter`` passes
        ``iter_ - 1`` because it decrements AFTER this abort (an
        on-cadence ``iter_`` would otherwise skip the cache
        re-derivation that the post-rollback off-cadence iteration
        needs)."""
        if self._scan_pend is None:
            return
        self._scan_pend = None
        self._scan_last = None
        self.score = self._place_score(
            self._score_dataset_binned(self.train_set))
        # the carry-resident bag ran ahead with the window; re-derive
        # the cache at the LAST REFRESH iteration so the next
        # _row_weights reuses the same draw the per-iteration path
        # would (checkpoint restore does the identical re-derivation;
        # drawing fresh at an off-cadence iteration would silently
        # fork the bagging stream)
        next_iter = self.iter_ if next_iter is None \
            else max(0, next_iter)
        self._cached_bag = None
        if self._bag_live():
            freq = self.cfg.bagging_freq
            last_refresh = (next_iter // freq) * freq
            if last_refresh < next_iter:
                self._row_weights(last_refresh, None, None)

    def telemetry_scan_stats(self) -> Optional[Dict[str, object]]:
        """Scan-window position of the LAST committed iteration for
        the telemetry recorder (obs/recorder.py): ``window`` size,
        ``pos`` inside it, and whether this iteration carried the
        window dispatch (its event absorbs the whole window's device
        phase time). None when the iteration ran per-iteration."""
        if self._scan_last is None:
            return None
        return dict(self._scan_last)

    # tpulint: hot
    def _train_one_iter_fused(self) -> bool:
        """One boosting iteration as a single device program.

        Host-side RNG consumers (per-tree feature_fraction mask,
        bagging weights) stay OUTSIDE the program and feed it as
        arguments so their streams match the eager path exactly; the
        finished tree comes back the same deferred route
        (_pending_dev + async copies) the eager defer branch uses.

        When a multi-iteration scan window is active (or can start —
        Config.fused_scan_iters, docs/FUSED.md), the iteration is
        popped from / dispatched as one whole-window program
        instead."""
        from ..utils.timer import timed

        if self._scan_pend is not None:
            return self._pop_scan_iter()
        W = self._scan_window()
        if W > 1:
            return self._dispatch_scan_window(W)

        cfg = self.cfg
        it = self.iter_
        with timed("boosting/bagging"):
            # evaluate the bagging gate LIVE (not the __init__-time
            # _bag_active snapshot): reset_parameter may turn bagging
            # on/off mid-training (LGBM_BoosterResetParameter), and the
            # eager path's _row_weights re-reads cfg every iteration
            bag_live = self._bag_live()
            if bag_live:
                row_w = self._row_weights(it, None, None)
            else:
                if self._row_w_ones is None:
                    self._row_w_ones = jnp.ones((self.n,), jnp.float32)
                row_w = self._row_w_ones
            if cfg.feature_fraction < 1.0:
                fmask = self._feature_mask()
            else:
                if self._fmask_cached is None:
                    self._fmask_cached = self._feature_mask()
                fmask = self._fmask_cached
        with timed("boosting/fused_iter"):
            # thunk re-reads _get_fused_fn so an OOM downgrade's
            # rebuilt program is picked up on the retry
            new_score, outs, guard_flags = self._run_with_oom_degrade(
                lambda: self._get_fused_fn()(
                    self.score, jnp.asarray(it, jnp.int32),
                    jnp.asarray(self._shrinkage, jnp.float32), row_w,
                    fmask, self.bins_T, self.feat_num_bins,
                    self.feat_nan_bin, self.label, self.weight,
                    self.monotone, self.feat_is_cat,
                    self.interaction_groups, self.forced,
                    self._bundle_dev),
                "fused iteration")
        self.score = new_score
        self._push_guard_flags(it, guard_flags)
        fold_now = it == 0 and self._fold_bias
        for k, (vec, cmask, num_leaves) in enumerate(outs):
            bias = float(self.init_score[k]) if fold_now else 0.0
            self._defer_tree(vec, cmask, self._fused_proto, num_leaves,
                             self._shrinkage, bias)
        self.iter_ += 1
        return False

    # tpulint: hot
    def _defer_tree(self, vec, cmask, proto, num_leaves, shrink,
                    bias) -> None:
        """Queue one finished device tree for lazy host materialization
        (consumed by _flush_pending; shared by the eager defer branch
        and the fused path — keep the pending-tuple shape in one
        place)."""
        try:
            vec.copy_to_host_async()
            cmask.copy_to_host_async()
            num_leaves.copy_to_host_async()
        except AttributeError:  # non-jax arrays (tests/cpu)
            pass
        self._pending_dev.append((vec, cmask, proto, shrink, bias))
        self._tree_weights.append(1.0)
        self._nl_async.append(num_leaves)

    # tpulint: hot
    def train_one_iter(self,
                       custom_grad: Optional[np.ndarray] = None,
                       custom_hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (TrainOneIter, gbdt.cpp:344).
        Returns True if no tree could be grown (training finished)."""
        cfg = self.cfg
        it = self.iter_

        # scan-window bookkeeping: the telemetry marker tracks only the
        # path actually taken this iteration, and precomputed lookahead
        # survives ONLY while _pop_scan_iter will serve this iteration
        # — a custom-gradient update, or a _fused_ok flip mid-pend
        # (add_valid between direct update() calls), would otherwise
        # train eagerly from the window-ahead score with stale packs
        # still queued
        self._scan_last = None
        if self._scan_pend is not None and (custom_grad is not None
                                            or not self._fused_ok()):
            self._abort_scan_window()

        # non-finite guard flags from the previous (async) program,
        # checked one iteration late like the tree queue below —
        # raises/records per nonfinite_policy (resilience/)
        self._drain_guard_flags()

        # checkpoint-restored no-growth marker: the snapshot's final
        # iteration grew nothing, so an uninterrupted run's next
        # update() would stop BEFORE growing — byte-exact resume must
        # stop at the same point instead of regrowing an extra
        # constant tree (resilience/checkpoint.py "stalled")
        if self._resume_stalled:
            self._resume_stalled = False
            if custom_grad is None:
                self._finished_natural = True
                return True

        # deferred-mode no-growth check, one iteration late: the async
        # copies were started last iteration so this read doesn't stall.
        # Custom gradients always get a fresh attempt (the reference's
        # TrainOneIterCustom never short-circuits on past iterations).
        # A recent fault suppresses the short-circuit: a skip_tree
        # demotion is indistinguishable from natural no-growth in the
        # leaf counts alone. The STICKY marker (not the drain's return
        # value) carries that across out-of-band drains — a checkpoint
        # callback draining between iterations must not eat it.
        if self._nl_async:
            nls = [int(np.asarray(x)) for x in self._nl_async]
            self._nl_async = []
            fault_recent, self._fault_recent = self._fault_recent, False
            if custom_grad is None and not fault_recent \
                    and all(nl <= 1 for nl in nls):
                # remembered past the drain: a checkpoint written after
                # this point must still carry the stalled marker
                self._finished_natural = True
                # lookahead iterations a scan window precomputed past
                # the natural stop never happened: the scan body's stop
                # carry already froze the score at this point, so the
                # queued packs are simply dropped
                self._scan_pend = None
                return True

        # Fast path: the whole iteration (gradients -> grow -> tree pack
        # -> contrib gather -> score update) as ONE jitted program. The
        # decomposition on a real chip (benchmarks/DECOMP_r05.txt)
        # showed each separate dispatch paying ~15-25 ms of launch
        # latency through the device tunnel — ~106 ms/iter against a
        # <1 ms bandwidth floor — so launch count, not FLOPs, was the
        # second-largest cost of an iteration.
        if custom_grad is None and self._fused_ok():
            return self._train_one_iter_fused()

        # DART: pick and temporarily drop trees (dart.hpp DroppingTrees)
        drop_idx: List[int] = []
        if cfg.boosting == "dart" and self.models:
            drop_idx = self._dart_select_drop()
            if drop_idx:
                self._dart_apply_drop(drop_idx)

        # phase annotations: the USE_TIMETAG points of GBDT::TrainOneIter
        # (gbdt.cpp:221-492) — see utils/timer.py
        from ..utils.timer import timed

        with timed("boosting/gradients"):
            if custom_grad is not None:
                grad = jnp.asarray(custom_grad,
                                   jnp.float32).reshape(self.K, self.n)
                hess = jnp.asarray(custom_hess,
                                   jnp.float32).reshape(self.K, self.n)
            elif cfg.boosting == "rf":
                # RF trees are independent: gradients always from the init
                # score, never the running average (rf.hpp Boosting)
                init = jnp.tile(jnp.asarray(self.init_score,
                                            jnp.float32)[:, None],
                                (1, self.n))
                grad, hess = self._gradients(init)
            else:
                grad, hess = self._gradients(self.score)

        # non-finite guard (+ fault injection) before anything consumes
        # the gradients; GOSS sampling below sees the clamped values
        grad, hess, gh_flag = self._gh_guard(it, grad, hess)

        with timed("boosting/bagging"):
            row_w = self._row_weights(it, grad[0] if self.K == 1 else grad,
                                      hess[0] if self.K == 1 else hess)
            fmask = self._feature_mask()

        shrinkage = self._shrinkage if cfg.boosting != "rf" else 1.0
        grew_any = False
        # loop-invariant defer gate (hoisted from the k loop): guard
        # flags travel async in defer mode, synchronously otherwise
        defer = (not self.valid_sets and cfg.boosting == "gbdt"
                 and not cfg.linear_tree)
        iter_flag = None   # device-side OR of this iteration's flags
        sync_flag = 0      # host-side flags (non-defer path)
        fault_now = False
        quant_key = None
        if cfg.use_quantized_grad and cfg.stochastic_rounding:
            quant_key = jax.random.fold_in(self._base_key, it)
        node_key = None
        if cfg.feature_fraction_bynode < 1.0:
            node_key = jax.random.fold_in(self._bynode_key, it)
        for k in range(self.K):
            if self.mesh is not None:
                gk = grad[k]
                hk = hess[k]
                rwk = row_w
                if self._pad:
                    gk = jnp.pad(gk, (0, self._pad))
                    hk = jnp.pad(hk, (0, self._pad))
                    rwk = jnp.pad(rwk, (0, self._pad))
                args = (self.bins_T, gk, hk, rwk, fmask,
                        self.feat_num_bins, self.feat_nan_bin)
                if self.monotone is not None:
                    args = args + (self.monotone,)
                if self.feat_is_cat is not None:
                    args = args + (self.feat_is_cat,)
                if quant_key is not None:
                    args = args + (jax.random.fold_in(quant_key, k),)
                if self.interaction_groups is not None:
                    args = args + (self.interaction_groups,)
                if self.forced is not None:
                    args = args + self.forced
                if node_key is not None:
                    args = args + (jax.random.fold_in(node_key, k),)
                if self._bundle_dev is not None:
                    args = args + self._bundle_dev
                with timed("tree_learner/grow"):
                    dev_tree, row_leaf = self._run_with_oom_degrade(
                        lambda: self._grow_fn(*args), "distributed grow")
                row_leaf = row_leaf[: self.n]
            else:
                cegb_arrays = None
                if self.cegb_enabled:
                    cegb_arrays = (self._cegb_pen_coupled,
                                   self._cegb_pen_lazy,
                                   self._cegb_coupled,
                                   self._cegb_lazy_used)
                with timed("tree_learner/grow"):
                    out = self._run_with_oom_degrade(
                        lambda: grow_tree(
                            self.grow_cfg, self.bins_T, grad[k], hess[k],
                            row_w, fmask, self.feat_num_bins,
                            self.feat_nan_bin,
                            self.monotone, self.feat_is_cat,
                            None if quant_key is None
                            else jax.random.fold_in(quant_key, k),
                            self.interaction_groups, self.forced,
                            cegb_arrays,
                            None if node_key is None
                            else jax.random.fold_in(node_key, k),
                            self._bundle_dev), "grow")
                if self.cegb_enabled:
                    dev_tree, row_leaf, self._cegb_coupled, lz = out
                    if self.cegb_lazy:
                        self._cegb_lazy_used = lz
                else:
                    dev_tree, row_leaf = out
            dev_tree, k_flag = self._leaf_guard(dev_tree, gh_flag)
            iter_flag = k_flag if iter_flag is None else iter_flag | k_flag
            if defer:
                # no blocking scalar fetch: the no-growth check runs one
                # iteration late off an async copy (see top of method);
                # constant trees are recognized at flush time
                num_leaves = 2
            else:
                # ONE batched transfer, not two sequential blocking
                # fetches (tpulint TPL002: each np.asarray scalar read
                # is its own full device round trip on this
                # latency-bound eager path)
                nl_host, flag_host = jax.device_get(
                    (dev_tree.num_leaves, k_flag))
                num_leaves = int(nl_host)
                sync_flag |= int(flag_host)
            if num_leaves <= 1:
                # constant tree; carries the boost_from_average bias when
                # it is the first iteration (gbdt.cpp models_.size() check /
                # rf.hpp AsConstantTree path)
                tree = tree_from_arrays(dev_tree, self.train_set.mappers,
                                        self.train_set.used_feature_indices())
                bias = 0.0
                if it == 0 and (self._fold_bias or cfg.boosting == "rf"):
                    bias = float(self.init_score[k])
                tree.leaf_value[:] = bias
                if cfg.linear_tree:
                    tree.is_linear = True
                    tree.leaf_const = tree.leaf_value.copy()
                    tree.leaf_features = [[] for _ in
                                          range(tree.num_leaves)]
                    tree.leaf_coeff = [[] for _ in range(tree.num_leaves)]
                self.models.append(tree)
                self._tree_weights.append(1.0)
                if cfg.boosting == "rf":
                    self.score = self.score.at[k].set(
                        (self.score[k] * it + bias) / (it + 1))
                    for v in self.valid_sets:
                        v.score = v.score.at[k].set(
                            (v.score[k] * it + bias) / (it + 1))
                elif bias != 0.0:
                    for v in self.valid_sets:
                        v.score = v.score.at[k].add(bias)
                continue
            grew_any = True

            # objective-specific per-leaf refinement (RenewTreeOutput).
            # rf refines against the init score, not the running average
            # (rf.hpp residual_getter uses init_scores_).
            leaf_values = dev_tree.leaf_value
            if (self.objective is not None and self.objective.need_renew
                    and custom_grad is None):
                if cfg.boosting == "rf":
                    base = jnp.full((self.n,), float(self.init_score[k]),
                                    jnp.float32)
                else:
                    base = self.score[k]
                resid = self.objective.renew_residual(base, self.label)
                rw = self.objective.renew_weight(self.label, self.weight)
                rw = row_w if rw is None else row_w * rw
                leaf_values = renew_leaf_values(
                    row_leaf, resid, rw, cfg.num_leaves,
                    self.objective.renew_alpha, leaf_values)
                dev_tree = dev_tree._replace(leaf_value=leaf_values)

            fold_now = (cfg.boosting == "rf") or (it == 0 and self._fold_bias)
            bias = float(self.init_score[k]) if fold_now else 0.0
            lin = None
            if defer:
                # Don't stall the device pipeline on a per-iteration
                # host fetch: pack the tree to one vector, start an
                # async copy, and materialize the host Tree lazily
                # (models property). Bias/shrinkage are re-applied at
                # materialization in the same order as the eager path.
                vec, cmask = pack_tree_device(dev_tree)
                proto = jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    dev_tree)
                self._defer_tree(vec, cmask, proto, dev_tree.num_leaves,
                                 shrinkage, bias)
                tree = None
            else:
                if cfg.linear_tree:
                    lin = self._fit_linear(
                        dev_tree, row_leaf, grad[k], hess[k], row_w,
                        is_first=(len(self.models) < self.K))
                tree = tree_from_arrays(dev_tree, self.train_set.mappers,
                                        self.train_set.used_feature_indices())
                tree.apply_shrinkage(shrinkage)
                if lin is not None:
                    self._attach_linear(tree, lin, shrinkage)
                if bias != 0.0:
                    # Tree::AddBias: the constant rides inside leaf values
                    # so the model file is self-contained (every tree for
                    # rf)
                    tree.leaf_value = tree.leaf_value + bias
                    tree.internal_value = tree.internal_value + bias
                    if tree.is_linear and getattr(tree, "leaf_const",
                                                  None) is not None:
                        # AddBias updates leaf_const too (tree.cpp:222-227)
                        tree.leaf_const = tree.leaf_const + bias
                self.models.append(tree)
                self._tree_weights.append(1.0)

            contrib_raw = lin[2] if lin is not None \
                else gather_small(leaf_values, row_leaf)
            if defer:
                # a no-growth tree is replaced by a constant at flush
                # (AsConstantTree, gbdt.cpp): contribute nothing here
                contrib_raw = jnp.where(dev_tree.num_leaves > 1,
                                        contrib_raw, 0.0)
            if cfg.boosting == "rf":
                # running average of unscaled tree outputs (rf.hpp
                # MultiplyScore m -> UpdateScore -> MultiplyScore 1/(m+1))
                contrib = contrib_raw + float(self.init_score[k])
                self.score = self.score.at[k].set(
                    (self.score[k] * it + contrib) / (it + 1))
                for v in self.valid_sets:
                    dv = self._predict_tree_binned_host(tree, v.dataset)
                    v.score = v.score.at[k].set(
                        (v.score[k] * it + dv) / (it + 1))
            else:
                # train-score update via the leaf partition — no
                # re-traversal (ScoreUpdater::AddScore, score_updater.hpp)
                with timed("boosting/update_score"):
                    self.score = self.score.at[k].add(
                        contrib_raw * shrinkage)
                if it == 0 and self._fold_bias \
                        and self.init_score[k] != 0.0:
                    # internal score already starts at init; nothing to add
                    pass
                for v in self.valid_sets:
                    v.score = v.score.at[k].add(
                        self._predict_tree_binned_host(tree, v.dataset))

        if defer:
            if iter_flag is not None:
                self._push_guard_flags(it, iter_flag)
        elif sync_flag:
            # non-defer paths already fetched num_leaves, so the flag
            # read cost nothing extra: record/raise at the exact
            # iteration, and keep training through a skip_tree demotion
            # (a fault is not "no more leaves to split")
            fault_now = True
            self._apply_guard_flag(it, sync_flag)

        if cfg.boosting == "dart" and drop_idx and grew_any:
            self._dart_normalize(drop_idx)

        self.iter_ += 1
        finished = not grew_any and not fault_now
        if finished:
            self._finished_natural = True
        return finished

    # ------------------------------------------------------------------
    # DART (dart.hpp)
    # ------------------------------------------------------------------
    def _dart_select_drop(self) -> List[int]:
        cfg = self.cfg
        n_models = len(self.models)
        n_iters = n_models // self.K
        if self._dart_rng.rand() < cfg.skip_drop or n_iters == 0:
            return []
        if cfg.uniform_drop:
            mask = self._dart_rng.rand(n_iters) < cfg.drop_rate
            drop_iters = np.where(mask)[0]
        else:
            k = min(max(1, int(round(n_iters * cfg.drop_rate))), cfg.max_drop)
            drop_iters = self._dart_rng.choice(n_iters, size=min(k, n_iters),
                                               replace=False)
        if len(drop_iters) > cfg.max_drop > 0:
            drop_iters = drop_iters[:cfg.max_drop]
        out = []
        for i in drop_iters:
            out.extend(range(i * self.K, (i + 1) * self.K))
        return sorted(out)

    def _dart_apply_drop(self, drop_idx: List[int]) -> None:
        """Remove dropped trees' contribution from all score vectors."""
        for i in drop_idx:
            k = i % self.K
            tree = self.models[i]
            self.score = self.score.at[k].add(
                -self._predict_tree_binned_host(tree, self.train_set))
            for v in self.valid_sets:
                v.score = v.score.at[k].add(-self._predict_tree_binned_host(
                    tree, v.dataset))

    def _dart_normalize(self, drop_idx: List[int]) -> None:
        """Shrink re-added dropped trees and the new tree (dart.hpp
        Normalize)."""
        cfg = self.cfg
        kd = len(drop_idx) // self.K
        if cfg.xgboost_dart_mode:
            new_w = self._shrinkage / (kd + self._shrinkage)
            old_factor = kd / (kd + self._shrinkage)
        else:
            new_w = 1.0 / (kd + 1.0)
            old_factor = kd / (kd + 1.0)
        # scale the trees added this iteration
        for i in range(len(self.models) - self.K, len(self.models)):
            if self.models[i].num_leaves > 1:
                k = i % self.K
                delta = self._predict_tree_binned_host(self.models[i],
                                                       self.train_set)
                self.score = self.score.at[k].add(delta * (new_w - 1.0))
                for v in self.valid_sets:
                    dv = self._predict_tree_binned_host(
                        self.models[i], v.dataset)
                    v.score = v.score.at[k].add(dv * (new_w - 1.0))
                self.models[i].apply_shrinkage(new_w)
        # scale the dropped trees and re-add
        for i in drop_idx:
            k = i % self.K
            self.models[i].apply_shrinkage(old_factor)
            delta = self._predict_tree_binned_host(self.models[i],
                                                   self.train_set)
            self.score = self.score.at[k].add(delta)
            for v in self.valid_sets:
                dv = self._predict_tree_binned_host(self.models[i],
                                                    v.dataset)
                v.score = v.score.at[k].add(dv)

    # ------------------------------------------------------------------
    def rollback_one_iter(self) -> None:
        """RollbackOneIter (gbdt.cpp:454)."""
        # a pending scan window's score runs ahead of iter_; restore
        # the committed-state score before unwinding one iteration
        # (next_iter: the decrement below happens after this abort)
        self._abort_scan_window(next_iter=self.iter_ - 1)
        self._nl_async = []
        self._guard_async = []
        self._fault_recent = False
        self._finished_natural = False
        if not self.models:
            return
        is_rf = self.cfg.boosting == "rf"
        m = self.iter_ - 1  # iterations remaining after rollback
        for k in reversed(range(self.K)):
            tree = self.models.pop()
            self._tree_weights.pop()
            if is_rf:
                dv = self._predict_tree_binned_host(
                    tree, self.train_set)
                if m > 0:
                    self.score = self.score.at[k].set(
                        (self.score[k] * (m + 1) - dv) / m)
                else:
                    self.score = self.score.at[k].set(jnp.full_like(
                        self.score[k], float(self.init_score[k])))
                for v in self.valid_sets:
                    vv = self._predict_tree_binned_host(
                        tree, v.dataset)
                    if m > 0:
                        v.score = v.score.at[k].set(
                            (v.score[k] * (m + 1) - vv) / m)
                    else:
                        v.score = v.score.at[k].set(
                            jnp.zeros_like(v.score[k]))
                continue
            if tree.num_leaves > 1 or tree.leaf_value[0] != 0.0:
                delta = self._predict_tree_binned_host(
                    tree, self.train_set)
                self.score = self.score.at[k].add(-delta)
                if m == 0 and self._fold_bias:
                    # the popped iter-0 tree carried the folded bias, but
                    # the internal train score starts at init: restore it
                    self.score = self.score.at[k].add(
                        float(self.init_score[k]))
                for v in self.valid_sets:
                    dv = self._predict_tree_binned_host(
                        tree, v.dataset)
                    v.score = v.score.at[k].add(-dv)
        self.iter_ -= 1

    def eval_metrics(self, metrics, data_idx: int) -> Dict[str, float]:
        """data_idx 0 = train, 1.. = valid sets."""
        if data_idx == 0:
            score, ds = self.score, self.train_set
        else:
            v = self.valid_sets[data_idx - 1]
            score, ds = v.score, v.dataset
        label = jnp.asarray(ds.get_label(), jnp.float32)
        w = ds.get_weight()
        weight = None if w is None else jnp.asarray(w, jnp.float32)
        convert = (self.objective.convert_output
                   if self.objective is not None else (lambda s: s))
        out = {}
        for m in metrics:
            extra = {}
            if hasattr(m, "eval_with_query"):
                val = m.eval_with_query(score, label, weight, ds, convert)
            else:
                val = m.eval(score, label, weight, convert)
            out[m.name] = float(val)
        return out

    def current_score(self, data_idx: int) -> np.ndarray:
        if data_idx == 0:
            return np.asarray(self.score)
        return np.asarray(self.valid_sets[data_idx - 1].score)
