"""scikit-learn estimator wrappers.

Re-design of the reference python-package/lightgbm/sklearn.py
(LGBMModel :121, LGBMClassifier/LGBMRegressor/LGBMRanker, custom
objective/metric adapters) over the TPU-native engine. The wrapper
surface — constructor params, fit(eval_set=...), predict/predict_proba,
fitted attributes (best_iteration_, evals_result_, feature_importances_,
classes_) — mirrors the reference so sklearn pipelines port unchanged.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset, LightGBMError
from .callback import early_stopping as early_stopping_cb
from .callback import log_evaluation, record_evaluation
from .engine import train as engine_train

__all__ = ["LGBMModel", "LGBMClassifier", "LGBMRegressor", "LGBMRanker"]

try:
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    from sklearn.preprocessing import LabelEncoder as _LabelEncoder
    _SKLEARN = True
except ImportError:  # pragma: no cover
    _SKBase = object

    class _SKClassifier:  # type: ignore
        pass

    class _SKRegressor:  # type: ignore
        pass
    _LabelEncoder = None
    _SKLEARN = False


class _ObjectiveFunctionWrapper:
    """Adapt a sklearn-style objective fn to the engine's fobj protocol
    (reference sklearn.py _ObjectiveFunctionWrapper)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, train_set):
        labels = train_set.get_label()
        try:
            grad, hess = self.func(labels, preds)
        except TypeError:
            grad, hess = self.func(labels, preds,
                                   train_set.get_weight())
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt a sklearn-style metric fn (y_true, y_pred[, weight]) ->
    (name, value, is_higher_better)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, eval_set):
        labels = eval_set.get_label()
        try:
            return self.func(labels, preds)
        except TypeError:
            return self.func(labels, preds, eval_set.get_weight())


class LGBMModel(_SKBase):
    """Base sklearn estimator (reference sklearn.py:121 LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0,
                 random_state: Optional[int] = None,
                 n_jobs: Optional[int] = None,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._best_score: Dict = {}
        self._objective = objective
        self._class_weight = class_weight
        self.fitted_ = False

    # -- sklearn plumbing --------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN else {}
        if not _SKLEARN:
            for k in ("boosting_type", "num_leaves", "max_depth",
                      "learning_rate", "n_estimators", "subsample_for_bin",
                      "objective", "class_weight", "min_split_gain",
                      "min_child_weight", "min_child_samples", "subsample",
                      "subsample_freq", "colsample_bytree", "reg_alpha",
                      "reg_lambda", "random_state", "n_jobs",
                      "importance_type"):
                params[k] = getattr(self, k)
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, "_other_params") and key not in (
                    "boosting_type", "num_leaves", "max_depth",
                    "learning_rate", "n_estimators", "subsample_for_bin",
                    "objective", "class_weight", "min_split_gain",
                    "min_child_weight", "min_child_samples", "subsample",
                    "subsample_freq", "colsample_bytree", "reg_alpha",
                    "reg_lambda", "random_state", "n_jobs",
                    "importance_type"):
                self._other_params[key] = value
        return self

    def _engine_params(self) -> Dict[str, Any]:
        """Map sklearn-style names to engine params (reference
        sklearn.py _process_params)."""
        params = {
            "boosting": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbose": -1,
        }
        if self.random_state is not None:
            params["seed"] = int(self.random_state)
        if isinstance(self._objective, str):
            params["objective"] = self._objective
        params.update(self._other_params)
        return params

    # -- core fit ----------------------------------------------------------
    def _fit(self, X, y, sample_weight=None, init_score=None, group=None,
             eval_set=None, eval_names=None, eval_sample_weight=None,
             eval_class_weight=None, eval_init_score=None, eval_group=None,
             eval_metric=None, feature_name="auto",
             categorical_feature="auto", callbacks=None) -> "LGBMModel":
        params = self._engine_params()

        fobj = None
        if callable(self._objective):
            fobj = _ObjectiveFunctionWrapper(self._objective)
            params["objective"] = "none"

        feval = None
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
        elif eval_metric:
            params["metric"] = eval_metric

        train_set = Dataset(X, label=y, weight=sample_weight,
                            group=group, init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)

        valid_sets: List[Dataset] = []
        names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):

                def at(lst, j):
                    return None if lst is None else lst[j]
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(train_set.create_valid(
                        vx, label=vy, weight=at(eval_sample_weight, i),
                        group=at(eval_group, i),
                        init_score=at(eval_init_score, i)))
                names.append(
                    eval_names[i] if eval_names and i < len(eval_names)
                    else f"valid_{i}")

        callbacks = list(callbacks) if callbacks else []
        self._evals_result = {}
        callbacks.append(record_evaluation(self._evals_result))

        self._Booster = engine_train(
            params, train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=names,
            feval=feval, fobj=fobj, callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self.n_features_ = self._Booster.num_feature()
        self.n_features_in_ = self.n_features_
        self.fitted_ = True
        return self

    fit = _fit

    def _check_fitted(self):
        if not self.fitted_:
            raise LightGBMError(
                "Estimator not fitted, call fit before exploiting the "
                "model.")

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs):
        self._check_fitted()
        return self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features)

    # -- fitted attributes -------------------------------------------------
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._best_score

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._best_iteration if self._best_iteration > 0 \
            else self._Booster.current_iteration()

    @property
    def n_iter_(self) -> int:
        return self.n_estimators_


class LGBMRegressor(_SKRegressor, LGBMModel):
    """sklearn regressor (reference sklearn.py LGBMRegressor)."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMRegressor":
        if self._objective is None:
            self._objective = "regression"
        return self._fit(X, y, sample_weight=sample_weight,
                         init_score=init_score, eval_set=eval_set,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_init_score=eval_init_score,
                         eval_metric=eval_metric, feature_name=feature_name,
                         categorical_feature=categorical_feature,
                         callbacks=callbacks)


class LGBMClassifier(_SKClassifier, LGBMModel):
    """sklearn classifier (reference sklearn.py LGBMClassifier)."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_class_weight=None,
            eval_init_score=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMClassifier":
        y = np.asarray(y).ravel()
        if _LabelEncoder is not None:
            self._le = _LabelEncoder().fit(y)
            y_enc = self._le.transform(y)
            self._classes = self._le.classes_
        else:
            self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)

        if callable(self._objective):
            pass  # custom objective keeps user semantics
        elif self._n_classes > 2:
            if self._objective is None or \
                    self._objective in ("binary",):
                self._objective = "multiclass"
            self._other_params.setdefault("num_class", self._n_classes)
        elif self._objective is None:
            self._objective = "binary"

        # class_weight -> per-row weights (reference maps via sklearn's
        # compute_sample_weight)
        if self.class_weight is not None:
            try:
                from sklearn.utils.class_weight import compute_sample_weight
                cw = compute_sample_weight(self.class_weight, y)
                sample_weight = cw if sample_weight is None \
                    else np.asarray(sample_weight) * cw
            except ImportError:  # pragma: no cover
                pass

        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            fixed = []
            for vx, vy in eval_set:
                vy = np.asarray(vy).ravel()
                if _LabelEncoder is not None:
                    vy = self._le.transform(vy)
                else:
                    vy = np.searchsorted(self._classes, vy)
                fixed.append((vx, vy))
            eval_set = fixed

        return self._fit(X, y_enc, sample_weight=sample_weight,
                         init_score=init_score, eval_set=eval_set,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_class_weight=eval_class_weight,
                         eval_init_score=eval_init_score,
                         eval_metric=eval_metric, feature_name=feature_name,
                         categorical_feature=categorical_feature,
                         callbacks=callbacks)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features)
        if callable(self._objective) or raw_score or pred_leaf \
                or pred_contrib:
            return result
        if result.ndim == 1:  # binary
            idx = (result > 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      validate_features: bool = False, **kwargs):
        self._check_fitted()
        result = self._Booster.predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features)
        if callable(self._objective) or raw_score or pred_leaf \
                or pred_contrib:
            return result
        if self._n_classes == 2 and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """sklearn-style ranker (reference sklearn.py LGBMRanker)."""

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None) -> "LGBMRanker":
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError(
                "Eval_group cannot be None when eval_set is not None")
        if self._objective is None:
            self._objective = "lambdarank"
        self._other_params.setdefault(
            "eval_at", list(eval_at))
        return self._fit(X, y, sample_weight=sample_weight,
                         init_score=init_score, group=group,
                         eval_set=eval_set, eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_init_score=eval_init_score,
                         eval_group=eval_group, eval_metric=eval_metric,
                         feature_name=feature_name,
                         categorical_feature=categorical_feature,
                         callbacks=callbacks)
