"""Measure the fused-iteration fast path end-to-end at bench scale
(10.5M x 28, 255 leaves/bins) on the real chip: wall per train_one_iter
(which now routes through _train_one_iter_fused) vs the eager path
(fused gate forced off), plus a hist_method="pallas" arm of the fused
path. The pallas-vs-mxu fused delta at THIS shape is the decision gate
for flipping hist_method="auto" to pallas on TPU (docs/PALLAS.md):
until the pallas arm measures faster here, auto keeps the mxu path
and pallas stays opt-in (LIGHTGBM_TPU_AUTO_PALLAS=1 / hist_method=
"pallas"). Run:  python benchmarks/fused_iter_bench.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDTBooster

N, F = 10_500_000, 28
rs = np.random.RandomState(0)
X = rs.randn(N, F).astype(np.float32)
coef = rs.randn(F).astype(np.float32)
y = ((X @ coef) > 0).astype(np.float64)
t0 = time.perf_counter()
ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
ds.construct()
print(f"construct: {time.perf_counter() - t0:.1f} s", flush=True)
del X

PARAMS = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
          "learning_rate": 0.1, "verbosity": -1}


def run(tag, fused, iters=10, hist_method=None):
    if not fused:
        orig = GBDTBooster._fused_ok
        GBDTBooster._fused_ok = lambda self: False
    try:
        params = dict(PARAMS)
        if hist_method:
            params["hist_method"] = hist_method
        bst = lgb.Booster(params=params, train_set=ds)
        eng = bst._engine
        t0 = time.perf_counter()
        eng.train_one_iter()
        eng.score.block_until_ready()
        print(f"{tag}: warmup (incl compile) "
              f"{time.perf_counter() - t0:.1f} s", flush=True)
        t0 = time.perf_counter()
        for _ in range(iters):
            eng.train_one_iter()
        eng.score.block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        print(f"{tag}: {dt * 1e3:.1f} ms/iter = {1 / dt:.3f} iters/sec "
              f"(vs_baseline {1 / dt / (500 / 130.094):.3f})", flush=True)
        return dt
    finally:
        if not fused:
            GBDTBooster._fused_ok = orig


eager = run("eager", fused=False)
fused = run("fused", fused=True)
print(f"speedup: {eager / fused:.3f}x", flush=True)

from lightgbm_tpu.ops.pallas_hist import pallas_available  # noqa: E402

if pallas_available():
    pallas = run("fused+pallas", fused=True, hist_method="pallas")
    print(f"pallas vs mxu (fused): {fused / pallas:.3f}x — "
          f"{'FLIP auto to pallas' if pallas < fused else 'keep mxu'} "
          "(record the verdict in docs/PALLAS.md + PROFILE.md)",
          flush=True)
else:
    print("pallas arm SKIPPED (unavailable)", flush=True)
