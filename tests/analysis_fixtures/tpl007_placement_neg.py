# tpulint fixture: TPL007 negative — the CORRECT placement host-sync
# shapes (docs/SHARDING.md): every rank joins the barrier and the
# checkpoint gather unconditionally; only LOCAL work (per-rank slice
# building, the rank-0 file write) sits behind rank branches.
import jax

from lightgbm_tpu.parallel.placement import (fetch_addressable,
                                             fetch_global,
                                             upload_barrier)


def unconditional_upload_barrier(plan, host_rows):
    """The engine's placement shape: the rank branch builds only the
    per-rank ARGUMENT (each process places its own slices); the
    barrier itself is joined by everyone."""
    offset = 0
    if jax.process_index() > 0:
        offset = jax.process_index() * host_rows.shape[0]
    placed = plan.place(host_rows, local_offset=offset)
    upload_barrier("ok/everyone_joins")
    return placed


def gather_above_the_rank_gate(score, path):
    """The PR 2 checkpoint shape done RIGHT: every rank joins the
    assembly, then only rank 0 writes the file (a local side
    effect)."""
    host = fetch_global(score)
    if jax.process_index() == 0:
        with open(path, "wb") as fh:
            fh.write(bytes(host))
    return host


def world_size_gated_barrier():
    """process_count() is rank-invariant — gating on it is uniform."""
    if jax.process_count() <= 1:
        return
    upload_barrier("ok/world_gate")


def addressable_fetch_is_not_a_collective(score):
    """fetch_addressable never joins a collective by construction —
    rank-gating it is a plain local read."""
    if jax.process_index() != 0:
        return None
    return fetch_addressable(score)
