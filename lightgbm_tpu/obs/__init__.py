"""Run telemetry: metrics registry, recompile/HBM tracking, JSONL events.

The observability spine the perf ROADMAP items report against. Round 5's
PROFILE.md lesson is that per-op microbenchmarks lie in both directions
on this codebase — only in-situ measurement of the real boosting loop is
trustworthy — so every layer here instruments the *actual* hot path and
is a strict no-op when disabled:

- :class:`MetricsRegistry` — label-keyed, thread-safe counters / gauges /
  histograms (`registry` is the process-global instance).
- :mod:`~lightgbm_tpu.obs.jit_tracker` — registered jitted entry points
  (grow / fused-iteration / predict) expose XLA cache-size deltas, so a
  shape-change recompile shows up as a counted event, not a mystery
  530 ms stall.
- :func:`device_memory_stats` — HBM gauges via ``device.memory_stats()``
  with explicit ``None`` on backends that lack it (CPU).
- :class:`TelemetryRecorder` — one JSONL event per boosting iteration
  (phase wall times, recompiles, HBM, tree stats, eval results),
  activated by ``lightgbm_tpu.callback.telemetry(path)`` or the
  ``LIGHTGBM_TPU_TELEMETRY=<path>`` env var.

See docs/OBSERVABILITY.md for the event schema and workflow.
"""

from .jit_tracker import (RecompileWatcher, jit_cache_sizes, register_jit,
                          total_recompiles)
from .memory import device_memory_stats
from .recorder import (ITERATION_EVENT_KEYS, TelemetryRecorder,
                       render_stats_table, summarize_events)
from .registry import Counter, Gauge, Histogram, MetricsRegistry, registry

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "registry",
    "register_jit", "jit_cache_sizes", "total_recompiles",
    "RecompileWatcher", "device_memory_stats",
    "TelemetryRecorder", "ITERATION_EVENT_KEYS",
    "summarize_events", "render_stats_table",
]
