"""Prediction paths (batch raw-feature inference).

Re-design of the reference Predictor / GBDT::Predict stack
(/root/reference/src/boosting/gbdt_prediction.cpp,
src/application/predictor.hpp, c_api LGBM_BoosterPredictForMat): the whole
forest is stacked into device tensors once (ops/predict.py StackedTrees)
and every row traverses every tree via vectorized gathers.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .models.tree import Tree
from .ops.predict import StackedTrees, predict_leaf_raw

__all__ = ["predict_any", "stack_trees", "convert_raw_scores"]


def stack_trees(trees: List[Tree], dtype=jnp.float32,
                device: bool = True) -> StackedTrees:
    """Stack a forest into SoA arrays (leading axis = tree index).

    ``device=False`` returns host (numpy) arrays in their final
    dtypes — the serving compiler stages the new model on the host so
    the upload can donate the OLD model's device buffers instead of
    holding two forests in HBM (serve/compile.py swap protocol)."""
    T = len(trees)
    max_nodes = max((t.num_nodes for t in trees), default=0)
    max_nodes = max(max_nodes, 1)
    max_leaves = max((t.num_leaves for t in trees), default=1)
    W = 1
    for t in trees:
        if t.num_cat > 0:
            spans = np.diff(t.cat_boundaries)
            W = max(W, int(spans.max()))

    def pad(arr, size, fill, dt):
        out = np.full((size,), fill, dt)
        out[: len(arr)] = arr
        return out

    sf = np.zeros((T, max_nodes), np.int32)
    thr = np.zeros((T, max_nodes), np.float64)
    tb = np.zeros((T, max_nodes), np.int32)
    dl = np.zeros((T, max_nodes), bool)
    mt = np.zeros((T, max_nodes), np.int8)
    ic = np.zeros((T, max_nodes), bool)
    bits = np.zeros((T, max_nodes, W), np.uint32)
    lc = np.full((T, max_nodes), -1, np.int32)
    rc = np.full((T, max_nodes), -1, np.int32)
    lv = np.zeros((T, max_leaves), np.float64)
    for i, t in enumerate(trees):
        nn = t.num_nodes
        if nn > 0:
            sf[i, :nn] = t.split_feature
            tb[i, :nn] = t.threshold_bin
            dl[i, :nn] = (t.decision_type & 2) != 0
            mt[i, :nn] = (t.decision_type >> 2) & 3
            ic[i, :nn] = (t.decision_type & 1) != 0
            lc[i, :nn] = t.left_child
            rc[i, :nn] = t.right_child
            for node in range(nn):
                if ic[i, node]:
                    cat_idx = int(t.threshold[node])
                    a = t.cat_boundaries[cat_idx]
                    b = t.cat_boundaries[cat_idx + 1]
                    bits[i, node, : b - a] = t.cat_threshold[a:b]
                else:
                    thr[i, node] = t.threshold[node]
        else:
            # stump: route every row to leaf 0
            lc[i, 0] = -1
            rc[i, 0] = -1
        lv[i, : t.num_leaves] = t.leaf_value
    any_linear = any(t.is_linear and t.leaf_const is not None
                     for t in trees)
    lin_args = {}
    if any_linear:
        km = max((len(fs) for t in trees if t.leaf_features
                  for fs in t.leaf_features), default=0)
        km = max(km, 1)
        lconst = np.zeros((T, max_leaves), np.float64)
        lnf = np.zeros((T, max_leaves), np.int32)
        lfe = np.zeros((T, max_leaves, km), np.int32)
        lco = np.zeros((T, max_leaves, km), np.float64)
        for i, t in enumerate(trees):
            if t.is_linear and t.leaf_const is not None:
                Lr = t.num_leaves
                lconst[i, :Lr] = t.leaf_const[:Lr]
                for leaf in range(Lr):
                    fs = t.leaf_features[leaf] if t.leaf_features else []
                    lnf[i, leaf] = len(fs)
                    lfe[i, leaf, : len(fs)] = fs
                    lco[i, leaf, : len(fs)] = t.leaf_coeff[leaf]
            else:
                # constant tree inside a linear forest: emulate with a
                # zero-feature linear model
                lconst[i, : t.num_leaves] = t.leaf_value
        lin_args = dict(lin_const=np.asarray(lconst, dtype),
                        lin_nfeat=lnf,
                        lin_feats=lfe,
                        lin_coef=np.asarray(lco, dtype))

    # f32-safe thresholds: round DOWN to the nearest f32 so that any
    # f32-representable feature value keeps its training-time side of the
    # split (thresholds are f64 midpoints between adjacent values; plain
    # round-to-nearest could land on/above the right neighbour).
    if dtype == jnp.float32:
        thr32 = thr.astype(np.float32)
        bad = thr32.astype(np.float64) > thr
        thr32[bad] = np.nextafter(thr32[bad], np.float32(-np.inf))
        thr = thr32
    stacked = StackedTrees(
        split_feature=sf,
        threshold=np.asarray(thr, dtype),
        threshold_bin=tb,
        default_left=dl,
        missing_type=mt,
        is_categorical=ic,
        cat_bitset=bits,
        left_child=lc,
        right_child=rc,
        leaf_value=np.asarray(lv, dtype),
        **lin_args,
    )
    if device:
        stacked = jax.tree_util.tree_map(jnp.asarray, stacked)
    return stacked


def _extract_matrix(booster, data) -> np.ndarray:
    from .basic import Dataset, LightGBMError
    if isinstance(data, Dataset):
        raise LightGBMError(
            "Cannot use Dataset instance for prediction, please use raw "
            "data instead")
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            arrs = []
            pc = booster.pandas_categorical
            ci = 0
            for col in data.columns:
                s = data[col]
                if isinstance(s.dtype, pd.CategoricalDtype):
                    cats = None
                    if pc is not None and ci < len(pc):
                        cats = pc[ci]
                    ci += 1
                    if cats is not None:
                        s = s.cat.set_categories(cats)
                    codes = s.cat.codes.to_numpy().astype(np.float64)
                    codes[codes < 0] = np.nan
                    arrs.append(codes)
                else:
                    arrs.append(s.to_numpy(dtype=np.float64,
                                           na_value=np.nan))
            return np.column_stack(arrs)
    except ImportError:
        pass
    if hasattr(data, "toarray"):
        return np.asarray(data.todense(), np.float64)
    X = np.asarray(data, dtype=np.float64)
    if X.ndim == 1:
        X = X[None, :]
    return X


def predict_any(booster, data, start_iteration: int = 0,
                num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False,
                pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
    from .basic import LightGBMError
    X = _extract_matrix(booster, data)
    n_feat = booster.num_feature()
    if n_feat and X.shape[1] != n_feat:
        raise LightGBMError(
            f"The number of features in data ({X.shape[1]}) is not the "
            f"same as it was in training data ({n_feat}).")
    trees = booster._models
    K = booster.num_model_per_iteration()
    total_iters = len(trees) // max(K, 1)
    if num_iteration is None or num_iteration <= 0:
        num_iteration = total_iters - start_iteration
    num_iteration = min(num_iteration, total_iters - start_iteration)
    lo = start_iteration * K
    hi = (start_iteration + num_iteration) * K
    sel = trees[lo:hi]
    n = X.shape[0]

    if pred_contrib:
        if any(t.is_linear and t.leaf_coeff and any(
                len(c) for c in t.leaf_coeff) for t in sel):
            raise LightGBMError(
                "pred_contrib (SHAP) is not supported for linear trees")
        from .shap import predict_contrib
        return predict_contrib(booster, X, sel, K)

    if not sel:
        out = np.zeros((n, K), np.float64)
        return out[:, 0] if K == 1 else out

    if pred_leaf:
        stacked = stack_trees(sel)
        Xd = jnp.asarray(X, jnp.float32)
        leaves = _predict_leaves_jit(stacked, Xd, len(sel))
        return np.asarray(leaves, np.int32)

    # the reference enables margin early-exit only when the objective
    # tolerates inexact sums (predictor.hpp:46 gates on
    # !NeedAccuratePrediction(), overridden false ONLY by binary,
    # multiclass and ranking objectives — cross-entropy keeps the
    # default true and never early-stops)
    obj_name = (booster._objective_str or "none").split()[0]
    es_ok = obj_name in ("binary", "multiclass", "multiclassova",
                         "softmax", "lambdarank", "rank_xendcg")
    use_es = pred_early_stop and es_ok and not booster._avg_output
    cf = getattr(booster, "_compiled_forest", None)
    if cf is not None and not use_es and cf.matches(lo, hi, len(trees)):
        # the shape-bucketed compiled path (serve/compile.py): the
        # forest is already stacked on device, the batch pads to its
        # power-of-two bucket, and ad-hoc batch sizes never recompile
        out = cf.predict_raw(X)               # [n, K] f64
    elif use_es:
        stacked = stack_trees(sel)
        Xd = jnp.asarray(X, jnp.float32)
        scores = _predict_scores_early_stop(
            stacked, Xd, len(sel), K, max(1, pred_early_stop_freq),
            pred_early_stop_margin)
        out = np.asarray(scores, np.float64)  # [n, K]
    else:
        stacked = stack_trees(sel)
        Xd = jnp.asarray(X, jnp.float32)
        scores = _predict_scores_jit(stacked, Xd, len(sel), K)
        out = np.asarray(scores, np.float64)  # [n, K]

    if booster._avg_output:
        # random forest: leaves are stored unscaled (reference rf.hpp /
        # average_output header); average over the iterations actually used
        out = out / max(1, num_iteration)

    if not raw_score:
        out = _convert_output(booster, out)
    return out[:, 0] if K == 1 else out


@jax.jit
def _forest_leaves(stacked: StackedTrees, X: jnp.ndarray) -> jnp.ndarray:
    def per_tree(ti):
        return predict_leaf_raw(stacked, ti, X)
    T = stacked.leaf_value.shape[0]
    return jax.vmap(per_tree)(jnp.arange(T))  # [T, n]


from .obs import register_jit  # noqa: E402  (after the jitted defs)

_forest_leaves = register_jit("prediction/forest_leaves",
                              _forest_leaves, max_signatures=16)


def _predict_leaves_jit(stacked, X, T):
    return _forest_leaves(stacked, X).T


def _predict_scores_jit(stacked, X, T, K):
    leaves = _forest_leaves(stacked, X)  # [T, n]
    if stacked.lin_const is not None:
        vals = _linear_forest_values(stacked, X, leaves)
    else:
        vals = jnp.take_along_axis(stacked.leaf_value, leaves, axis=1)
    n = X.shape[0]
    # tree i contributes to class i % K
    scores = jnp.zeros((K, n), vals.dtype)
    class_of_tree = jnp.arange(T) % K
    scores = scores.at[class_of_tree].add(vals)
    return scores.T  # [n, K]


@jax.jit
def _linear_forest_values(stacked: StackedTrees, X: jnp.ndarray,
                          leaves: jnp.ndarray) -> jnp.ndarray:
    """Per-tree linear-leaf outputs (shared evaluator vmapped over
    trees)."""
    from .ops.linear import linear_leaf_values

    def per_tree(ti):
        return linear_leaf_values(
            stacked.lin_const[ti], stacked.lin_coef[ti],
            stacked.lin_feats[ti], stacked.lin_nfeat[ti],
            stacked.leaf_value[ti], X, leaves[ti])

    T = stacked.leaf_value.shape[0]
    return jax.vmap(per_tree)(jnp.arange(T))


def _predict_scores_early_stop(stacked, X, T, K, freq, margin):
    """Margin-based prediction early exit (prediction_early_stop.cpp):
    every ``freq`` iterations a row whose margin exceeds the threshold is
    frozen — binary margin = 2|score|, multiclass = top1 - top2. Rows are
    processed in tree chunks; once every row is frozen remaining chunks
    are skipped entirely."""
    n = X.shape[0]
    scores = jnp.zeros((n, K), stacked.leaf_value.dtype)
    done = jnp.zeros((n,), bool)
    chunk = freq * K
    for lo in range(0, T, chunk):
        hi = min(T, lo + chunk)
        sub = jax.tree_util.tree_map(lambda a: a[lo:hi], stacked)
        leaves = _forest_leaves(sub, X)                      # [t, n]
        if sub.lin_const is not None:
            vals = _linear_forest_values(sub, X, leaves)
        else:
            vals = jnp.take_along_axis(sub.leaf_value, leaves, axis=1)
        delta = jnp.zeros((K, n), vals.dtype)
        delta = delta.at[(jnp.arange(lo, hi)) % K].add(vals)
        scores = scores + jnp.where(done[:, None], 0.0, delta.T)
        if K == 1:
            m = 2.0 * jnp.abs(scores[:, 0])
        else:
            top2 = lax.top_k(scores, 2)[0]
            m = top2[:, 0] - top2[:, 1]
        done = done | (m > margin)
        if bool(jnp.all(done)):
            break
    return scores


def _convert_output(booster, out: np.ndarray) -> np.ndarray:
    return convert_raw_scores(booster._objective_str, out)


def convert_raw_scores(objective_str: Optional[str],
                       out: np.ndarray) -> np.ndarray:
    """Objective-specific output transform (ConvertOutput analog), driven
    by the objective string stored in the model header. Shared by the
    library predict path and the serving daemon (serve/), which applies
    it host-side after the compiled raw-score program."""
    obj = (objective_str or "none").split()
    name = obj[0] if obj else "none"
    kv = dict(t.split(":", 1) for t in obj[1:] if ":" in t)
    flags = {t for t in obj[1:] if ":" not in t}
    if name == "binary":
        sig = float(kv.get("sigmoid", 1.0))
        return 1.0 / (1.0 + np.exp(-sig * out))
    if name == "multiclass" or name == "softmax":
        e = np.exp(out - out.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)
    if name == "multiclassova":
        sig = float(kv.get("sigmoid", 1.0))
        return 1.0 / (1.0 + np.exp(-sig * out))
    if name in ("poisson", "gamma", "tweedie"):
        return np.exp(out)
    if name == "cross_entropy":
        return 1.0 / (1.0 + np.exp(-out))
    if name == "cross_entropy_lambda":
        return np.log1p(np.exp(out))
    if name in ("regression", "regression_l2") and "sqrt" in flags:
        return np.sign(out) * out * out
    return out
