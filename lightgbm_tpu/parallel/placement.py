"""Device-resident sharded dataset placement (``ShardPlan``).

The out-of-core ingest path (lightgbm_tpu/data/, docs/DATA.md) stopped
the dense float matrix from ever existing; this module removes the next
copy up the ladder: with ``Config.shard_residency="device"`` each
host's binned rows are laid **directly into their ``NamedSharding``
mesh slice** via ``jax.make_array_from_single_device_arrays``, and the
host copy is freed after the upload — so the global binned matrix
never sits whole in any single host's RAM (docs/SHARDING.md). This is
the device-side completion of the reference's distributed DatasetLoader
story (dataset_loader.cpp two-round load: every rank ends up holding
only its partition), re-expressed over a JAX mesh.

Topologies:

- **single-controller mesh** (one process, N local devices — including
  the virtual-CPU test worlds): every device's slice is cut from this
  host's matrix; the assembled global array is fully addressable.
- **multi-controller mesh** (one process per host on a pod): each
  process cuts slices only for its *addressable* mesh devices; the
  assembled array is the usual multi-host global jax.Array. The rows
  this process must hold are exactly its mesh slice — pair with
  ``spmd.distributed_dataset``, whose device-residency mode keeps each
  rank's binned shard local instead of allgathering the global matrix.

Every rank joins :func:`upload_barrier` after placing its shards — a
watchdog-guarded host collective (hostsync), so a host that died
mid-upload surfaces as an attributable error at a named sync point
instead of a hang in the first training collective. The barrier is
rank-invariant by construction (every rank joins unconditionally);
tpulint TPL007 holds that invariant at review time.

The checkpoint layer uses :func:`fetch_global` /
:func:`shard_fingerprints` to save a sharded score matrix: the
snapshot always stores the assembled ``[K, n]`` host matrix (so resume
works across residency modes), plus one sha256 per device shard so a
re-placed score can be proven equal to what was saved
(resilience/checkpoint.py).
"""

from __future__ import annotations

import hashlib
from typing import List, Optional

import numpy as np

__all__ = ["ShardPlan", "place_rows", "upload_barrier",
           "fetch_addressable", "fetch_global", "shard_fingerprints",
           "host_bytes_gauge"]


class ShardPlan:
    """Row layout of one global array over a 1-D mesh's data axis.

    ``n_global`` rows (caller-padded to a device-count multiple) are
    split into ``D`` equal contiguous shards in mesh-device order;
    shard ``d`` covers rows ``[d * rows_per_shard, (d+1) *
    rows_per_shard)``. The plan knows which shards are addressable
    from this process and builds the global array from per-device
    uploads of exactly those rows."""

    def __init__(self, mesh, n_global: int):
        devices = list(np.ravel(mesh.devices))
        if n_global % len(devices) != 0:
            raise ValueError(
                f"ShardPlan needs n_global ({n_global}) divisible by "
                f"the mesh size ({len(devices)}); pad the rows first "
                "(parallel.mesh.pad_rows)")
        self.mesh = mesh
        self.axis_name = mesh.axis_names[0]
        self.n_global = int(n_global)
        self.devices = devices
        self.rows_per_shard = self.n_global // len(devices)

    def local_shards(self):
        """(device, global_lo, global_hi) for each shard addressable
        from this process, in mesh order."""
        out = []
        for d, dev in enumerate(self.devices):
            if dev.process_index != _process_index():
                continue
            lo = d * self.rows_per_shard
            out.append((dev, lo, lo + self.rows_per_shard))
        return out

    def place(self, host_rows, row_axis: int = 0,
              local_offset: int = 0, exclusive_rows: bool = False):
        """Assemble the global device-resident array from this host's
        ``host_rows`` (numpy; rows on ``row_axis``).

        ``host_rows`` holds the global rows ``[local_offset,
        local_offset + host_rows.shape[row_axis])`` — the whole matrix
        on a single-controller mesh (``local_offset=0``), or just this
        rank's shard on a multi-controller one. Rows of a local mesh
        slice that the host matrix does not cover (row padding, or
        rows another rank also holds) are zero-filled.

        ``exclusive_rows=True`` declares that NO other rank holds
        these rows (the distributed_dataset keep-local path): every
        held row must then land inside this rank's own device windows
        — one outside would be zero-filled by another rank's pad and
        silently corrupt histograms, so place() refuses instead."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        host_rows = np.asarray(host_rows)
        gshape = list(host_rows.shape)
        gshape[row_axis] = self.n_global
        n_have = host_rows.shape[row_axis]
        blocks = []
        covered = 0
        for dev, lo, hi in self.local_shards():
            # global rows [cov_lo, cov_hi) of this shard are covered
            # by the host matrix; the rest (row padding / rows another
            # rank holds) zero-fill. Both bounds stay clamped inside
            # [lo, hi] so a shard with NO overlap (all padding, or
            # rows another rank holds) yields an empty block and a
            # full-width pad instead of negative pad widths.
            cov_lo = min(max(lo, local_offset), hi)
            cov_hi = min(max(min(hi, local_offset + n_have), cov_lo),
                         hi)
            covered += cov_hi - cov_lo
            sl = [slice(None)] * host_rows.ndim
            sl[row_axis] = slice(
                min(max(cov_lo - local_offset, 0), n_have),
                min(max(cov_hi - local_offset, 0), n_have))
            block = host_rows[tuple(sl)]
            if cov_hi - cov_lo != hi - lo:
                pad = [(0, 0)] * host_rows.ndim
                pad[row_axis] = (cov_lo - lo, hi - cov_hi)
                block = np.pad(block, pad)
            blocks.append((dev, block))
        if exclusive_rows and covered != n_have:
            # only THIS process holds these rows — any held row
            # outside its own device windows would be zero-filled by
            # some other rank's pad and silently corrupt histograms
            raise ValueError(
                f"ShardPlan.place: process {_process_index()} holds "
                f"global rows [{local_offset}, {local_offset + n_have}"
                f") but its device slices cover only {covered} of "
                f"those {n_have} rows — per-rank row counts must be a "
                f"whole number of device slices ({self.rows_per_shard}"
                " rows each); pad every rank's shard (weight-0 rows) "
                "so n_local is a multiple of rows_per_shard")
        spec = [None] * host_rows.ndim
        spec[row_axis] = self.axis_name
        sharding = NamedSharding(self.mesh, P(*spec))
        arrays = [jax.device_put(np.ascontiguousarray(block), dev)
                  for dev, block in blocks]
        return jax.make_array_from_single_device_arrays(
            tuple(gshape), sharding, arrays)


def _process_index() -> int:
    import jax

    try:
        return jax.process_index()
    except Exception:
        return 0


def place_rows(mesh, host_rows, row_axis: int = 0, pad: int = 0):
    """One-shot :class:`ShardPlan` placement for the single-controller
    case: shard ``host_rows`` (its ``row_axis`` extended by ``pad``
    zero rows) over ``mesh``'s data axis and return the global
    device-resident array. Multi-controller callers build a
    :class:`ShardPlan` with the global row count and pass their
    ``local_offset``."""
    host_rows = np.asarray(host_rows)
    plan = ShardPlan(mesh, int(host_rows.shape[row_axis]) + int(pad))
    return plan.place(host_rows, row_axis=row_axis)


def upload_barrier(what: str = "placement/upload_barrier") -> None:
    """Post-upload world sync: every rank joins unconditionally (never
    rank-guard this call — a rank that skips it deadlocks the world;
    TPL007). Single-process worlds return immediately."""
    import jax

    if jax.process_count() <= 1:
        return
    from .hostsync import host_allgather

    host_allgather(np.asarray([_process_index()], np.int64), what)


def fetch_addressable(arr) -> np.ndarray:
    """Host value of a fully-addressable (numpy / single-controller)
    array — never a collective. A multi-controller global array raises:
    assemble those with :func:`fetch_global`, a world collective every
    rank must join — callers that rank-gate their work (checkpoint
    writes) must hoist that gather above the gate and pass the result
    down."""
    if isinstance(arr, np.ndarray):
        return arr
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    raise RuntimeError(
        "fetch_addressable: the array is not fully addressable from "
        "this process; assemble it with placement.fetch_global (a "
        "world collective — every rank must join) and pass the host "
        "matrix down")


def fetch_global(arr) -> np.ndarray:
    """The full host value of a possibly-sharded array.

    numpy / fully-addressable jax arrays: one ``np.asarray``. A
    multi-controller global array is assembled from this process's
    addressable shards allgathered over the host transport (every rank
    joins — the sharded-checkpoint gather named by docs/SHARDING.md);
    ranks hold identical results afterwards, so rank 0 can write the
    snapshot for all."""
    if isinstance(arr, np.ndarray) \
            or getattr(arr, "is_fully_addressable", True):
        return fetch_addressable(arr)
    from .hostsync import host_allgather

    # gather only this rank's shard DATA plus tiny index bounds — not
    # a full-array-shaped buffer per rank (at [K, n] f32 score scale
    # that would ship P x the whole matrix through the host transport
    # per snapshot). Same-index local shards (replication within a
    # rank) collapse to one contribution, mirroring cross-rank
    # replication raising below.
    uniq = {}
    for sh in arr.addressable_shards:
        uniq.setdefault(str(sh.index), sh)
    shards = [uniq[k] for k in sorted(uniq)]
    blocks = [np.ascontiguousarray(np.asarray(sh.data))
              for sh in shards]
    if len({b.shape for b in blocks}) != 1:
        raise RuntimeError(
            "placement.fetch_global: unequal local shard shapes — "
            "only equal-partition NamedSharding layouts are supported")
    bounds = np.asarray(
        [[(sl.start or 0,
           sl.stop if sl.stop is not None else dim)
          for sl, dim in zip(sh.index, arr.shape)]
         for sh in shards], np.int64)              # [S, ndim, 2]
    gdata = host_allgather(np.stack(blocks),
                           "placement/checkpoint_gather")
    gidx = host_allgather(bounds, "placement/checkpoint_gather_idx")
    out = np.zeros(arr.shape, arr.dtype)
    count = np.zeros(arr.shape, np.uint8)          # local, never sent
    for p in range(gdata.shape[0]):
        for s in range(gdata.shape[1]):
            sl = tuple(slice(int(a), int(b)) for a, b in gidx[p, s])
            out[sl] = gdata[p, s]
            count[sl] += 1
    if (count == 0).any() or (count > 1).any():
        raise RuntimeError(
            "placement.fetch_global: shard covers do not tile the "
            "array exactly (a rank is missing or shards overlap)")
    return out


def shard_fingerprints(arr) -> Optional[List[dict]]:
    """One ``{"index", "sha256"}`` per addressable shard of ``arr``
    (device order), or None for unsharded/host arrays — the
    per-rank/per-device identity the checkpoint stores so a re-placed
    sharded score can be proven byte-equal to what was saved."""
    shards = getattr(arr, "addressable_shards", None)
    if shards is None or len(shards) <= 1:
        return None
    out = []
    for sh in sorted(shards, key=lambda s: str(s.index)):
        h = hashlib.sha256(
            np.ascontiguousarray(np.asarray(sh.data)).tobytes())
        out.append({"index": str(sh.index), "sha256": h.hexdigest()})
    return out


def host_bytes_gauge(nbytes: int) -> None:
    """Publish the host-resident binned-matrix footprint (bytes) to
    the telemetry registry — the measured backing for the "no host
    holds the global matrix" claim (bench.py --streaming records it)."""
    try:
        from ..obs.registry import registry
        registry.gauge("host_binned_bytes").set(float(nbytes))
    except Exception:
        pass
