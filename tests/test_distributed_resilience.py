"""Distributed resilience: collective watchdog, init retry/backoff,
elastic launch supervisor, distributed fault kinds.

Three layers:

1. fast unit tests — watchdog deadlines/passthrough, init retry with
   injected refusals (monkeypatched ``jax.distributed.initialize``),
   ``parse_machines`` edge cases, FaultPlan distributed kinds, the
   supervisor restart loop with jax-free workers, telemetry
   truncation tolerance;
2. subprocess regression — ``kill@N`` mid-iteration with telemetry on:
   the stream must re-parse;
3. chaos tests (``slow`` + ``mp``) — a real 2-process world over the
   kv host transport: ``stall_rank`` makes the surviving rank raise a
   watchdog ``LightGBMError`` naming the stuck collective (no hang, no
   orphans), and ``python -m lightgbm_tpu launch`` survives
   ``rank_kill`` + ``init_refuse``, restarting from the newest
   checkpoint to a model byte-identical to an uninterrupted run.
"""

import json
import os
import subprocess
import sys
import time

import pytest

import lightgbm_tpu  # noqa: F401  (repo-root sys.path via conftest)
from _mp_utils import (REPO_DIR, TESTS_DIR, free_port, kill_group,
                       spawn_worker, worker_base_env)
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.obs.recorder import summarize_events
from lightgbm_tpu.obs.registry import registry
from lightgbm_tpu.resilience import watchdog
from lightgbm_tpu.resilience.elastic import (strip_one_shot_faults,
                                             supervise, worker_env)
from lightgbm_tpu.resilience.faults import (FAULT_EVENTS, FaultPlan,
                                            InjectedInitRefused)
from lightgbm_tpu.parallel import distributed
from lightgbm_tpu.parallel.distributed import (init_distributed,
                                               parse_machines)

pytestmark = pytest.mark.mp


# ---------------------------------------------------------------------
# watchdog unit tests (single process; guarded() itself is jax-free)
# ---------------------------------------------------------------------

def test_watchdog_passthrough_and_heartbeat():
    assert watchdog.guarded("t/ok", lambda: {"x": 1}, deadline=5.0,
                            iteration=4, world=2) == {"x": 1}
    heard = watchdog.last_heard()
    assert heard["name"] == "t/ok"
    assert heard["iteration"] == 4
    assert heard["world"] == 2


def test_watchdog_timeout_names_collective_and_counts():
    before = registry.counter("collective_timeouts").value
    FAULT_EVENTS.clear()
    with pytest.raises(LightGBMError) as ei:
        watchdog.guarded("telemetry/verify_step", time.sleep, 10,
                         iteration=12, deadline=0.2)
    msg = str(ei.value)
    assert "telemetry/verify_step" in msg
    assert "iteration 12" in msg
    assert "deadline" in msg
    assert registry.counter("collective_timeouts").value == before + 1
    kinds = [e["kind"] for e in FAULT_EVENTS]
    assert "collective_timeout" in kinds


def test_watchdog_wraps_transport_error_but_not_lgbm_error():
    def boom():
        raise RuntimeError("connection reset by peer")

    with pytest.raises(LightGBMError) as ei:
        watchdog.guarded("spmd/sync_bin_mappers", boom, deadline=5.0)
    assert "spmd/sync_bin_mappers" in str(ei.value)
    assert isinstance(ei.value.__cause__, RuntimeError)

    def diverged():
        raise LightGBMError("SPMD divergence: ranks disagree")

    with pytest.raises(LightGBMError) as ei:
        watchdog.guarded("spmd/verify_step", diverged, deadline=5.0)
    # the collective's own LightGBMError passes through unwrapped
    assert str(ei.value) == "SPMD divergence: ranks disagree"


def test_watchdog_deadline_resolution(monkeypatch):
    monkeypatch.delenv("LIGHTGBM_TPU_COLLECTIVE_TIMEOUT", raising=False)
    watchdog.configure(None)
    assert watchdog.deadline_seconds() == \
        watchdog.DEFAULT_DEADLINE_SECONDS
    watchdog.configure(42.0)
    assert watchdog.deadline_seconds() == 42.0
    monkeypatch.setenv("LIGHTGBM_TPU_COLLECTIVE_TIMEOUT", "7.5")
    assert watchdog.deadline_seconds() == 7.5   # env wins
    watchdog.configure(None)


def test_watchdog_config_field_parses():
    from lightgbm_tpu.config import Config
    cfg = Config.from_params({"collective_timeout_sec": "12.5"})
    assert cfg.collective_timeout_sec == 12.5
    with pytest.raises(ValueError):
        Config.from_params({"collective_timeout_sec": -1})


# ---------------------------------------------------------------------
# parse_machines edge cases + init_distributed arg validation
# ---------------------------------------------------------------------

def test_parse_machines_string_formats():
    assert parse_machines(machines="a:1,b:2") == [("a", 1), ("b", 2)]
    # whitespace, blank entries, newlines as separators
    assert parse_machines(machines=" a:1 , ,\n b:2 ,, ") == \
        [("a", 1), ("b", 2)]
    assert parse_machines(machines="") == []
    assert parse_machines() == []


def test_parse_machines_file_formats(tmp_path):
    # 'host port', 'host:port', blank + whitespace-only lines,
    # multi-space separators
    mlist = tmp_path / "mlist.txt"
    mlist.write_text("10.0.0.1 12400\n\n   \n10.0.0.2:12401\n"
                     "  10.0.0.3   12402  \n")
    assert parse_machines(machine_list_file=str(mlist)) == [
        ("10.0.0.1", 12400), ("10.0.0.2", 12401), ("10.0.0.3", 12402)]


def test_parse_machines_port_defaults_and_errors():
    assert parse_machines(machines="justhost") == [("justhost", 0)]
    with pytest.raises(ValueError, match="bad port"):
        parse_machines(machines="host:notaport")
    with pytest.raises(ValueError, match="bad machine-list entry"):
        parse_machines(machines="a:1:2")


def test_single_entry_machine_list_is_noop(monkeypatch):
    # num_machines=1: must return without touching jax.distributed
    import jax

    def forbid(**kwargs):
        raise AssertionError("initialize called for a 1-machine list")

    monkeypatch.setattr(jax.distributed, "initialize", forbid)
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    init_distributed(machines="localhost:12400")
    assert distributed._INITIALIZED is False


def test_missing_rank_raises(monkeypatch):
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    monkeypatch.delenv("LIGHTGBM_TPU_RANK", raising=False)
    with pytest.raises(ValueError, match="local_rank"):
        init_distributed(machines="a:1,b:2")


# ---------------------------------------------------------------------
# init retry / backoff (monkeypatched initialize — no real network)
# ---------------------------------------------------------------------

def test_init_retry_succeeds_after_injected_refusals(monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "init_refuse@2")
    monkeypatch.setenv("LIGHTGBM_TPU_INIT_BACKOFF", "0.01")
    before = registry.counter("init_retries").value
    init_distributed(coordinator_address="127.0.0.1:1",
                     num_processes=2, process_id=0)
    assert distributed._INITIALIZED is True
    assert len(calls) == 1   # real initialize ran once, after refusals
    # acceptance: init_retries == K for init_refuse@K
    assert registry.counter("init_retries").value == before + 2
    assert registry.counter("init_backoff_seconds").value > 0
    monkeypatch.setattr(distributed, "_INITIALIZED", False)


def test_init_retries_exhausted_raises(monkeypatch):
    import jax

    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(AssertionError("unreached")))
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_INJECT", "init_refuse@99")
    monkeypatch.setenv("LIGHTGBM_TPU_INIT_BACKOFF", "0.001")
    monkeypatch.setenv("LIGHTGBM_TPU_INIT_RETRIES", "3")
    with pytest.raises(LightGBMError, match="4 attempts"):
        init_distributed(coordinator_address="127.0.0.1:1",
                         num_processes=2, process_id=0)
    assert distributed._INITIALIZED is False


def test_init_nonretryable_error_propagates(monkeypatch):
    import jax

    def bad(**kw):
        raise RuntimeError("invalid coordinator address")

    monkeypatch.setattr(jax.distributed, "initialize", bad)
    monkeypatch.setattr(distributed, "_INITIALIZED", False)
    monkeypatch.delenv("LIGHTGBM_TPU_FAULT_INJECT", raising=False)
    with pytest.raises(RuntimeError, match="invalid coordinator"):
        init_distributed(coordinator_address="127.0.0.1:1",
                         num_processes=2, process_id=0)


# ---------------------------------------------------------------------
# FaultPlan distributed kinds
# ---------------------------------------------------------------------

def test_fault_plan_distributed_kinds_parse():
    p = FaultPlan("rank_kill@3,stall_rank@5,init_refuse@2,nan_grad@1")
    assert p.iters("rank_kill") == (3,)
    assert p.iters("stall_rank") == (5,)
    assert p._init_refusals_left == 2
    with pytest.raises(ValueError, match="unknown fault-injection"):
        FaultPlan("explode@3")


def test_fault_plan_init_refusals_consume():
    p = FaultPlan("init_refuse@2")
    for _ in range(2):
        with pytest.raises(InjectedInitRefused,
                           match="connection refused"):
            p.maybe_refuse_init()
    p.maybe_refuse_init()   # budget spent: no-op
    assert p._init_refusals_left == 0


def test_fault_rank_gating(monkeypatch):
    # this single process is rank 0; a fault targeted at rank 1 must
    # not fire (and must not consume its token)
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_RANK", "1")
    p = FaultPlan("stall_rank@0")
    p.maybe_distributed_fault(0)   # would sleep forever if mis-gated
    assert p.iters("stall_rank") == (0,)
    monkeypatch.setenv("LIGHTGBM_TPU_FAULT_RANK", "0,3")
    assert FaultPlan._rank_selected() is True


# ---------------------------------------------------------------------
# elastic supervisor (jax-free workers: pure restart-loop logic)
# ---------------------------------------------------------------------

_FLAKY_WORKER = """\
import os, sys
marker = sys.argv[1]
if os.environ["LIGHTGBM_TPU_RANK"] == "0" and not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(5)
sys.exit(0)
"""


def test_supervisor_restarts_failed_world(tmp_path):
    worker = tmp_path / "flaky.py"
    worker.write_text(_FLAKY_WORKER)
    marker = tmp_path / "marker"
    rc = supervise(2, [sys.executable, str(worker), str(marker)],
                   max_restarts=2, log_dir=str(tmp_path), grace=1.0,
                   env=dict(os.environ))
    assert rc == 0
    # generation 0 failed, generation 1 succeeded — both logged
    assert (tmp_path / "elastic_g0_rank0.log").exists()
    assert (tmp_path / "elastic_g1_rank0.log").exists()
    assert not (tmp_path / "elastic_g2_rank0.log").exists()


def test_supervisor_exhausts_restart_budget(tmp_path):
    worker = tmp_path / "fail.py"
    worker.write_text("import sys; sys.exit(7)\n")
    rc = supervise(1, [sys.executable, str(worker)], max_restarts=1,
                   log_dir=str(tmp_path), grace=0.5,
                   env=dict(os.environ))
    assert rc == 7
    assert (tmp_path / "elastic_g1_rank0.log").exists()


def test_worker_env_wiring_and_fault_stripping():
    base = {"LIGHTGBM_TPU_FAULT_INJECT":
            "rank_kill@3,stall_rank@5,oom@2,init_refuse@1"}
    g0 = worker_env(base, rank=1, nprocs=4, port=555, generation=0)
    assert g0["LIGHTGBM_TPU_COORDINATOR"] == "127.0.0.1:555"
    assert g0["LIGHTGBM_TPU_NUM_PROCS"] == "4"
    assert g0["LIGHTGBM_TPU_RANK"] == "1"
    assert g0["LIGHTGBM_TPU_FAULT_INJECT"] == base[
        "LIGHTGBM_TPU_FAULT_INJECT"]   # generation 0 keeps the plan
    g1 = worker_env(base, rank=0, nprocs=4, port=556, generation=1)
    # one-shot distributed kinds must not re-fire after a restart
    assert g1["LIGHTGBM_TPU_FAULT_INJECT"] == "oom@2,init_refuse@1"
    assert strip_one_shot_faults("rank_kill@1") == ""


def test_launch_cli_is_jax_free():
    """The supervisor must never import jax: it outlives dying worker
    worlds and must not pin the accelerator devices they need."""
    code = ("import sys\n"
            "from lightgbm_tpu.resilience.elastic import build_parser\n"
            "text = build_parser().format_help()\n"
            "assert 'exit codes' in text and '--max-restarts' in text\n"
            "assert 'jax' not in sys.modules, 'launch imported jax!'\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------
# telemetry truncation (satellite): a killed writer must leave a
# re-parseable stream
# ---------------------------------------------------------------------

def _iteration_event(i):
    return {"event": "iteration", "iteration": i, "wall_time": 0.1 * i,
            "phases": {}, "recompiles": {"delta": 0, "total": 0},
            "hbm": {}, "tree": {"trees": 1, "leaves": 3,
                                "split_gain_sum": 1.0}, "eval": {}}


def test_summarize_tolerates_truncated_final_line(tmp_path):
    path = tmp_path / "t.jsonl"
    with open(path, "w") as fh:
        for i in range(3):
            fh.write(json.dumps(_iteration_event(i)) + "\n")
        fh.write('{"event": "iteration", "iteration": 3, "wal')  # cut
    summary = summarize_events(str(path))
    assert summary["iterations"] == 3


def test_summarize_still_rejects_mid_file_garbage(tmp_path):
    path = tmp_path / "t.jsonl"
    with open(path, "w") as fh:
        fh.write(json.dumps(_iteration_event(0)) + "\n")
        fh.write("NOT JSON AT ALL\n")
        fh.write(json.dumps(_iteration_event(1)) + "\n")
    with pytest.raises(ValueError):
        summarize_events(str(path))


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_kill_mid_iteration_leaves_parseable_stream(tmp_path):
    """The regression the recorder-hardening satellite pins: SIGKILL
    mid-train (kill@7) must never leave the JSONL stream unparseable —
    whatever landed before the kill summarizes cleanly."""
    telem = tmp_path / "run.jsonl"
    env = worker_base_env({
        "JAX_PLATFORMS": "cpu",
        "LIGHTGBM_TPU_TELEMETRY": str(telem),
        "LIGHTGBM_TPU_FAULT_INJECT": "kill@7",
    })
    proc = spawn_worker(
        [os.path.join(TESTS_DIR, "ckpt_worker.py"),
         str(tmp_path / "model.txt")], env)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == -9, out.decode(errors="replace")
    summary = summarize_events(str(telem))   # must not raise
    assert 1 <= summary["iterations"] <= 7


# ---------------------------------------------------------------------
# chaos: real 2-process worlds over the kv host transport
# ---------------------------------------------------------------------

def _chaos_env(tmp_path, port, rank, fault="", fault_rank="1",
               deadline="20"):
    return worker_base_env({
        "LIGHTGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "LIGHTGBM_TPU_NUM_PROCS": "2",
        "LIGHTGBM_TPU_RANK": str(rank),
        "LIGHTGBM_TPU_CHECKPOINT": str(tmp_path / "ckpts"),
        "LIGHTGBM_TPU_TELEMETRY": str(tmp_path / "telemetry.jsonl"),
        "LIGHTGBM_TPU_FAULT_INJECT": fault,
        "LIGHTGBM_TPU_FAULT_RANK": fault_rank,
        "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": deadline,
        "LIGHTGBM_TPU_INIT_BACKOFF": "0.05",
    })


@pytest.mark.slow
@pytest.mark.timeout(420)
def test_stalled_rank_aborts_survivor_within_deadline(tmp_path):
    """stall_rank@2 on rank 1: the survivor must raise a watchdog
    LightGBMError naming the stuck collective — no hang, no orphan
    processes."""
    port = free_port()
    worker = os.path.join(TESTS_DIR, "elastic_worker.py")
    procs = [
        spawn_worker([worker, str(tmp_path)],
                     _chaos_env(tmp_path, port, rank,
                                fault="stall_rank@2", fault_rank="1",
                                deadline="15"))
        for rank in (0, 1)
    ]
    t0 = time.monotonic()
    try:
        out0, _ = procs[0].communicate(timeout=300)
    except subprocess.TimeoutExpired:
        from _mp_utils import drain_all
        drain_all(procs, "survivor hung despite the watchdog")
    elapsed = time.monotonic() - t0
    text0 = out0.decode(errors="replace")
    assert procs[0].returncode == 13, text0
    assert "WORKER ABORT" in text0
    # the error names the stuck collective and the silent rank
    assert "spmd/verify_step" in text0, text0
    assert "rank 1" in text0, text0
    # "within the watchdog deadline": init+train+deadline, with CI slack
    assert elapsed < 240, f"survivor took {elapsed:.0f}s to abort"
    # the stalled rank is still alive (that is the failure mode);
    # reap it so nothing leaks into the suite
    assert procs[1].poll() is None, "stalled rank exited early?"
    kill_group(procs[1])
    procs[1].communicate(timeout=30)
    # the fault stream recorded the timeout (rank 0 is the writer)
    summary = summarize_events(str(tmp_path / "telemetry.jsonl"))
    assert summary["faults"].get("collective_timeout", 0) >= 1


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_launch_supervisor_resumes_to_identical_model(tmp_path):
    """End-to-end acceptance: `python -m lightgbm_tpu launch` survives
    rank_kill@3 (+ init_refuse@2 on every rank), restarts the world
    from the newest checkpoint, and the final model is byte-identical
    to an uninterrupted supervised run. init_retries==2 is proved from
    the worker logs."""
    worker = os.path.join(TESTS_DIR, "elastic_worker.py")

    def launch(outdir, fault):
        outdir.mkdir()
        env = worker_base_env({
            "JAX_PLATFORMS": "cpu",
            "LIGHTGBM_TPU_CHECKPOINT": str(outdir / "ckpts"),
            "LIGHTGBM_TPU_TELEMETRY": str(outdir / "telemetry.jsonl"),
            "LIGHTGBM_TPU_FAULT_INJECT": fault,
            "LIGHTGBM_TPU_FAULT_RANK": "1",
            "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": "15",
            "LIGHTGBM_TPU_INIT_BACKOFF": "0.05",
        })
        proc = subprocess.run(
            [sys.executable, "-m", "lightgbm_tpu", "launch", "2",
             "--max-restarts", "2", "--log-dir", str(outdir),
             # grace > watchdog deadline: the survivor must get to
             # abort (and log) on its own before the world teardown
             "--grace", "30", "--",
             sys.executable, worker, str(outdir)],
            env=env, cwd=REPO_DIR, capture_output=True, text=True,
            timeout=540)
        return proc

    faulted = launch(tmp_path / "faulted",
                     "rank_kill@3,init_refuse@2")
    assert faulted.returncode == 0, (
        f"supervised run failed:\n{faulted.stdout}\n{faulted.stderr}\n"
        + _tail_logs(tmp_path / "faulted"))
    g0_rank0 = (tmp_path / "faulted" / "elastic_g0_rank0.log").read_text()
    g1_rank0 = (tmp_path / "faulted" / "elastic_g1_rank0.log").read_text()
    # generation 0: every rank retried init exactly K=2 times...
    assert "INIT_RETRIES=2" in g0_rank0
    # ...and the survivor watchdog-aborted on the stuck collective
    assert "WORKER ABORT" in g0_rank0
    assert "spmd/verify_step" in g0_rank0
    # generation 1 resumed and finished all 8 rounds
    assert "rank 0 DONE iterations=8" in g1_rank0

    clean = launch(tmp_path / "clean", "")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    model_faulted = (tmp_path / "faulted" / "model_elastic.txt").read_bytes()
    model_clean = (tmp_path / "clean" / "model_elastic.txt").read_bytes()
    assert model_faulted == model_clean, (
        "restarted world diverged from the uninterrupted run")


def _tail_logs(d, limit=2000):
    parts = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return "(no log dir)"
    for name in names:
        if name.startswith("elastic_g") and name.endswith(".log"):
            try:
                text = (d / name).read_text(errors="replace")
            except OSError:
                continue
            parts.append(f"--- {name} ---\n{text[-limit:]}")
    return "\n".join(parts)
