"""Subprocess worker for the checkpoint kill/resume tests.

Usage: python ckpt_worker.py <model_out>

Trains a fixed deterministic 20-round regression model on the CPU
backend. All resilience wiring comes from the environment the parent
test sets:

- ``LIGHTGBM_TPU_CHECKPOINT=<dir>`` — auto-checkpoint every iteration
  AND auto-resume from the newest valid snapshot,
- ``LIGHTGBM_TPU_FAULT_INJECT=kill@N`` — SIGKILL mid-train (the run
  the parent expects to die with -SIGKILL),
- ``CKPT_WORKER_PARAMS=<json>`` — extra params merged over the
  defaults (the fused-scan resume tests pass ``fused_scan_iters`` and
  drop the host-RNG ``feature_fraction`` so the scan engages).

On completion the model is saved to ``<model_out>`` and ``WORKER DONE``
is printed; the parent compares the saved model byte-for-byte against
an uninterrupted run.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import lightgbm_tpu as lgb  # noqa: E402

NUM_ROUNDS = 20
PARAMS = {
    "objective": "regression", "num_leaves": 7, "verbosity": -1,
    "min_data_in_leaf": 5, "bagging_fraction": 0.7, "bagging_freq": 3,
    "feature_fraction": 0.8, "seed": 11,
}


def make_data():
    rs = np.random.RandomState(4)
    X = rs.randn(800, 8)
    y = X @ rs.randn(8) + 0.1 * rs.randn(800)
    return X, y


def main() -> int:
    model_out = sys.argv[1]
    X, y = make_data()
    params = dict(PARAMS)
    extra = os.environ.get("CKPT_WORKER_PARAMS")
    if extra:
        import json
        params.update(json.loads(extra))
    bst = lgb.train(params, lgb.Dataset(X, label=y),
                    num_boost_round=NUM_ROUNDS)
    bst.save_model(model_out)
    print(f"WORKER DONE iterations={bst.current_iteration()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
