# tpulint fixture: TPL007 positive — rank-divergent collective order.
# An `# EXPECT: <RULE>` comment pins a finding (by rule id + line
# number) on the line that FOLLOWS it. Fixtures are parsed, never
# imported.
import os

import jax
from jax.experimental import multihost_utils

from lightgbm_tpu.parallel.hostsync import (host_allgather,
                                            host_broadcast_bytes)


def rank_gated_collective(arr):
    """The direct shape: only rank 0 ever joins the allgather."""
    if jax.process_index() == 0:
        # EXPECT: TPL007
        return host_allgather(arr, "bad/rank_gated")
    return arr[None]


def early_return_divergence(arr):
    """The early-return shape: the collective is lexically unguarded,
    but the CFG meet carries the rank pin past the diverting arm."""
    rank = jax.process_index()
    if rank != 0:
        return None
    # EXPECT: TPL007
    return host_allgather(arr, "bad/early_return")


def collective_in_handler(arr):
    """Only ranks that hit the exception run the recovery broadcast."""
    try:
        out = host_allgather(arr, "ok/try_body_is_fine")
    except RuntimeError:
        # EXPECT: TPL007
        host_broadcast_bytes(b"", "bad/recovery")
        out = None
    return out


def env_rank_gate():
    """LIGHTGBM_TPU_RANK-derived condition, through int()."""
    me = int(os.environ.get("LIGHTGBM_TPU_RANK", "0"))
    if me == 0:
        # EXPECT: TPL007
        multihost_utils.sync_global_devices("bad/env_gate")


def rank_dependent_trip_count(arr):
    """A rank-dependent number of joins deadlocks like a skipped one."""
    for _ in range(jax.process_index()):
        # EXPECT: TPL007
        host_allgather(arr, "bad/loop")
