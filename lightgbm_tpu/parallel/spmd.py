"""Multi-controller (SPMD) training helpers.

The reference's distributed data loading protocol
(/root/reference/src/io/dataset_loader.cpp:1070
``ConstructBinMappersFromTextData``): each rank loads its row shard,
ranks find bins on disjoint feature subsets, and the serialized
BinMappers are allgathered (:1228-1236) so every rank bins against
IDENTICAL boundaries. The Dask layer then trains per-worker and keeps
worker 0's model (python-package/lightgbm/dask.py:_train_part).

Under JAX's multi-controller runtime the same protocol is three steps:
``init_distributed`` (parallel/distributed.py) wires the processes,
``sync_bin_mappers`` broadcasts process 0's mappers to all, and the
ordinary mesh-parallel Booster trains SPMD — every process computes the
identical replicated model, so there is no "keep worker 0's result"
step at all.

    from lightgbm_tpu.parallel import distributed, spmd
    distributed.init_distributed(...)          # Network::Init analog
    ds = spmd.distributed_dataset(my_shard_X, my_shard_y, params=...)
    bst = lgb.train(params | {"tree_learner": "data"}, ds, 100)
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

__all__ = ["sync_bin_mappers", "distributed_dataset"]


def sync_bin_mappers(mappers: List) -> List:
    """Make bin boundaries identical on every process: serialize
    process 0's mappers and broadcast (the Network::Allgather of
    serialized BinMappers, dataset_loader.cpp:1228, collapsed to a
    one-to-all broadcast — process 0's sample decides, like rank-0
    bin-merging in ConstructFromSampleData :723)."""
    import jax

    if jax.process_count() <= 1:
        return mappers
    from jax.experimental import multihost_utils
    from ..ops.binning import BinMapper

    payload = json.dumps([m.to_dict() for m in mappers]).encode()
    # length-prefix so every process allocates the same buffer; only
    # process 0's bytes matter (and only they fit the broadcast size —
    # other ranks' serializations can be longer)
    n = np.asarray([len(payload)], np.int32)
    n = multihost_utils.broadcast_one_to_all(n)
    buf = np.zeros(int(n[0]), np.uint8)
    if jax.process_index() == 0:
        buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(buf)
    dicts = json.loads(bytes(buf.tobytes()).decode())
    return [BinMapper.from_dict(d) for d in dicts]


def distributed_dataset(X, label=None, params: Optional[dict] = None,
                        **kwargs):
    """Build a Dataset from THIS process's row shard with bin
    boundaries synchronized across all processes (rank-strided loading
    + mapper sync, the LoadFromFile(rank, num_machines) analog)."""
    from ..basic import Dataset

    ds = Dataset(X, label=label, params=params, **kwargs)
    ds.construct()
    ds.mappers = sync_bin_mappers(ds.mappers)
    # re-bin the local rows against the synchronized boundaries
    import jax

    if jax.process_count() > 1:
        from ..ops.binning import bin_values

        Xf = np.asarray(X, np.float64)
        cols = [Xf[:, j] for j in ds._used_features]
        ds._bins = bin_values(cols, ds.mappers)
        ds._device_bins = None
    return ds
