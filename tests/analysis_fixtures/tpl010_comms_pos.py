# tpulint fixture: TPL010 positives — the parallel/comms.py quantized
# allreduce wrappers ARE device collectives: wrapping lax.psum in
# comms.hist_allreduce must not blind the rule (ISSUE 9), including
# when comms.py itself is outside the linted file set.
import jax.numpy as jnp
from jax import lax

from lightgbm_tpu.parallel import comms


def quantized_reduce_in_branch(pred, hist, axis):
    """comms.hist_allreduce lexically inside a cond branch lambda."""
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: comms.hist_allreduce(hist, axis, "int8"),
                    lambda: hist)


def _pool_miss_recompute(hist, axis, ef):
    """Local helper that transitively dispatches the quantized
    allreduce — the ops/grow.py window_hist -> hist_psum_ef shape."""
    return comms.hist_allreduce(hist, axis, "int16", ef)


def branch_reaches_wrapper_through_helper(pred, hist, axis, ef):
    """The hazard one call level down: the branch calls a local
    function that reaches the comms wrapper through the call graph."""
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: _pool_miss_recompute(hist, axis, ef),
                    lambda: (hist, ef))


def bare_import_spelling(pred, hist, axis):
    """`from ..parallel.comms import hist_allreduce` spelling."""
    from lightgbm_tpu.parallel.comms import hist_allreduce
    # EXPECT: TPL010
    return lax.cond(pred,
                    lambda: hist_allreduce(hist, axis, "int8"),
                    lambda: hist)
