# tpulint fixture: TPL008 positive — a /metrics scrape endpoint whose
# request-handler threads mutate shared scrape bookkeeping with no
# lock. Handler methods of http.server/socketserver request-handler
# subclasses run on the serving stack's per-connection daemon threads
# (ThreadingHTTPServer), which no Thread(target=...) spawn reveals —
# the analyzer seeds them thread-side from the class bases. This is
# the strip-the-export-lock acceptance shape: obs/tpl008_export_neg.py
# is the same endpoint WITH the lock, and removing it must re-surface
# these findings.
import http.server
import socketserver
import threading

_scrapes = {}          # port -> scrape count, shared with readers


class ScrapeHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        # EXPECT: TPL008
        _scrapes[self.server.server_address[1]] = \
            _scrapes.get(self.server.server_address[1], 0) + 1
        self.send_response(200)
        self.end_headers()


class ProtocolHandler(socketserver.StreamRequestHandler):
    def handle(self):
        # EXPECT: TPL008
        _scrapes["protocol"] = _scrapes.get("protocol", 0) + 1


def scrape_count(port):
    return _scrapes.get(port, 0)


def start(port):
    server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                             ScrapeHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
