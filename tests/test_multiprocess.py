"""REAL multi-process SPMD training: two OS processes, each with two
virtual CPU devices, form one 4-device mesh over the JAX distributed
runtime (the reference's socket/MPI Network::Init + distributed
learners, _test_distributed.py:54 pattern) and must train the
IDENTICAL model a single process trains on the same 4-device mesh.

This is the full multi-host path: coordinator wiring
(parallel/distributed.py), bin-mapper sync + per-process row shards
(parallel/spmd.py), and global-array assembly for the shard_map
learner (models/gbdt.py). The data-parallel learner dispatches jitted
collectives across processes, which jaxlib's CPU backend refuses
("Multiprocess computations aren't implemented on the CPU backend") —
hence the capability gate; the host-transport chaos tests
(test_distributed_resilience.py) cover the CPU-runnable distributed
surface.
"""

import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb
from _mp_utils import (TESTS_DIR, drain_all, free_port,
                       requires_multiprocess_computations, spawn_worker,
                       worker_base_env)

pytestmark = pytest.mark.mp


@requires_multiprocess_computations
@pytest.mark.timeout(600)
def test_two_process_data_parallel_matches_single_process(tmp_path):
    port = free_port()
    env = worker_base_env()
    procs = [
        spawn_worker([os.path.join(TESTS_DIR, "spmd_worker.py"),
                      str(rank), str(port), str(tmp_path)], env)
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            drain_all(procs, "SPMD workers timed out after 540 s "
                             "(stuck collective?)")
        outs.append(out.decode())
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"rank {rank} DONE" in out

    # single-process oracle: same data, same 4-device mesh, and bin
    # boundaries from process 0's shard (what sync_bin_mappers
    # broadcast in the workers)
    rs = np.random.RandomState(0)
    n, f = 2000, 6
    X = rs.randn(n, f)
    y = ((X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2]
          + 0.1 * rs.randn(n)) > 0).astype(float)
    ref = lgb.Dataset(X[: n // 2], label=y[: n // 2],
                      params={"verbosity": -1})
    ref.construct()
    full = lgb.Dataset(X, label=y, reference=ref)
    single = lgb.train({"objective": "binary", "num_leaves": 15,
                        "min_data_in_leaf": 5, "tree_learner": "data",
                        "num_devices": 4, "verbosity": -1}, full,
                       num_boost_round=5)
    mp_model = lgb.Booster(
        model_file=str(tmp_path / "model_mp.txt"))
    ps = single.predict(X[:300])
    pm = mp_model.predict(X[:300])
    np.testing.assert_allclose(ps, pm, rtol=1e-5, atol=1e-7)
