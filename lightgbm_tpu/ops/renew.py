"""Per-leaf output refinement (RenewTreeOutput analog).

The reference's L1-family objectives re-fit each leaf's output as a
(weighted) percentile of the residuals in that leaf
(/root/reference/src/objective/regression_objective.hpp RenewTreeOutput /
PercentileFun / WeightedPercentileFun). TPU re-design: one lexicographic
sort of (leaf, residual) over all rows, then segment-wise weighted
percentile selection — no per-leaf gather loops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["renew_leaf_values"]


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def renew_leaf_values(row_leaf: jnp.ndarray,
                      residual: jnp.ndarray,
                      row_weight: jnp.ndarray,
                      num_leaves: int,
                      alpha: float,
                      fallback: jnp.ndarray) -> jnp.ndarray:
    """Weighted alpha-percentile of ``residual`` per leaf.

    Args:
      row_leaf: [n] i32 leaf assignment.
      residual: [n] float (label - score).
      row_weight: [n] float; rows with weight 0 (out-of-bag) are ignored.
      num_leaves: static leaf count L.
      alpha: percentile in (0, 1); 0.5 = median.
      fallback: [L] values used for empty leaves.

    Returns [L] refined leaf outputs.
    """
    n = row_leaf.shape[0]
    active = row_weight > 0
    # push inactive rows to a dummy segment L
    seg = jnp.where(active, row_leaf, num_leaves)
    order = jnp.lexsort((residual, seg))
    seg_s = seg[order]
    res_s = residual[order]
    w_s = jnp.where(active, row_weight, 0.0)[order]

    totals = jax.ops.segment_sum(w_s, seg_s, num_segments=num_leaves + 1)
    cumw = jnp.cumsum(w_s)
    seg_offsets = jnp.concatenate(
        [jnp.zeros((1,), cumw.dtype), jnp.cumsum(totals)])[:-1]
    cum_in_seg = cumw - seg_offsets[seg_s]

    target = alpha * totals[seg_s]
    hit = cum_in_seg >= target - 1e-12
    # first index in each segment where the cumulative weight crosses target
    cand = jnp.where(hit, jnp.arange(n), n)
    first_idx = jax.ops.segment_min(cand, seg_s,
                                    num_segments=num_leaves + 1)[:num_leaves]
    valid = (first_idx < n) & (totals[:num_leaves] > 0)
    vals = res_s[jnp.minimum(first_idx, n - 1)]
    return jnp.where(valid, vals, fallback)
