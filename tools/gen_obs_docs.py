#!/usr/bin/env python3
"""Regenerate docs/OBSERVABILITY.md tables from obs/schemas.py.

The registry module (lightgbm_tpu/obs/schemas.py) is the single
source of truth for the cross-process plane: JSONL event schemas,
metric families, LIGHTGBM_TPU_* env vars. This tool renders them as
markdown tables and splices each between its marker pair

    <!-- BEGIN GENERATED: <block> (tools/gen_obs_docs.py) -->
    ...
    <!-- END GENERATED: <block> -->

so the prose around the tables stays hand-written while the
name/kind/label/default columns can never drift from the code.

    python tools/gen_obs_docs.py --write   # regenerate in place
    python tools/gen_obs_docs.py --check   # exit 1 on drift (lint.sh)

Jax-free: the registry is loaded by file path, never through the
package __init__.
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMAS = os.path.join(REPO, "lightgbm_tpu", "obs", "schemas.py")
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

_BEGIN = "<!-- BEGIN GENERATED: {name} (tools/gen_obs_docs.py) -->"
_END = "<!-- END GENERATED: {name} -->"


def load_schemas():
    spec = importlib.util.spec_from_file_location(
        "lightgbm_tpu_obs_schemas_standalone", SCHEMAS)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cell(s: str) -> str:
    return s.replace("|", "\\|")       # never break the table grammar


def _code(s: str) -> str:
    return f"`{_cell(s)}`"


def _keys(keys) -> str:
    return " ".join(_code(k) for k in keys) or "—"


def render_env(schemas) -> str:
    rows = ["| Variable | Default | Effect |", "| --- | --- | --- |"]
    for name in sorted(schemas.ENV_VARS):
        spec = schemas.ENV_VARS[name]
        default = spec.get("default")
        shown = "*(unset)*" if default is None else _code(repr(default))
        rows.append(f"| {_code(name)} | {shown} | {_cell(spec['doc'])} |")
    return "\n".join(rows)


def render_events(schemas) -> str:
    rows = ["| Event | Required keys | Optional keys | Meaning |",
            "| --- | --- | --- | --- |"]
    for name in sorted(schemas.EVENTS):
        spec = schemas.EVENTS[name]
        rows.append(
            f"| {_code(name)} | {_keys(spec.get('required', ()))} "
            f"| {_keys(spec.get('optional', ()))} | {_cell(spec['doc'])} |")
    return "\n".join(rows)


def render_metrics(schemas) -> str:
    rows = ["| Family | Kind | Labels | Meaning |",
            "| --- | --- | --- | --- |"]
    for name in sorted(schemas.METRICS):
        spec = schemas.METRICS[name]
        labels = ", ".join(
            _code(lb) for lb in spec.get("labels", ())) or "—"
        rows.append(f"| {_code(name)} | {spec['kind']} | {labels} "
                    f"| {_cell(spec['doc'])} |")
    return "\n".join(rows)


def render_export(schemas) -> str:
    rows = ["| Sample family | Kind | Exported by |",
            "| --- | --- | --- |"]
    for name in sorted(schemas.EXPORT_FAMILIES):
        spec = schemas.EXPORT_FAMILIES[name]
        rows.append(f"| {_code(name)} | {spec['kind']} "
                    f"| {_cell(spec['doc'])} |")
    return "\n".join(rows)


BLOCKS = {
    "env-vars": render_env,
    "events": render_events,
    "metrics": render_metrics,
    "export-families": render_export,
}


def splice(text: str, schemas) -> str:
    for name, render in BLOCKS.items():
        begin, end = _BEGIN.format(name=name), _END.format(name=name)
        pattern = re.compile(re.escape(begin) + r".*?" + re.escape(end),
                             re.S)
        if not pattern.search(text):
            raise SystemExit(
                f"gen_obs_docs: marker pair for {name!r} missing from "
                f"{os.path.relpath(DOC, REPO)}")
        block = f"{begin}\n{render(schemas)}\n{end}"
        text = pattern.sub(lambda _m: block, text, count=1)
    return text


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--write", action="store_true",
                      help="regenerate the doc tables in place")
    mode.add_argument("--check", action="store_true",
                      help="exit 1 when the doc drifted from the "
                           "registry (CI/lint.sh mode)")
    args = ap.parse_args(argv)

    schemas = load_schemas()
    with open(DOC, encoding="utf-8") as fh:
        current = fh.read()
    regenerated = splice(current, schemas)
    if args.check:
        if regenerated != current:
            print("gen_obs_docs: docs/OBSERVABILITY.md tables drifted "
                  "from lightgbm_tpu/obs/schemas.py — run "
                  "`python tools/gen_obs_docs.py --write`",
                  file=sys.stderr)
            return 1
        print("gen_obs_docs: docs/OBSERVABILITY.md is in sync")
        return 0
    if regenerated != current:
        with open(DOC, "w", encoding="utf-8") as fh:
            fh.write(regenerated)
        print("gen_obs_docs: rewrote generated tables in "
              "docs/OBSERVABILITY.md")
    else:
        print("gen_obs_docs: docs/OBSERVABILITY.md already in sync")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
