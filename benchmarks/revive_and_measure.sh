#!/bin/bash
# Tunnel revival watcher (round 6). Probes the axon TPU tunnel every
# PROBE_INTERVAL seconds; as soon as backend init succeeds, runs the
# measurement battery in priority order and exits:
#   1. benchmarks/decompose_iter.py  -> benchmarks/DECOMP_r06.txt
#      (per-phase attribution of the 893-vs-392 ms gap AND the full
#       train_one_iter number, VERDICT r4 #1/#2)
#   2. bench.py (Higgs 10.5M)        -> benchmarks/BENCH_LOCAL_r06.json
#   3. bench.py allstate preset 2M   -> benchmarks/BENCH_ALLSTATE_r06.json
#   4. benchmarks/fused_iter_bench.py -> benchmarks/FUSED_r06.txt
#      (THREE pending flip gates in one run: the fused+pallas arm's
#       verdict decides hist_method auto on TPU (docs/PALLAS.md), the
#       fused+scan arm's verdict decides fused_scan_iters auto
#       (docs/FUSED.md — its dispatch-gap decomposition must also show
#       inter-iteration host driver time ~ 0 inside a window), and the
#       eager-vs-fused speedup refreshes the r05 baseline)
#   5. benchmarks/quant_bench.py --comms -> benchmarks/COMMS_r06.txt
#      (f32 vs int16 vs int8 histogram allreduce at the Allstate-wide
#       shape on 8 devices; its verdict gates hist_comm auto -> int8,
#       docs/COLLECTIVES.md)
#   6. benchmarks/serve_bench.py     -> benchmarks/SERVE_r06.json
#      (ROADMAP 3d: on-chip serving rows/s + p99 through the real
#       CompiledForest + MicroBatcher stack, with the span-derived
#       queue/batch/dispatch stage decomposition in the same line)
# Each step is individually time-bounded so a mid-battery tunnel death
# still leaves earlier results on disk.
# Step 0 (before any tunnel probing): lint --ir --strict on CPU. The
# battery burns hours of scarce TPU time — don't spend them measuring
# a tree whose lowered programs already violate a committed contract
# (dtype widening, collective-budget regression, dead donation,
# undeclared recompile surface).
cd "$(dirname "$0")/.." || exit 1
PROBE_INTERVAL=${PROBE_INTERVAL:-120}
MAX_WAIT=${MAX_WAIT:-39600}   # give up after 11 h
start=$(date +%s)
log() { echo "[revive $(date +%H:%M:%S)] $*"; }

log "step 0: lint --ir --strict (CPU, IR contracts gate the battery)"
if ! JAX_PLATFORMS=cpu timeout 300 \
        python -m lightgbm_tpu lint --ir --strict; then
    log "lint --ir FAILED - fix the IR contracts before burning TPU time"
    exit 3
fi

while :; do
    if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        log "tunnel ALIVE - starting battery"
        break
    fi
    now=$(date +%s)
    if (( now - start > MAX_WAIT )); then
        log "gave up after ${MAX_WAIT}s"
        exit 2
    fi
    log "tunnel dead, retry in ${PROBE_INTERVAL}s"
    sleep "$PROBE_INTERVAL"
done

log "step 1/6: decompose_iter"
timeout 2400 python benchmarks/decompose_iter.py \
    > benchmarks/DECOMP_r06.txt 2>&1
log "decompose rc=$? (results in benchmarks/DECOMP_r06.txt)"

# bench.py ALWAYS exits 0 (its supervisor owns the one-JSON-line
# contract), so success is judged on the JSON itself: a failure
# record carries an "error" field.
bench_status() {  # $1 = json file
    if grep -q '"error"' "$1" 2>/dev/null; then echo FAILED;
    elif grep -q '"value"' "$1" 2>/dev/null; then echo MEASURED;
    else echo NO-OUTPUT; fi
}

log "step 2/6: full Higgs bench"
BENCH_DEADLINE=1800 timeout 2000 python bench.py \
    > benchmarks/BENCH_LOCAL_r06.json 2>benchmarks/BENCH_LOCAL_r06.err
log "higgs bench $(bench_status benchmarks/BENCH_LOCAL_r06.json): $(cat benchmarks/BENCH_LOCAL_r06.json)"

log "step 3/6: allstate preset"
BENCH_PRESET=allstate BENCH_DEADLINE=3000 timeout 3200 python bench.py \
    > benchmarks/BENCH_ALLSTATE_r06.json 2>benchmarks/BENCH_ALLSTATE_r06.err
log "allstate bench $(bench_status benchmarks/BENCH_ALLSTATE_r06.json): $(cat benchmarks/BENCH_ALLSTATE_r06.json)"

log "step 4/6: fused_iter_bench (pallas + scan flip gates)"
timeout 3000 python benchmarks/fused_iter_bench.py \
    > benchmarks/FUSED_r06.txt 2>&1
log "fused_iter rc=$? pallas verdict: $(grep -a 'pallas vs mxu' benchmarks/FUSED_r06.txt || echo none)"
log "fused_iter scan verdict: $(grep -a 'scan vs fused' benchmarks/FUSED_r06.txt || echo none)"

log "step 5/6: quant_bench --comms (hist_comm flip gate)"
timeout 1200 python benchmarks/quant_bench.py --comms \
    > benchmarks/COMMS_r06.txt 2>&1
log "comms rc=$? verdict: $(grep -a 'vs f32 allreduce' benchmarks/COMMS_r06.txt || echo none)"

log "step 6/6: serve_bench (on-chip rows/s + p99, ROADMAP 3d)"
timeout 1200 python benchmarks/serve_bench.py \
    > benchmarks/SERVE_r06.json 2>benchmarks/SERVE_r06.err
log "serve bench $(bench_status benchmarks/SERVE_r06.json): $(cat benchmarks/SERVE_r06.json)"
log "battery done"
