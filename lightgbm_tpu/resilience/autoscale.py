"""Fleet scaling + rollback policy: decisions from the scrape signal.

The elastic fleet supervisor (resilience/elastic.py) runs two threads:
a SCRAPE thread that polls every replica's ``{"cmd": "metrics"}`` verb
into ``{"event": "fleet"}`` records, and the MAIN supervision loop
that launches/retires/revives processes. This module is the seam
between them — the scrape thread feeds observations in
(:meth:`AutoscalePolicy.observe`, :meth:`RollbackGuard.observe`), the
main loop consumes decisions out (:meth:`~AutoscalePolicy.decide`,
:meth:`~RollbackGuard.decide`), and every byte of shared state sits
under one lock per policy object (the exact cross-thread
read-modify-write shape tpulint TPL008 exists for).

**Autoscaling** (docs/RESILIENCE.md "Autoscaling policy"): scale UP
when the fleet-total QPS exceeds ``n x up_qps`` for the *current*
replica count, when the worst replica p99 exceeds ``up_p99_ms``, or
when any replica shed load since the last scrape; scale DOWN only
when the total QPS would still clear ``down_qps`` per replica with
one replica FEWER and nothing else is degraded. Hysteresis comes from
three knobs: ``down_qps`` strictly below ``up_qps`` (enforced by
Config), a per-direction cooldown after any scaling action, and
decisions consuming at most one scrape observation each — a single
spike cannot double-scale between scrapes, and a fleet at the up
threshold does not flap back down.

**Rollback** (docs/RESILIENCE.md "Rollback state machine"): the guard
watches the newest publication in the store and drives it through
``watching -> adopted | rolled-back``. A publication is ADOPTED as
last-known-good once some replica has served its sha for
``adopt_sec`` without a health eviction; it is ROLLED BACK when (a)
no replica serves it after ``refuse_sec`` AND the fleet's cumulative
``swap_failures`` grew since it appeared (every replica's canary gate
refused it — the ``publish_poison`` shape), or (b) a replica that
swapped onto it was evicted by post-swap health checks. The main loop
executes the decision via
:func:`~.publisher.rollback_publication`; rolled-back shas are
remembered so a rollback can never loop.

This module never imports jax — it runs inside the jax-free
supervisor process.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["AutoscalePolicy", "RollbackGuard"]


def _alive_rows(rows: List[dict]) -> List[dict]:
    return [r for r in rows if r.get("alive")]


class AutoscalePolicy:
    """Hysteresis scaling decisions from ``{"event": "fleet"}`` rows.

    ``observe`` runs on the supervisor's scrape thread, ``decide`` and
    ``metrics_families`` on other threads — all state is guarded by
    ``self._lock``."""

    def __init__(self, min_replicas: int, max_replicas: int, *,
                 up_qps: float = 0.0, down_qps: float = 0.0,
                 up_p99_ms: float = 0.0,
                 up_cooldown_sec: float = 5.0,
                 down_cooldown_sec: float = 15.0,
                 _now=time.monotonic):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.up_qps = float(up_qps)
        self.down_qps = float(down_qps)
        self.up_p99_ms = float(up_p99_ms)
        self.up_cooldown_sec = float(up_cooldown_sec)
        self.down_cooldown_sec = float(down_cooldown_sec)
        self._now = _now
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._seq = 0            # observations ingested (scrape thread)
        self._decided_seq = 0    # observations consumed by decide()
        self._qps = 0.0
        self._p99 = 0.0
        self._shed_delta = 0.0
        self._shed_totals: Dict[Any, float] = {}
        self._last_scale_t: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0

    # -- scrape thread -------------------------------------------------
    def observe(self, rows: List[dict]) -> None:
        """Ingest one fleet scrape (the ``replicas`` rows of a
        ``{"event": "fleet"}`` record)."""
        alive = _alive_rows(rows)
        qps = sum(float(r.get("qps") or 0.0) for r in alive)
        p99 = max((float(r.get("p99_ms") or 0.0) for r in alive),
                  default=0.0)
        with self._lock:
            shed_delta = 0.0
            for r in rows:
                rank, tot = r.get("rank"), r.get("shed_total")
                if rank is None or tot is None:
                    continue
                prev = self._shed_totals.get(rank)
                # a restarted replica resets its counter — only count
                # forward motion
                if prev is not None and tot > prev:
                    shed_delta += tot - prev
                self._shed_totals[rank] = tot
            self._qps, self._p99 = qps, p99
            self._shed_delta = shed_delta
            self._seq += 1

    # -- supervision loop ----------------------------------------------
    def decide(self, n_active: int) -> Optional[Tuple[str, str]]:
        """One scaling decision — ``("up"|"down", reason)`` or None.

        Consumes at most one observation per call: with no scrape
        since the last decision there is nothing new to act on, so a
        tight supervision loop cannot re-fire on stale numbers."""
        now = self._now()
        with self._lock:
            if self._seq == self._decided_seq:
                return None
            self._decided_seq = self._seq
            qps, p99 = self._qps, self._p99
            shed = self._shed_delta
            since = (None if self._last_scale_t is None
                     else now - self._last_scale_t)
            if n_active < self.max_replicas:
                reasons = []
                if self.up_qps > 0 and qps > n_active * self.up_qps:
                    reasons.append(
                        f"qps {qps:.1f} > {n_active}x{self.up_qps:g}")
                if self.up_p99_ms > 0 and p99 > self.up_p99_ms:
                    reasons.append(
                        f"p99 {p99:.1f}ms > {self.up_p99_ms:g}ms")
                if shed > 0:
                    reasons.append(f"shed +{shed:g}")
                if reasons and (since is None
                                or since >= self.up_cooldown_sec):
                    self._last_scale_t = now
                    self.scale_ups += 1
                    return ("up", "; ".join(reasons))
            if n_active > self.min_replicas and self.down_qps > 0:
                calm = (shed == 0
                        and (self.up_p99_ms <= 0
                             or p99 <= self.up_p99_ms)
                        and qps < (n_active - 1) * self.down_qps)
                if calm and (since is None
                             or since >= self.down_cooldown_sec):
                    self._last_scale_t = now
                    self.scale_downs += 1
                    return ("down",
                            f"qps {qps:.1f} < "
                            f"{n_active - 1}x{self.down_qps:g}")
            return None

    def metrics_families(self) -> Dict[str, dict]:
        """Live policy state for the supervisor's /metrics endpoint
        (read from the HTTP handler thread)."""
        from ..obs.export import counter_family, gauge_family
        with self._lock:
            return {
                "fleet_autoscale_up": counter_family(self.scale_ups),
                "fleet_autoscale_down": counter_family(self.scale_downs),
                "fleet_autoscale_qps": gauge_family(self._qps),
                "fleet_autoscale_p99_ms": gauge_family(self._p99),
            }


class RollbackGuard:
    """Last-known-good tracking + rollback decisions for the newest
    publication (state machine above; docs/RESILIENCE.md).

    ``observe``/``note_eviction`` run on supervisor threads other than
    the one calling ``note_publication``/``decide`` — all state is
    guarded by ``self._lock``."""

    def __init__(self, *, refuse_sec: float = 5.0,
                 adopt_sec: float = 2.0, _now=time.monotonic):
        self.refuse_sec = float(refuse_sec)
        self.adopt_sec = float(adopt_sec)
        self._now = _now
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._served: Dict[Any, str] = {}       # rank -> serving sha
        self._fail_totals: Dict[Any, float] = {}
        self._fail_cum = 0.0
        self._watched: Optional[Dict[str, Any]] = None
        self._good: Optional[Tuple[str, str]] = None  # (name, sha)
        self._good_shas: set = set()
        self._bad_shas: set = set()
        self.rollbacks = 0

    # -- scrape thread -------------------------------------------------
    def observe(self, rows: List[dict]) -> None:
        """Ingest per-replica serving shas + swap-failure counters
        from one fleet scrape."""
        with self._lock:
            for r in rows:
                rank = r.get("rank")
                if rank is None:
                    continue
                sha = r.get("sha256")
                if sha:
                    self._served[rank] = sha
                tot = r.get("swap_failures_total")
                if tot is not None:
                    prev = self._fail_totals.get(rank)
                    if prev is not None and tot > prev:
                        self._fail_cum += tot - prev
                    elif prev is None and tot > 0:
                        self._fail_cum += tot
                    self._fail_totals[rank] = tot

    # -- supervision loop ----------------------------------------------
    def note_publication(self, name: str, sha: str) -> bool:
        """Start watching a newly observed publication; True when the
        watch actually changed (known-good / known-bad / already
        watched shas are ignored)."""
        if not sha:
            return False
        with self._lock:
            if sha in self._good_shas or sha in self._bad_shas:
                return False
            if self._watched is not None \
                    and self._watched["sha"] == sha:
                return False
            self._watched = {"name": name, "sha": sha,
                             "t": self._now(),
                             "first_served_t": None,
                             "fail_base": self._fail_cum,
                             "evicted": False}
            return True

    def note_eviction(self, rank) -> None:
        """A replica failed post-swap health checks and is being
        evicted; if it was serving the watched publication, that
        publication is condemned."""
        with self._lock:
            w = self._watched
            if w is not None \
                    and self._served.get(rank) == w["sha"]:
                w["evicted"] = True

    def decide(self) -> Optional[Dict[str, Any]]:
        """Advance the watched publication through the state machine;
        a rollback order ``{"bad_name", "bad_sha", "good_name",
        "good_sha"}`` when it is condemned, else None."""
        now = self._now()
        with self._lock:
            w = self._watched
            if w is None:
                return None
            sha = w["sha"]
            serving = any(s == sha for s in self._served.values())
            if w["evicted"]:
                return self._condemn(w)
            if serving:
                if w["first_served_t"] is None:
                    w["first_served_t"] = now
                elif now - w["first_served_t"] >= self.adopt_sec:
                    # adopted: the fleet runs it — last-known-good
                    self._good = (w["name"], sha)
                    self._good_shas.add(sha)
                    self._watched = None
                return None
            if now - w["t"] >= self.refuse_sec \
                    and self._fail_cum > w["fail_base"]:
                # nobody swapped onto it and swap failures mounted:
                # the fleet's canary gates refused it
                return self._condemn(w)
            return None

    def _condemn(self, w: Dict[str, Any]) -> Dict[str, Any]:
        # caller holds self._lock
        self._bad_shas.add(w["sha"])
        self._watched = None
        self.rollbacks += 1
        good_name, good_sha = self._good or (None, None)
        return {"bad_name": w["name"], "bad_sha": w["sha"],
                "good_name": good_name, "good_sha": good_sha}

    @property
    def last_known_good(self) -> Optional[Tuple[str, str]]:
        with self._lock:
            return self._good
