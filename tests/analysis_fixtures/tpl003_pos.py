# tpulint fixture: TPL003 positive — recompile hazards.
import functools

import jax
import jax.numpy as jnp


def _impl(x, n):
    return x * n


stepper = jax.jit(_impl, static_argnums=(1,))
named = jax.jit(_impl, static_argnames=("n",))


def storm(xs, counts):
    out = []
    for c in counts:
        # EXPECT: TPL003
        f = jax.jit(lambda v: v * 2)   # fresh wrapper per iteration
        # EXPECT: TPL003
        out.append(stepper(xs, int(c)))          # data -> static pos
        # EXPECT: TPL003
        out.append(named(xs, n=float(c.max())))  # data -> static name
    return out


def storm_partial(xs, c):
    # EXPECT: TPL003
    return stepper(xs, c.item())      # .item() into a static position
