"""Flow-sensitive rules TPL007-TPL010 (CFG + dataflow based).

These rules sit on top of :mod:`~lightgbm_tpu.analysis.cfg` (per-
function control-flow graphs with guard-pin and lock dataflow) and
:mod:`~lightgbm_tpu.analysis.dataflow` (rank taint, thread-side
closure, float64 producers), where TPL001-TPL006 are per-statement —
except TPL010, which needs only the call graph (a device collective
reached from a ``lax.cond``/``switch`` branch is flagged wherever it
sits; the replicated-predicate argument lives in the pragma, not in a
dataflow proof).

Imported by :mod:`~lightgbm_tpu.analysis.rules` (which owns
``ALL_RULES``); import that module, not this one, to get the full rule
set.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .astscan import ModuleScan, dotted_of
from .callgraph import CallGraph, CallRecord, Key
from .cfg import FunctionCFG
from .dataflow import (MUTATOR_METHODS, SYNC_PRIMITIVE_CTORS, RankTaint,
                       is_float64_expr, rank_tainted_returns,
                       thread_side_functions)
from .rules import Finding, LintContext, Rule

__all__ = ["CollectiveOrder", "ThreadSharedState", "DtypePromotionLeak",
           "CollectiveUnderTracedCond", "FLOW_RULES"]


def _src(node: ast.AST, limit: int = 58) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse failure
        text = node.__class__.__name__
    text = " ".join(text.split())
    return text if len(text) <= limit else text[: limit - 3] + "..."


class _CfgCache:
    """FunctionCFGs are built lazily, once per function, per rule run."""

    def __init__(self):
        self._cfgs: Dict[int, FunctionCFG] = {}

    def get(self, fn_node: ast.AST) -> FunctionCFG:
        cfg = self._cfgs.get(id(fn_node))
        if cfg is None:
            cfg = FunctionCFG(fn_node)
            self._cfgs[id(fn_node)] = cfg
        return cfg


def _enclosing_chain(ctx: LintContext, key: Key):
    """FuncInfos from outermost enclosing function to ``key``'s own."""
    chain = []
    info = ctx.graph.funcs.get(key)
    while info is not None:
        chain.append(info)
        info = ctx.graph.funcs.get((info.relpath, info.parent_qual)) \
            if info.parent_qual else None
    chain.reverse()
    return chain


# ---------------------------------------------------------------------
class CollectiveOrder(Rule):
    """TPL007: every host-level collective must be reached in
    rank-invariant order. Three rank-divergence shapes are flagged:

    - a collective whose guard pins (CFG meet over all paths) include a
      rank-derived condition — a ``process_index()`` /
      ``LIGHTGBM_TPU_RANK`` branch, *including* the early-return shape
      where one arm diverts (``if rank: return`` then a collective);
    - a collective inside an ``except`` handler or ``finally`` block —
      only the ranks that hit the exception run it;
    - a collective in a loop whose iterable is rank-derived — a
      rank-dependent number of joins.

    Rank-dependent *arguments* are fine (``sync_bin_mappers`` builds
    rank 0's payload under a rank branch, then every rank joins the
    broadcast) — the CFG meet keeps fall-through branches pin-free.
    """

    id = "TPL007"
    title = "host collective reached in rank-divergent order"

    #: device-collective wrappers from parallel/comms.py: wrapping
    #: ``lax.psum``/``all_to_all`` in a helper must not blind the lint
    #: — a quantized-comms reduction reached in rank-divergent host
    #: order is the same world-desync hazard one level down (the
    #: traced program itself then differs per rank). Kept as its own
    #: set so the recognizer-strip mutation test can prove the entry
    #: is load-bearing.
    _COMMS_WRAPPERS = frozenset({"hist_allreduce"})

    #: host-sync wrappers from parallel/placement.py (docs/SHARDING.md):
    #: the per-rank upload barrier and the sharded-checkpoint gather
    #: are world-joining host collectives one level up — rank-guarding
    #: a call site skips a world join exactly like skipping the
    #: underlying allgather (``fetch_addressable`` is deliberately NOT
    #: here: it never joins a collective by construction). Kept as its
    #: own set so the placement mutation test can prove the entries
    #: are load-bearing.
    _PLACEMENT_WRAPPERS = frozenset({"upload_barrier", "fetch_global"})

    #: direct host-collective entry points (basenames — matches both
    #: resolved package functions and unresolved externals, so fixtures
    #: and the real tree hit the same detector)
    _COLLECTIVES = {"host_allgather", "host_broadcast_bytes", "guarded",
                    "verify_step_consistency", "sync_bin_mappers",
                    "aggregate_phase_snapshot", "process_allgather",
                    "broadcast_one_to_all", "sync_global_devices",
                    "wait_at_barrier",
                    "assert_equal_per_process"} \
        | _COMMS_WRAPPERS | _PLACEMENT_WRAPPERS

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        reaches = self._reaches_collective(ctx.graph)
        # gather the scoped collective call sites FIRST: a scope with
        # none (the common --changed slice) never pays for the
        # package-wide rank-taint fixed point
        sites = []
        for scope, facts in ctx.graph.facts.items():
            if scope is None or ctx.is_traced(scope):
                continue
            for rec in facts.records:
                if rec.relpath not in ctx.scope:
                    continue
                name, direct = self._collective_name(rec, reaches)
                if name is not None:
                    sites.append((scope, rec, name, direct))
        if not sites:
            return
        tainted_fns = rank_tainted_returns(ctx.graph)
        cfgs = _CfgCache()
        taints: Dict[Key, RankTaint] = {}
        for scope, rec, name, direct in sites:
            info = ctx.graph.funcs.get(scope)
            if info is None:
                continue
            cfg = cfgs.get(info.node)
            unit = cfg.info(rec.node)
            if unit is None:
                continue
            what = name if direct else f"{name} (reaches a host " \
                "collective through the call graph)"
            if unit.in_except or unit.in_finally:
                where = "an `except` handler" if unit.in_except \
                    else "a `finally` block"
                yield self._finding(
                    ctx, rec.relpath, rec.node,
                    f"collective:{name}",
                    f"host collective {what} runs inside {where}: "
                    "only the ranks that hit the exception path "
                    "join it, so the world's collective sequences "
                    "diverge — the survivors hang in mismatched "
                    "collectives until the watchdog deadline. Keep "
                    "collectives out of error-recovery paths; fail "
                    "fast and let the supervisor restart the world "
                    "(resilience/elastic.py).",
                    func=scope[1])
                continue
            taint = self._taint_for(ctx, scope, tainted_fns, taints)
            hit = next(((t, pol) for (t, pol) in unit.pins
                        if taint.is_tainted(t)), None)
            if hit is None:
                continue
            test, pol = hit
            shape = ("a rank-dependent number of times (loop over "
                     f"`{_src(test)}`)"
                     if self._is_loop_iter(cfg, test)
                     else f"only when `{_src(test)}` is {pol}")
            yield self._finding(
                ctx, rec.relpath, rec.node, f"collective:{name}",
                f"host collective {what} is reached {shape} — a "
                "condition derived from the process rank "
                "(process_index() / a *RANK* env var): ranks take "
                "different paths, so part of the world never joins "
                "(or joins out of order) and the rest deadlocks "
                "until the watchdog deadline. Make every rank join "
                "the collective and branch on the rank only for "
                "its *arguments* or for local side effects "
                "(parallel/spmd.sync_bin_mappers is the pattern).",
                func=scope[1])

    @staticmethod
    def _is_loop_iter(cfg: FunctionCFG, node: ast.AST) -> bool:
        unit = cfg.info(node)
        return unit is not None and isinstance(unit.stmt,
                                               (ast.For, ast.AsyncFor))

    def _collective_name(self, rec: CallRecord,
                         reaches: Set[Key]) -> Tuple[Optional[str], bool]:
        if rec.kind == "ext" and rec.dotted:
            base = rec.dotted.rsplit(".", 1)[-1]
            if base in self._COLLECTIVES \
                    or "multihost_utils" in rec.dotted:
                return base, True
        elif rec.kind == "method" and rec.attr in self._COLLECTIVES:
            return rec.attr, True
        elif rec.kind == "known" and rec.target is not None:
            base = rec.target[1].rsplit(".", 1)[-1]
            if base in self._COLLECTIVES:
                return base, True
            if rec.target in reaches:
                return base, False
        return None, False

    @staticmethod
    def _reaches_collective(graph: CallGraph) -> Set[Key]:
        """Functions that transitively call a host collective —
        rank-gating a *call* to one of these is the same hazard one
        level up."""
        direct: Set[Key] = set()
        for scope, facts in graph.facts.items():
            if scope is None:
                continue
            for rec in facts.records:
                base = None
                if rec.kind == "ext" and rec.dotted:
                    base = rec.dotted.rsplit(".", 1)[-1]
                    if "multihost_utils" in rec.dotted:
                        direct.add(scope)
                        continue
                elif rec.kind == "method":
                    base = rec.attr
                if base in CollectiveOrder._COLLECTIVES:
                    direct.add(scope)
        callers: Dict[Key, Set[Optional[Key]]] = {}
        for scope, facts in graph.facts.items():
            for rec in facts.records:
                if rec.kind == "known" and rec.target is not None:
                    callers.setdefault(rec.target, set()).add(scope)
        out = set(direct)
        frontier = list(direct)
        while frontier:
            k = frontier.pop()
            for caller in callers.get(k, ()):
                if caller is not None and caller not in out:
                    out.add(caller)
                    frontier.append(caller)
        return out

    @staticmethod
    def _taint_for(ctx: LintContext, key: Key, tainted_fns: Set[str],
                   cache: Dict[Key, RankTaint]) -> RankTaint:
        got = cache.get(key)
        if got is not None:
            return got
        names: Set[str] = set()
        taint: Optional[RankTaint] = None
        for info in _enclosing_chain(ctx, key):
            taint = RankTaint(info.node, seed_names=names,
                              tainted_fns=tainted_fns)
            names = set(taint.names)
        assert taint is not None
        cache[key] = taint
        return taint


# ---------------------------------------------------------------------
class ThreadSharedState(Rule):
    """TPL008: state written from thread-started code (a
    ``threading.Thread``/``Timer`` target, or the collective body a
    ``watchdog.guarded`` call runs on its worker thread) and shared
    with other code must be guarded by a *common* lock — proved on the
    lock-acquisition CFG, not syntactically — or carry a
    ``# tpulint: threadsafe <why>`` pragma explaining the
    synchronization that makes it safe (e.g. an Event handshake).

    Shared state = module globals (including imported ones), ``self``
    attributes, and closure variables of an enclosing function;
    mutation = assignment, subscript/attribute store, or a mutating
    method call (``append``/``update``/...). A module global mutated
    from thread-side code is flagged even without a main-path reader:
    every spawn is a *fresh* thread, so two successive collectives
    already race on it. Scope includes ``serve/``: the inference
    daemon's batcher worker, hot-swap watcher and stats loop all
    mutate state that submit()/stats() callers read concurrently —
    and ``pipeline.py``, whose load-generator thread records outcome
    stats the supervisor loop snapshots."""

    id = "TPL008"
    title = "thread-shared state mutated without a common lock"

    _SCOPE_PREFIXES = ("obs/", "resilience/", "parallel/", "serve/",
                       "pipeline")

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        thread_side = thread_side_functions(ctx.graph)
        if not thread_side:
            return
        cfgs = _CfgCache()
        for key in sorted(thread_side):
            relpath, qual = key
            if relpath not in ctx.scope \
                    or not relpath.startswith(self._SCOPE_PREFIXES):
                continue
            info = ctx.graph.funcs.get(key)
            scan = ctx.scans.get(relpath)
            if info is None or scan is None:
                continue
            how, _ = thread_side[key]
            yield from self._check_thread_fn(ctx, scan, info, how,
                                             thread_side, cfgs)

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _own_nodes(fn_node: ast.AST):
        """Nodes of this function, not descending into nested defs."""
        stack = list(getattr(fn_node, "body", []))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    @classmethod
    def _local_names(cls, fn_node: ast.AST) -> Set[str]:
        a = fn_node.args
        out = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            out.add(a.vararg.arg)
        if a.kwarg:
            out.add(a.kwarg.arg)
        globals_decl: Set[str] = set()
        for node in cls._own_nodes(fn_node):
            if isinstance(node, ast.Global):
                globals_decl.update(node.names)
            elif isinstance(node, ast.Nonlocal):
                continue  # nonlocal stores are shared, not local
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                out.add(node.id)
            elif isinstance(node, ast.excepthandler) and node.name:
                out.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    out.add((alias.asname
                             or alias.name.split(".", 1)[0]))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                out.add(node.name)
        return out - globals_decl

    @staticmethod
    def _root_name(node: ast.AST) -> Optional[str]:
        while isinstance(node, (ast.Subscript, ast.Attribute,
                                ast.Starred)):
            node = node.value
        if isinstance(node, ast.Name):
            return node.id
        return None

    @classmethod
    def _sync_primitives(cls, ctx: LintContext, scan: ModuleScan,
                         info) -> Set[str]:
        """Names bound to objects that synchronize internally (Event,
        Queue, deque, itertools.count, ...) in this function, its
        enclosing chain, or at module level."""
        out: Set[str] = set()

        def collect(node):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) \
                        and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name) \
                        and isinstance(sub.value, ast.Call):
                    d = dotted_of(sub.value.func) or ""
                    if d.rsplit(".", 1)[-1] in SYNC_PRIMITIVE_CTORS:
                        out.add(sub.targets[0].id)

        for fi in _enclosing_chain(ctx, info.key):
            collect(fi.node)
        collect(scan.tree)
        return out

    @staticmethod
    def _module_globals(scan: ModuleScan) -> Set[str]:
        out: Set[str] = set(scan.imports)
        for node in scan.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                out.add(node.target.id)
        return out

    def _threadsafe_ok(self, scan: ModuleScan, info,
                       lineno: int) -> bool:
        for ln in (lineno, lineno - 1, info.lineno, info.lineno - 1):
            if scan.threadsafe_lines.get(ln):
                return True
        return False

    # -- the check -----------------------------------------------------
    def _check_thread_fn(self, ctx, scan, info, how, thread_side,
                         cfgs: _CfgCache) -> Iterator[Finding]:
        locals_ = self._local_names(info.node)
        sync_names = self._sync_primitives(ctx, scan, info)
        mod_globals = self._module_globals(scan)
        enclosing = {fi.qual for fi in _enclosing_chain(ctx, info.key)}
        enclosing.discard(info.qual)
        cfg = cfgs.get(info.node)

        writes: List[Tuple[ast.AST, str, str]] = []  # (node, sym, kind)
        for node in self._own_nodes(info.node):
            for target, wnode in self._write_targets(node):
                sym, kind = self._classify(target, locals_, sync_names,
                                           mod_globals, scan, info)
                if sym is not None:
                    writes.append((wnode, sym, kind))

        seen: Set[Tuple[str, int]] = set()
        for wnode, sym, kind in writes:
            lineno = getattr(wnode, "lineno", info.lineno)
            if (sym, lineno) in seen:
                continue
            seen.add((sym, lineno))
            if self._threadsafe_ok(scan, info, lineno):
                continue
            wlocks = cfg.held_locks(wnode)
            accesses = self._main_side_accesses(
                ctx, scan, info, sym, kind, thread_side, cfgs)
            unsafe = [
                (ln, locks) for (ln, locks) in accesses
                if not (wlocks & locks)]
            if accesses and not unsafe:
                continue  # common lock proven on every main-side access
            if not accesses:
                if kind != "global" or wlocks:
                    continue
                detail = ("no lock is held at the write, and every "
                          f"{how} spawn is a FRESH thread — successive "
                          "collectives already race on it")
            else:
                ln = unsafe[0][0]
                detail = ("main-path code accesses it at line "
                          f"{ln} with no lock in common with this "
                          "write" + ("" if wlocks else
                                     " (the write holds no lock at "
                                     "all)"))
            yield self._finding(
                ctx, scan.relpath, wnode, f"shared:{sym}",
                f"`{sym}` is mutated from thread-side code "
                f"({info.qual} runs on a {how} thread) without a "
                f"common lock: {detail}. Guard both sides with one "
                "lock (copy-under-lock, dispatch outside — "
                "docs/STATIC_ANALYSIS.md), hand the data over through "
                "a queue/Event, or mark the write `# tpulint: "
                "threadsafe <why>` when an existing handshake already "
                "orders it.", func=info.qual)

    def _write_targets(self, node):
        """(target expr, finding anchor) pairs for every mutation in
        ``node``."""
        if isinstance(node, ast.Assign):
            for t in node.targets:
                yield t, node
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", True) is not None:
                yield node.target, node
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                yield t, node
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATOR_METHODS:
            yield node.func.value, node

    def _classify(self, target, locals_, sync_names, mod_globals,
                  scan, info):
        """-> (symbol, kind) with kind in global|closure|attr, or
        (None, "") when the write is purely local."""
        # plain local rebinding is local by Python scoping
        if isinstance(target, ast.Name):
            if target.id in mod_globals and target.id not in locals_:
                return target.id, "global"
            return None, ""
        root = self._root_name(target)
        if root is None:
            # self.attr / chained attribute write
            node = target
            while isinstance(node, (ast.Subscript, ast.Attribute)):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id in ("self", "cls"):
                    return f"self.{node.attr}", "attr"
                node = node.value
            return None, ""
        if root in ("self", "cls"):
            sub = target
            while isinstance(sub, ast.Subscript):
                sub = sub.value
            if isinstance(sub, ast.Attribute):
                return f"self.{sub.attr}", "attr"
            return None, ""
        if root in locals_ or root in sync_names:
            return None, ""
        if root in mod_globals:
            return root, "global"
        # not local, not a module global: bound in an enclosing
        # function -> closure variable
        return root, "closure"

    def _main_side_accesses(self, ctx, scan, info, sym, kind,
                            thread_side, cfgs: _CfgCache):
        """(lineno, held-locks) for every access to ``sym`` from
        non-thread-side code that can see it."""
        out: List[Tuple[int, frozenset]] = []

        def scan_fn(fi):
            if fi.key in thread_side or fi.key == info.key:
                return
            if fi.name in ("__init__", "__new__", "__post_init__"):
                # constructors run before any thread can see the
                # object — their unguarded initialization is not a race
                return
            cfg = cfgs.get(fi.node)
            for node in ThreadSharedState._own_nodes(fi.node):
                hit = False
                if kind == "attr":
                    hit = (isinstance(node, ast.Attribute)
                           and isinstance(node.value, ast.Name)
                           and node.value.id in ("self", "cls")
                           and f"self.{node.attr}" == sym)
                else:
                    hit = isinstance(node, ast.Name) and node.id == sym
                if hit:
                    out.append((node.lineno, cfg.held_locks(node)))

        if kind == "attr":
            for fi in scan.funcs.values():
                if fi.class_name == info.class_name:
                    scan_fn(fi)
        elif kind == "closure":
            for fi in _enclosing_chain(ctx, info.key):
                if fi.key != info.key:
                    scan_fn(fi)
        else:  # module global (possibly imported from another module)
            for fi in scan.funcs.values():
                scan_fn(fi)
            origin = scan.imports.get(sym)
            if origin and "." in origin:
                mod = origin.rsplit(".", 1)[0]
                rel = ctx.graph.module_of.get(mod)
                if rel and rel in ctx.scans:
                    for fi in ctx.scans[rel].funcs.values():
                        scan_fn(fi)
        return out


# ---------------------------------------------------------------------
class DtypePromotionLeak(Rule):
    """TPL009: a float64-producing numpy expression passed into a
    jit-reachable function. With jax's default x64-disabled config the
    array is silently downcast on *every* call (a host-side convert +
    copy per dispatch); with x64 enabled it drags the traced
    computation to float64, which TPUs emulate at a fraction of f32
    throughput. Either way the f64 precision never survives to the
    device — build the array as float32 (or convert once at setup)."""

    id = "TPL009"
    title = "float64 numpy value flowing into jit-reachable code"

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        assigns_cache: Dict[Optional[Key], Dict] = {}
        for scope, facts in ctx.graph.facts.items():
            for rec in facts.records:
                if rec.relpath not in ctx.scope:
                    continue
                callee = self._traced_callee(ctx, rec)
                if callee is None:
                    continue
                scan = ctx.scans[rec.relpath]
                assigns = assigns_cache.get(scope)
                if assigns is None:
                    assigns = self._f64_assigns(ctx, scope, scan)
                    assigns_cache[scope] = assigns
                for arg in list(rec.node.args) \
                        + [kw.value for kw in rec.node.keywords]:
                    if is_float64_expr(arg, scan.imports, assigns):
                        yield self._finding(
                            ctx, rec.relpath, arg, f"f64->{callee}",
                            "float64 numpy value flows into "
                            f"jit-reachable {callee}(): under the "
                            "default x64-disabled config jax silently "
                            "downcasts it on every call (a host-side "
                            "convert+copy per dispatch); with x64 "
                            "enabled the whole traced computation "
                            "promotes to float64, which TPUs emulate "
                            "at a fraction of f32 throughput. Build "
                            "it float32 (dtype=np.float32) or convert "
                            "once outside the per-call path.")
                        break

    def _traced_callee(self, ctx: LintContext,
                       rec: CallRecord) -> Optional[str]:
        if rec.kind == "wrapper":
            if rec.target is not None:
                return rec.target[1].rsplit(".", 1)[-1]
            d = dotted_of(rec.node.func)
            return (d or "jitted").rsplit(".", 1)[-1]
        if rec.kind == "known" and rec.target is not None:
            info = ctx.graph.funcs.get(rec.target)
            if info is None:
                return None
            if ctx.is_traced(rec.target) or info.decorator_wrap \
                    or info.wrappers:
                return rec.target[1].rsplit(".", 1)[-1]
        return None

    @staticmethod
    def _f64_assigns(ctx: LintContext, scope: Optional[Key],
                     scan: ModuleScan) -> Dict:
        """name -> [(lineno, was_f64)] history for the enclosing
        function (one level of local propagation)."""
        out: Dict[str, List[Tuple[int, bool]]] = {}
        node = None
        if scope is not None:
            info = ctx.graph.funcs.get(scope)
            node = info.node if info is not None else None
        if node is None:
            node = scan.tree
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                out.setdefault(sub.targets[0].id, []).append(
                    (sub.lineno,
                     is_float64_expr(sub.value, scan.imports)))
        for hist in out.values():
            hist.sort()
        return out


# ---------------------------------------------------------------------
class CollectiveUnderTracedCond(Rule):
    """TPL010: a DEVICE collective (``lax.psum`` family) inside a
    branch of a traced conditional (``lax.cond`` / ``lax.switch``).

    Under SPMD sharding, ``lax.cond`` is real control flow: only the
    taken branch's ops execute. A collective in one branch is
    deadlock-safe **iff the predicate is bit-identical on every
    device** — a divergent predicate leaves part of the mesh waiting
    in a collective the rest never joins, hanging all hosts (no error,
    no watchdog: device collectives sit below the host-level watchdog
    that TPL007 polices). The hazard is invisible at the call site
    because the predicate's replication is a *global* dataflow
    property, so this rule makes the invariant explicit: every such
    site must carry a ``# tpulint: replicated-cond <why>`` pragma (on
    the conditional's line or the line above) whose non-empty ``why``
    names the argument for the predicate's replication — e.g.
    ops/grow.py's histogram-pool reads, where ``leaf2slot`` derives
    only from the replicated tree/argmax sequence (the ADVICE r4
    ``_research_leafwise`` finding). A bare pragma does not suppress.

    Detection is lexical + one callgraph closure: a branch argument
    (lambda body, a referenced function/method — positional or
    ``true_fun=``/``false_fun=``/``branches=`` keyword, including
    ``functools.partial``-wrapped and from-import spellings) that
    dispatches a device collective directly, or calls a package
    function that transitively reaches one. Known out of scope: a
    ``switch`` branch LIST built in a variable before the call (needs
    dataflow), and collectives reached only through a function passed
    in as an *argument* (e.g. a pool-context closure) — keep such
    indirections out of cond branches or pragma the call site.
    """

    id = "TPL010"
    title = "device collective under a traced conditional"

    #: jax device-level collectives (basenames under jax./lax.)
    _DEVICE_COLLECTIVES = {"psum", "pmax", "pmin", "pmean", "all_gather",
                           "all_to_all", "ppermute", "pshuffle",
                           "psum_scatter", "pgather"}
    #: package wrappers that ARE device collectives (parallel/comms.py
    #: quantized histogram allreduce): recognized directly — spelled
    #: ``comms.hist_allreduce`` or bare — so wrapping ``lax.psum``
    #: does not blind this rule even when comms.py itself is outside
    #: the linted file set (fixtures, --changed slices). The
    #: callgraph closure still covers in-package spellings.
    _COMMS_WRAPPERS = frozenset({"hist_allreduce"})
    _COND_NAMES = {"cond", "switch"}

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        reaches = self._reaches_device_collective(ctx.graph)
        # package-wide basename map: branch helpers imported from
        # sibling modules (and method calls on package objects) must
        # resolve too, not just same-module defs
        global_base: Dict[str, List[Key]] = {}
        for key in ctx.graph.funcs:
            global_base.setdefault(key[1].rsplit(".", 1)[-1],
                                   []).append(key)
        for scan in ctx.scoped_scans():
            by_base = self._funcs_by_basename(ctx, scan.relpath)
            for node in ast.walk(scan.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_of(node.func)
                if not dotted:
                    continue
                parts = dotted.split(".")
                # bare `cond(`/`switch(` (from-import spelling) counts
                # too: over-approximate — a shadowing local only flags
                # when a branch actually reaches a collective
                if parts[-1] not in self._COND_NAMES or (
                        len(parts) > 1
                        and parts[0] not in ("jax", "lax")):
                    continue
                encl = ctx.scope_of_node(scan, node.lineno)
                hit = self._branch_collective(node, by_base,
                                              global_base, reaches,
                                              encl)
                if hit is None:
                    continue
                why = None
                for ln in (node.lineno, node.lineno - 1):
                    if ln in scan.replicated_cond_lines:
                        why = scan.replicated_cond_lines[ln]
                        break
                if why:  # non-empty justification accepts the site
                    continue
                name, via = hit
                extra = "" if via is None \
                    else f" (via {via}(), which reaches it through " \
                         "the call graph)"
                bare = "" if why is None else \
                    " The pragma on this site has no why — state the " \
                    "replication argument."
                yield self._finding(
                    ctx, scan.relpath, node,
                    f"cond-collective:{name}",
                    f"device collective lax.{name} runs inside a "
                    f"branch of {parts[-1]}(){extra}: under SPMD this "
                    "deadlocks every host unless the predicate is "
                    "bit-identical on all devices, and nothing at "
                    "this call site proves that. Hoist the "
                    "collective out of the conditional, or annotate "
                    "the line with `# tpulint: replicated-cond <why>` "
                    "naming why the predicate is replicated (derived "
                    "only from globally-reduced state)." + bare)

    # -- helpers -------------------------------------------------------
    def _branch_collective(self, call: ast.Call, by_base, global_base,
                           reaches,
                           encl: str) -> Optional[Tuple[str,
                                                        Optional[str]]]:
        """(collective, via_fn | None) when a branch arg reaches one.

        Branches arrive positionally (``cond(pred, t, f)``), as
        keywords (``true_fun=``/``false_fun=``/``branches=``), or as a
        branch list for ``switch`` — all three legal call forms are
        inspected; a branch may be a lambda, a bare name, or an
        attribute reference (``self._helper``)."""
        dotted = dotted_of(call.func) or ""
        is_cond = dotted.rsplit(".", 1)[-1] == "cond"
        branches: List[ast.AST] = []
        if is_cond:
            branches = list(call.args[1:3])
        elif len(call.args) >= 2:  # switch(index, branches, *operands)
            b = call.args[1]
            if isinstance(b, (ast.List, ast.Tuple)):
                branches = list(b.elts)
        for kw in call.keywords:
            if kw.arg in ("true_fun", "false_fun"):
                branches.append(kw.value)
            elif kw.arg == "branches" and isinstance(
                    kw.value, (ast.List, ast.Tuple)):
                branches.extend(kw.value.elts)
        for br in branches:
            if isinstance(br, ast.Call):
                # functools.partial(fn, ...)-wrapped branch: inspect
                # the wrapped function reference
                d = dotted_of(br.func) or ""
                if d.rsplit(".", 1)[-1] == "partial" and br.args:
                    br = br.args[0]
            if isinstance(br, ast.Lambda):
                hit = self._body_collective(br.body, by_base,
                                            global_base, reaches, encl)
                if hit is not None:
                    return hit
            else:
                name = br.id if isinstance(br, ast.Name) else (
                    br.attr if isinstance(br, ast.Attribute) else None)
                if name is None:
                    continue
                hit = self._resolve_hit(name, by_base, global_base,
                                        reaches, encl)
                if hit is not None:
                    return hit
        return None

    def _body_collective(self, body: ast.AST, by_base, global_base,
                         reaches,
                         encl: str) -> Optional[Tuple[str,
                                                      Optional[str]]]:
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            dotted = dotted_of(sub.func)
            if not dotted:
                continue
            parts = dotted.split(".")
            # bare `psum(` (from-import) counts like `lax.psum(`
            if parts[-1] in self._DEVICE_COLLECTIVES \
                    and (len(parts) == 1
                         or parts[0] in ("jax", "lax")):
                return parts[-1], None
            # comms.hist_allreduce(...) IS a device collective
            if parts[-1] in self._COMMS_WRAPPERS \
                    and (len(parts) == 1 or "comms" in parts):
                return parts[-1], None
            if parts[0] in ("jax", "lax", "jnp", "np", "numpy",
                            "functools"):
                continue
            # bare local/imported helper, or a method call
            # (self._helper(...)): resolve the basename — same-module
            # scoping first, any package function of that name last
            # (over-approximate, so a refactor can't hide a collective)
            hit = self._resolve_hit(parts[-1], by_base, global_base,
                                    reaches, encl)
            if hit is not None:
                return hit[0], parts[-1]
        return None

    def _resolve_hit(self, name: str, by_base, global_base, reaches,
                     encl: str) -> Optional[Tuple[str, Optional[str]]]:
        """Python-scoped resolution of a function reference, checked
        against the reaches-collective closure. Priority: the
        innermost enclosing-scope definition of ``name`` is EXCLUSIVE
        (proper lexical scoping — a clean local `do` never inherits a
        sibling's collective); otherwise any same-module, then any
        PACKAGE function of that basename counts (imported helpers,
        methods on package objects — over-approximate by design, so a
        refactor can't hide a collective; justified sites carry the
        pragma)."""
        cands = by_base.get(name, ())
        if cands:
            quals = {k[1]: k for k in cands}
            parts = encl.split(".") if encl != "<module>" else []
            for depth in range(len(parts), -1, -1):
                q = ".".join(parts[:depth] + [name])
                if q in quals:
                    key = quals[q]
                    if key in reaches:
                        return self._closure_name(key, reaches), name
                    return None
        for key in list(cands) + list(global_base.get(name, ())):
            if key in reaches:
                return self._closure_name(key, reaches), name
        return None

    @staticmethod
    def _funcs_by_basename(ctx: LintContext,
                           relpath: str) -> Dict[str, List[Key]]:
        out: Dict[str, List[Key]] = {}
        for key in ctx.graph.funcs:
            if key[0] == relpath:
                out.setdefault(key[1].rsplit(".", 1)[-1],
                               []).append(key)
        return out

    @staticmethod
    def _closure_name(key: Key, reaches) -> str:
        return reaches.get(key) or "psum"

    @staticmethod
    def _reaches_device_collective(graph: CallGraph) -> Dict[Key, str]:
        """key -> the device collective it (transitively) dispatches."""
        direct: Dict[Key, str] = {}
        wrappers = CollectiveUnderTracedCond._COMMS_WRAPPERS
        for scope, facts in graph.facts.items():
            if scope is None:
                continue
            for rec in facts.records:
                if rec.kind == "ext" and rec.dotted:
                    parts = rec.dotted.split(".")
                    if parts[-1] in \
                            CollectiveUnderTracedCond._DEVICE_COLLECTIVES \
                            and parts[0] in ("jax", "lax"):
                        direct.setdefault(scope, parts[-1])
                    elif parts[-1] in wrappers \
                            and (len(parts) == 1 or "comms" in parts):
                        # same spellings the cond-site recognizer
                        # accepts (bare from-import included) — the
                        # transitive map must not be narrower
                        direct.setdefault(scope, parts[-1])
        callers: Dict[Key, Set[Optional[Key]]] = {}
        for scope, facts in graph.facts.items():
            for rec in facts.records:
                if rec.kind == "known" and rec.target is not None:
                    callers.setdefault(rec.target, set()).add(scope)
        out = dict(direct)
        frontier = list(direct)
        while frontier:
            k = frontier.pop()
            for caller in callers.get(k, ()):
                if caller is not None and caller not in out:
                    out[caller] = out[k]
                    frontier.append(caller)
        return out


FLOW_RULES: List[Rule] = [CollectiveOrder(), ThreadSharedState(),
                          DtypePromotionLeak(),
                          CollectiveUnderTracedCond()]
