"""Logging (Log singleton analog, /root/reference/include/LightGBM/utils/log.h:88).

Levels Fatal/Warning/Info/Debug with a registerable callback, mirroring
``LGBM_RegisterLogCallback`` (c_api.h:73) / the python-package's
``register_logger``.
"""

from __future__ import annotations

import logging
import sys
from contextlib import contextmanager
from typing import Callable, Optional

__all__ = ["log_debug", "log_info", "log_warning", "LightGBMError",
           "register_logger", "set_verbosity", "get_verbosity",
           "scoped_verbosity"]

_logger: Optional[logging.Logger] = None
_info_method = "info"
_warning_method = "warning"
_verbosity = 1


def _default_logger() -> logging.Logger:
    logger = logging.getLogger("lightgbm_tpu")
    if not logger.handlers:
        h = logging.StreamHandler(sys.stdout)
        h.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(h)
        logger.setLevel(logging.INFO)
    return logger


def register_logger(logger: logging.Logger, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    global _logger, _info_method, _warning_method
    _logger = logger
    _info_method = info_method_name
    _warning_method = warning_method_name


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def get_verbosity() -> int:
    return _verbosity


@contextmanager
def scoped_verbosity(v: int):
    """Apply ``Config.verbosity`` for the duration of a train()/cv()/
    Booster entry point and restore the prior level on exit (reference
    semantics: ``verbosity=-1`` silences [Info] lines for that call
    only, it is not a global sticky setting)."""
    prev = get_verbosity()
    set_verbosity(v)
    try:
        yield
    finally:
        set_verbosity(prev)


def log_debug(msg: str) -> None:
    if _verbosity >= 2:
        getattr(_logger or _default_logger(), _info_method)(
            f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= 1:
        getattr(_logger or _default_logger(), _info_method)(
            f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= 0:
        getattr(_logger or _default_logger(), _warning_method)(
            f"[LightGBM-TPU] [Warning] {msg}")


class LightGBMError(Exception):
    pass
