# tpulint fixture: TPL008 positive — a micro-batcher whose worker
# thread mutates queue/latency bookkeeping no lock guards. This is the
# "delete the lock inside serve/batcher.py" acceptance shape:
# serve/tpl008_neg.py is the same batcher WITH the common lock, and
# stripping it must re-surface these findings.
import threading

_inflight = []        # module-global request book


class Batcher:
    def __init__(self):
        self.pending_rows = 0
        self.requests_total = 0
        self._worker = threading.Thread(target=self._worker_loop,
                                        daemon=True)
        self._worker.start()

    def _worker_loop(self):
        while True:
            # EXPECT: TPL008
            self.pending_rows = 0
            # EXPECT: TPL008
            self.requests_total += 1

    def submit(self, n):
        self.pending_rows += n
        return self.pending_rows

    def stats(self):
        return {"pending": self.pending_rows,
                "requests": self.requests_total}


def _drain_worker():
    # EXPECT: TPL008
    _inflight.clear()


def start_drain():
    threading.Thread(target=_drain_worker).start()
    return list(_inflight)
