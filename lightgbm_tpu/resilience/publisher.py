"""Atomic model publication: the train -> serve handoff.

The missing edge of the continuous lifecycle (docs/PIPELINE.md):
training produces a model, the serve daemon (serve/daemon.py) polls a
``--watch-dir`` for the newest artifact — this module is the writer
side of that contract, and it must survive being killed at any byte.

Protocol (manifest-first):

1. ``<name>.manifest.json`` is written atomically (same-dir tmp +
   ``os.replace``, utils/atomic.py) carrying the artifact's identity:
   its exact byte length and sha256, plus caller metadata (generation,
   data digest, train metrics). The manifest lands BEFORE the model
   file it describes, so a watcher can validate every model artifact
   it ever observes.
2. ``<name>`` (the model text) is written atomically.

A watcher that finds a model whose bytes do not match its manifest is
looking at a TORN publication — a writer that died between the two
steps, or a non-atomic writer mid-write. The serve watcher skips such
an artifact with a ``swap_failure`` fault event and retries next poll
(the atomic re-publish below will replace it); it never swaps to it.
Artifacts without a manifest (hand-dropped model files, checkpoint
snapshots) keep the legacy behavior: served as-is once they parse.

Transient publication failures (full disk, a slow NFS rename, the
injected ``publish_torn@G`` chaos kind) are retried with jittered
exponential backoff — the same retry shape as
``init_distributed`` — and counted in the ``publish_retries`` /
``publish_backoff_seconds`` registry counters.

This module never imports jax: the pipeline supervisor and the serve
watcher both consume it on jax-free paths.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.registry import bump_counter as _count
from ..utils.atomic import atomic_write_bytes
from ..utils.log import log_info, log_warning

__all__ = ["PublishError", "publish_model", "manifest_path",
           "load_manifest", "validate_artifact", "latest_manifest"]

MANIFEST_MAGIC = "lightgbm_tpu.publish.v1"
MANIFEST_SUFFIX = ".manifest.json"

#: retry/backoff defaults — overridable per call and via Config
#: (publish_retries / publish_backoff_sec, docs/PARAMETERS.md)
DEFAULT_RETRIES = 5
DEFAULT_BACKOFF_SEC = 0.25
BACKOFF_CAP_SEC = 15.0


class PublishError(RuntimeError):
    """A model publication failed (exhausted retries), or an artifact
    failed its manifest validation (torn / partial write)."""


def manifest_path(model_path) -> str:
    return os.fspath(model_path) + MANIFEST_SUFFIX


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def publish_model(model, directory, name: str, *,
                  metadata: Optional[Dict[str, Any]] = None,
                  retries: int = DEFAULT_RETRIES,
                  backoff_base_sec: float = DEFAULT_BACKOFF_SEC,
                  fault_iteration: int = -1,
                  _sleep: Callable[[float], None] = time.sleep,
                  _rng: Callable[[], float] = random.random
                  ) -> Dict[str, Any]:
    """Publish ``model`` into ``directory`` as ``name`` with a
    validating manifest; returns the manifest dict.

    ``model`` is a model-text string or anything with
    ``model_to_string()`` (a Booster). ``metadata`` is merged into the
    manifest (generation number, data digest, train metrics — whatever
    the retrain loop wants the serve side and post-mortems to see).
    ``fault_iteration`` keys the ``publish_torn@G`` chaos kind
    (typically the retrain generation number).

    Transient failures (OSError, injected tears) retry up to
    ``retries`` times with jittered exponential backoff
    (``backoff_base_sec`` doubling per attempt, capped at 15 s,
    x[0.5, 1.5) jitter); exhaustion raises :class:`PublishError`.
    """
    if not isinstance(model, str):
        model = model.model_to_string()
    t_start = time.perf_counter()
    payload = model.encode("utf-8")
    directory = os.fspath(directory)
    target = os.path.join(directory, name)
    # trace context (obs/trace.py): inherit the publishing process's
    # current trace (the pipeline supervisor's per-generation context,
    # via LIGHTGBM_TPU_TRACE_CTX) or start a fresh one, and stamp it
    # INTO the manifest — the serve watcher's validate->load->swap
    # spans then correlate back to the generation that published
    from ..obs import trace as _trace
    ctx = _trace.current_context()
    trace_id = ctx["trace_id"] if ctx else _trace.new_trace_id()
    parent_id = ctx["span_id"] if ctx else None
    span_id = _trace.new_span_id()
    manifest = {
        "magic": MANIFEST_MAGIC,
        "file": name,
        "bytes": len(payload),
        "sha256": _sha256_hex(payload),
        "created_unix": time.time(),
        "trace": {"trace_id": trace_id, "span_id": span_id},
        **(metadata or {}),
    }
    from .faults import FaultPlan, record_fault_event
    plan = FaultPlan.from_env()
    last_err: Optional[BaseException] = None
    for attempt in range(max(0, int(retries)) + 1):
        try:
            # manifest FIRST: every model artifact a watcher can ever
            # observe under this protocol is validatable
            atomic_write_bytes(
                manifest_path(target),
                (json.dumps(manifest) + "\n").encode("utf-8"))
            if plan.take("publish_torn", fault_iteration):
                # chaos: leave the torn artifact a crashed / non-atomic
                # writer would — a partial prefix, written in place —
                # then fail this attempt so the retry loop (and the
                # watcher's validation) must both do their jobs
                with open(target, "wb") as fh:
                    fh.write(payload[: max(1, len(payload) // 3)])
                record_fault_event(
                    "publish_torn", iteration=fault_iteration,
                    action="retry",
                    detail=f"injected torn publish of {name} "
                           "(LIGHTGBM_TPU_FAULT_INJECT)")
                raise PublishError(
                    f"injected torn publish of {name} "
                    "(LIGHTGBM_TPU_FAULT_INJECT)")
            atomic_write_bytes(target, payload)
        except (OSError, PublishError) as e:
            last_err = e
            if attempt >= retries:
                break
            delay = min(BACKOFF_CAP_SEC,
                        float(backoff_base_sec) * (2 ** attempt))
            delay *= 0.5 + _rng()            # jitter: x[0.5, 1.5)
            _count("publish_retries")
            _count("publish_backoff_seconds", delay)
            log_warning(f"publish: attempt {attempt + 1} for {name} "
                        f"failed ({e}); retrying in {delay:.2f}s")
            _sleep(delay)
            continue
        _count("publish_total")
        _trace.record_span(
            "publish/model", t_start, trace_id=trace_id,
            span_id=span_id, parent_id=parent_id,
            attrs={"file": name,
                   "generation": (metadata or {}).get("generation"),
                   "sha256": manifest["sha256"][:12],
                   "attempts": attempt + 1})
        log_info(f"publish: wrote {target} "
                 f"({len(payload)} bytes, sha256 "
                 f"{manifest['sha256'][:12]}…)")
        return manifest
    _count("publish_failures")
    raise PublishError(
        f"publishing {name} into {directory} failed after "
        f"{retries + 1} attempt(s): {last_err}") from last_err


def load_manifest(model_path) -> Optional[Dict[str, Any]]:
    """The manifest published alongside ``model_path``, or None when
    the artifact is unmanaged (no sidecar). A sidecar that exists but
    is unreadable/foreign raises :class:`PublishError` — a manifest
    is written atomically, so garbage there is corruption, not a
    mid-write artifact."""
    path = manifest_path(model_path)
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return None
    except OSError as e:
        raise PublishError(f"{path}: unreadable manifest ({e})") from e
    try:
        manifest = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise PublishError(f"{path}: malformed manifest ({e})") from e
    if not isinstance(manifest, dict) \
            or manifest.get("magic") != MANIFEST_MAGIC:
        raise PublishError(f"{path}: bad manifest magic "
                           f"{manifest.get('magic') if isinstance(manifest, dict) else None!r}")
    return manifest


def validate_artifact(model_path) -> Optional[Dict[str, Any]]:
    """Validate ``model_path`` against its published manifest.

    Returns the manifest when the bytes match, None when the artifact
    carries no manifest (legacy / hand-dropped file — the caller
    decides whether to trust it), and raises :class:`PublishError` on
    a mismatch: the artifact is torn (a publisher died between the
    manifest and the model write, or a non-atomic writer is mid-way
    through) and must not be served."""
    manifest = load_manifest(model_path)
    if manifest is None:
        return None
    with open(model_path, "rb") as fh:
        data = fh.read()
    if len(data) != int(manifest.get("bytes", -1)) \
            or _sha256_hex(data) != manifest.get("sha256"):
        raise PublishError(
            f"{os.fspath(model_path)}: torn or partial artifact — "
            f"{len(data)} bytes on disk vs {manifest.get('bytes')} "
            "published (sha256 mismatch); a publisher retry or the "
            "next atomic replace will supersede it")
    return manifest


def latest_manifest(directory) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Newest VALIDATED publication in ``directory``:
    ``(model_path, manifest)`` by manifest creation time, skipping
    torn or unreadable entries (with a warning). None when nothing
    validates — the warm-start path then trains from scratch.

    Ordering comes from the (cheap, json-read) manifests alone;
    artifact bytes are only hashed newest-first until one validates —
    a long-lived publish directory is not re-hashed end to end on
    every generation."""
    directory = os.fspath(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    candidates: List[Tuple[float, str, Dict[str, Any]]] = []
    for nm in names:
        if not nm.endswith(MANIFEST_SUFFIX):
            continue
        model_path = os.path.join(
            directory, nm[: -len(MANIFEST_SUFFIX)])
        try:
            manifest = load_manifest(model_path)
        except PublishError as e:
            log_warning(f"publish: skipping unusable publication "
                        f"{model_path!r} ({e})")
            continue
        if manifest is None:
            continue
        candidates.append(
            (float(manifest.get("created_unix", 0.0)), model_path,
             manifest))
    for _, model_path, manifest in sorted(candidates, reverse=True,
                                          key=lambda c: (c[0], c[1])):
        try:
            if validate_artifact(model_path) is not None:
                return model_path, manifest
        except (PublishError, OSError) as e:
            log_warning(f"publish: skipping unusable publication "
                        f"{model_path!r} ({e})")
    return None
