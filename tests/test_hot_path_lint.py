"""Static guard against the eager-loop regression class.

PROFILE.md (round 5) records a 530 ms/iter regression whose root cause
was a ``lax`` loop dispatching eagerly — op-by-op through the device
tunnel — instead of inside one jitted program. Op-level timing looks
fine in microbenchmarks, so nothing catches it at runtime; this lint
catches it at review time instead: every ``lax.fori_loop`` /
``lax.scan`` / ``lax.while_loop`` call in the boosting path
(``models/gbdt.py`` + ``ops/``) must live inside a function on the
KNOWN_JITTED allowlist — functions whose only entry is through a
``jax.jit`` wrapper (``grow_tree``, the fused-iteration program, the
prediction jits).

Adding a new device loop? Put it behind a jitted entry point, register
that entry point with ``obs.register_jit`` (so recompiles are counted),
and add the enclosing function here.
"""

import ast
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "lightgbm_tpu")

LOOP_NAMES = {"fori_loop", "scan", "while_loop"}

# root-level functions whose bodies are only ever traced (verified:
# every call path enters through a jax.jit wrapper)
KNOWN_JITTED = {
    ("ops/gather.py", "_gather_small"),      # gather_small jit
    ("ops/grow.py", "_grow_masked_impl"),    # grow_tree jit
    ("ops/grow.py", "_grow_compact_impl"),   # grow_tree jit
    ("ops/histogram.py", "_hist_from_rows_impl"),
    ("ops/histogram.py", "_hist_scatter"),
    ("ops/predict.py", "_traverse"),         # predict jits
    ("ops/predict.py", "predict_forest_raw"),
}


def _hot_path_files():
    out = [os.path.join(PKG, "models", "gbdt.py")]
    ops = os.path.join(PKG, "ops")
    out.extend(os.path.join(ops, f) for f in sorted(os.listdir(ops))
               if f.endswith(".py"))
    return out


def _loop_sites(path):
    """(lineno, loop_name, root_function) of every lax loop call."""
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    sites = []

    def visit(node, stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack = stack + [node.name]
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in LOOP_NAMES:
                root = stack[0] if stack else "<module>"
                sites.append((node.lineno, fn.attr, root))
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(tree, [])
    return sites


def test_no_eager_lax_loops_in_boosting_path():
    offenders = []
    for path in _hot_path_files():
        rel = os.path.relpath(path, PKG).replace(os.sep, "/")
        for lineno, loop, root in _loop_sites(path):
            if (rel, root) not in KNOWN_JITTED:
                offenders.append(f"{rel}:{lineno}: lax.{loop} in "
                                 f"{root}() is not on the KNOWN_JITTED "
                                 "allowlist")
    assert not offenders, (
        "eager-dispatch risk (PROFILE.md 530 ms/iter class):\n  "
        + "\n  ".join(offenders))


def _function_node(tree, qualpath):
    """Find a (possibly nested) FunctionDef by ['outer', 'inner'] path."""
    nodes = [tree]
    for name in qualpath:
        found = None
        for node in nodes:
            for child in ast.walk(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == name:
                    found = child
                    break
            if found is not None:
                break
        assert found is not None, f"function {'.'.join(qualpath)} not found"
        nodes = [found]
    return nodes[0]


def test_nonfinite_guard_stays_inside_jitted_step():
    """The resilience guard contract (docs/RESILIENCE.md): the
    non-finite check on gradients/hessians/leaf values must live INSIDE
    the fused jitted step (one fused reduction), and the fused
    iteration wrapper must not grow an eager per-iteration host fetch
    (np.asarray / device_get / block_until_ready) — that would
    serialize the device pipeline, the exact regression class the lint
    above guards against."""
    path = os.path.join(PKG, "models", "gbdt.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)

    # (1) guard fused into the traced program: `step` (the body jitted
    # by _get_fused_fn) must trace the guard — either inline isfinite
    # reductions or calls into the shared pure-jnp guard helpers
    # (_gh_flag_clamp / _leaf_guard), which themselves must reduce via
    # isfinite
    guard_helpers = {"_gh_flag_clamp", "_leaf_guard"}

    def _calls(fn_node):
        names = set()
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    names.add(n.func.attr)
                elif isinstance(n.func, ast.Name):
                    names.add(n.func.id)
        return names

    step = _function_node(tree, ["_get_fused_fn", "step"])
    step_calls = _calls(step)
    assert "isfinite" in step_calls or (step_calls & guard_helpers), (
        "the non-finite guard left the fused jitted step: "
        "_get_fused_fn.step must trace jnp.isfinite (directly or via "
        "_gh_flag_clamp/_leaf_guard), not check eagerly")
    for helper in guard_helpers & step_calls:
        node = _function_node(tree, [helper])
        assert "isfinite" in _calls(node), (
            f"{helper} no longer reduces via jnp.isfinite — the fused "
            "guard is gone")

    # (2) no host materialization in the fused iteration driver: the
    # guard flag must travel through the async one-iteration-late queue
    fused = _function_node(tree, ["_train_one_iter_fused"])
    offenders = []
    for n in ast.walk(fused):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)):
            continue
        attr = n.func.attr
        base = n.func.value
        if attr == "block_until_ready":
            offenders.append(f"line {n.lineno}: .block_until_ready()")
        elif isinstance(base, ast.Name) and (base.id, attr) in (
                ("np", "asarray"), ("jax", "device_get"),
                ("np", "array")):
            offenders.append(f"line {n.lineno}: {base.id}.{attr}()")
    assert not offenders, (
        "eager host fetch in _train_one_iter_fused (guard/fault flags "
        "must use the async _push_guard_flags queue):\n  "
        + "\n  ".join(offenders))


def test_allowlist_entries_still_exist():
    """A renamed/deleted function must be pruned from the allowlist —
    stale entries would silently stop guarding anything."""
    live = set()
    for path in _hot_path_files():
        rel = os.path.relpath(path, PKG).replace(os.sep, "/")
        for _, _, root in _loop_sites(path):
            live.add((rel, root))
    stale = KNOWN_JITTED - live
    assert not stale, f"prune stale allowlist entries: {sorted(stale)}"
