"""Linear trees (LinearTreeLearner, linear_tree_learner.cpp)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _linear_data(n=2000, f=5, seed=3, with_nan=False):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = 2.0 * X[:, 0] - 1.5 * X[:, 1] + 0.3 * X[:, 2] + 0.05 * rs.randn(n)
    if with_nan:
        X[rs.rand(n) < 0.05, 0] = np.nan
    return X, y


def test_linear_tree_beats_constant_on_linear_data():
    X, y = _linear_data()
    params = {"objective": "regression", "num_leaves": 4,
              "min_data_in_leaf": 20, "learning_rate": 0.5,
              "verbosity": -1}
    d1 = lgb.Dataset(X, label=y, params={"linear_tree": True})
    b_lin = lgb.train(dict(params, linear_tree=True), d1,
                      num_boost_round=10)
    d2 = lgb.Dataset(X, label=y)
    b_const = lgb.train(dict(params), d2, num_boost_round=10)
    mse_lin = float(np.mean((b_lin.predict(X) - y) ** 2))
    mse_const = float(np.mean((b_const.predict(X) - y) ** 2))
    assert mse_lin < 0.5 * mse_const
    # trained trees carry linear models
    assert any(t.is_linear and any(len(c) for c in (t.leaf_coeff or []))
               for t in b_lin._models)


def test_linear_tree_save_load_roundtrip(tmp_path):
    X, y = _linear_data(seed=7)
    d = lgb.Dataset(X, label=y, params={"linear_tree": True})
    bst = lgb.train({"objective": "regression", "num_leaves": 5,
                     "linear_tree": True, "verbosity": -1}, d,
                    num_boost_round=8)
    p1 = bst.predict(X)
    path = str(tmp_path / "lin.txt")
    bst.save_model(path)
    b2 = lgb.Booster(model_file=path)
    p2 = b2.predict(X)
    np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-6)
    assert "is_linear=1" in open(path).read()


def test_linear_tree_nan_falls_back_to_constant():
    X, y = _linear_data(with_nan=True)
    d = lgb.Dataset(X, label=y, params={"linear_tree": True})
    bst = lgb.train({"objective": "regression", "num_leaves": 5,
                     "linear_tree": True, "verbosity": -1}, d,
                    num_boost_round=5)
    p = bst.predict(X)
    assert np.all(np.isfinite(p))
    # train metric consistency: internal score equals re-predicted score
    internal = bst._engine.current_score(0)[0]
    np.testing.assert_allclose(internal, bst.predict(X), rtol=1e-4,
                               atol=1e-4)


def test_linear_tree_with_valid_sets_and_cv():
    """Valid Datasets built with reference= inherit raw retention; cv
    folds subset the raw matrix (review findings on reference-aligned
    datasets)."""
    X, y = _linear_data(n=600, seed=9)
    d = lgb.Dataset(X[:500], label=y[:500], params={"linear_tree": True})
    v = lgb.Dataset(X[500:], label=y[500:], reference=d)
    ev = {}
    bst = lgb.train({"objective": "regression", "num_leaves": 4,
                     "linear_tree": True, "metric": "l2",
                     "verbosity": -1}, d, num_boost_round=5,
                    valid_sets=[v],
                    callbacks=[lgb.record_evaluation(ev)])
    assert len(ev["valid_0"]["l2"]) == 5
    # valid score equals re-predicted score
    internal = bst._engine.current_score(1)[0]
    np.testing.assert_allclose(internal, bst.predict(X[500:],
                                                     raw_score=True),
                               rtol=1e-4, atol=1e-4)
    res = lgb.cv({"objective": "regression", "num_leaves": 4,
                  "linear_tree": True, "metric": "l2", "verbosity": -1},
                 lgb.Dataset(X, label=y, params={"linear_tree": True}),
                 num_boost_round=3, nfold=3)
    assert len(res["valid l2-mean"]) == 3
