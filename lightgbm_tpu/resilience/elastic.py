"""Supervised elastic restart: ``python -m lightgbm_tpu launch``.

The missing half of distributed fault tolerance: the collective
watchdog (resilience/watchdog.py) turns a hung world into per-rank
*errors*, and the checkpoint layer (resilience/checkpoint.py) makes the
training state durable — but something still has to notice dead
workers, tear down the survivors, and bring the world back up. That is
this supervisor::

    python -m lightgbm_tpu launch 4 -- python train.py

It spawns one training subprocess per rank with the coordinator
environment pre-wired (``LIGHTGBM_TPU_COORDINATOR`` /
``LIGHTGBM_TPU_NUM_PROCS`` / ``LIGHTGBM_TPU_RANK`` — a bare
``init_distributed()`` in the training script picks them up), watches
for any rank exiting nonzero (a crash, or a surviving rank's watchdog
abort), kills the rest of the world, and relaunches everything on a
fresh coordinator port. With ``LIGHTGBM_TPU_CHECKPOINT`` exported (or
``--checkpoint-dir``), every relaunch auto-resumes from the newest
snapshot, so the restarted run converges to the same model an
uninterrupted run produces (docs/RESILIENCE.md "Distributed
failures").

Two supervision shapes share this module:

- **World restart** (:func:`supervise`, the training shape): ranks
  form ONE collective world, so the first nonzero exit kills the rest
  and relaunches everything on a fresh coordinator port, resuming
  from the newest checkpoint.
- **Fleet restart** (:func:`supervise_fleet`, ``--health-port``; the
  serving shape): ranks are INDEPENDENT replicas, so only the dead
  one is relaunched while the others keep answering traffic. The
  supervisor additionally health-checks each replica through the
  daemon's own JSON ``{"cmd": "ping"}`` protocol on
  ``health_port + rank`` — a replica that is alive-but-wedged (no
  exit code will ever come) fails ``--health-fails`` consecutive
  pings and is killed and relaunched like a dead one. With
  ``--max-replicas`` the fleet additionally GROWS and SHRINKS: an
  :class:`~.autoscale.AutoscalePolicy` fed by the scrape thread
  spawns fresh replicas under load (QPS / p99 / shed triggers with
  hysteresis) and retires the highest-rank replica with a SIGTERM
  drain when traffic subsides, and with ``--publish-dir`` a
  :class:`~.autoscale.RollbackGuard` watches the newest publication
  and rolls the store back to last-known-good when the fleet's
  canary gates refuse it or a swapped replica trips post-swap health
  checks (docs/RESILIENCE.md).

Both shapes draw restarts from one :class:`RestartBudget`: a total
cap (``--max-restarts``) plus an optional SLIDING WINDOW cap
(``--max-restarts-per-window`` within ``--restart-window`` seconds) so
a crash-loop burns out quickly instead of thrashing for hours at a
slow total budget, and each restart waits out a jittered exponential
backoff (base 0.5 s doubling per consecutive failure, 15 s cap —
``init_distributed``'s retry shape) counted in the
``supervisor_restarts`` / ``supervisor_backoff_seconds`` registry
counters.

One-shot injected faults (``rank_kill`` / ``stall_rank`` /
``serve_kill`` in ``LIGHTGBM_TPU_FAULT_INJECT``) are stripped from the
environment on relaunch — consume-on-fire cannot survive a process
restart, and without stripping the injected failure would recur every
generation forever.

This module (and the whole ``launch`` dispatch in ``__main__``) never
imports jax: the supervisor must stay alive and tiny while worlds die
around it, and must not pin accelerator devices the workers need.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..utils.log import log_info, log_warning

__all__ = ["main", "supervise", "supervise_fleet", "worker_env",
           "strip_one_shot_faults", "RestartBudget", "replica_ping",
           "replica_rpc", "fleet_telemetry_path"]

#: fault kinds that must not re-fire after a supervised restart —
#: the one_shot classification in the single-source fault registry
#: (obs/schemas.py FAULT_KINDS, the TPL018 contract)
from ..obs.schemas import one_shot_fault_kinds as _one_shot_kinds

_ONE_SHOT_KINDS = _one_shot_kinds()

_POLL_SECONDS = 0.2

#: jittered exponential backoff shape between restarts (mirrors
#: parallel/distributed.py init_distributed's retry curve)
_BACKOFF_BASE_SEC = 0.5
_BACKOFF_CAP_SEC = 15.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill_group(proc: subprocess.Popen) -> None:
    """SIGKILL a worker's whole process group (workers run in their own
    session); fall back to killing the process alone."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.kill()
        except OSError:
            pass


from ..obs.registry import bump_counter as _count


class RestartBudget:
    """Total + sliding-window restart admission, with the jittered
    exponential backoff delay to respect before each admitted restart.

    ``admit()`` returns None when a restart may proceed (recording it
    against both budgets) or a human-readable refusal. ``backoff()``
    returns the pre-restart delay for the ``consecutive``-th failure
    in a row and counts it in ``supervisor_backoff_seconds``.
    """

    def __init__(self, max_restarts: int,
                 max_per_window: int = 0,
                 window_sec: float = 300.0,
                 backoff_base_sec: float = _BACKOFF_BASE_SEC,
                 _now=time.monotonic,
                 _rng: Optional[random.Random] = None):
        self.max_restarts = int(max_restarts)
        self.max_per_window = int(max_per_window)
        self.window_sec = float(window_sec)
        self.backoff_base_sec = float(backoff_base_sec)
        self.total = 0
        self._times: deque = deque()
        self._now = _now
        self._rng = _rng if _rng is not None else random.Random()

    def admit(self) -> Optional[str]:
        now = self._now()
        if self.total >= self.max_restarts:
            return f"the total restart budget ({self.max_restarts}) " \
                   "is spent"
        if self.max_per_window > 0:
            while self._times and now - self._times[0] > self.window_sec:
                self._times.popleft()
            if len(self._times) >= self.max_per_window:
                return (f"{len(self._times)} restarts within the last "
                        f"{self.window_sec:g}s sliding window "
                        f"(--max-restarts-per-window "
                        f"{self.max_per_window}) — this is a crash "
                        "loop, not a transient fault")
        self.total += 1
        self._times.append(now)
        _count("supervisor_restarts")
        return None

    def backoff(self, consecutive: int) -> float:
        """Jittered exponential delay before the ``consecutive``-th
        restart in a row (1-based): base x 2^(n-1), capped, x[0.5,
        1.5) jitter so simultaneously-restarting supervisors do not
        stampede one coordinator/port."""
        exp = max(0, int(consecutive) - 1)
        delay = min(_BACKOFF_CAP_SEC, self.backoff_base_sec * (2 ** exp))
        delay *= 0.5 + self._rng.random()
        _count("supervisor_backoff_seconds", delay)
        return delay


def replica_rpc(port: int, obj: Dict, timeout: float = 5.0,
                host: str = "127.0.0.1") -> Optional[Dict]:
    """One request -> one reply against a serve replica's JSON-lines
    protocol; None on any transport/parse failure, never an exception
    — the callers are supervision/polling loops."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall((json.dumps(obj) + "\n").encode("utf-8"))
            fh = s.makefile("r", encoding="utf-8")
            line = fh.readline()
        out = json.loads(line)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def replica_ping(port: int, timeout: float = 5.0,
                 host: str = "127.0.0.1") -> bool:
    """One health probe: the daemon's ``{"cmd": "ping"}`` answered
    with ``ok``."""
    reply = replica_rpc(port, {"cmd": "ping"}, timeout=timeout,
                        host=host)
    return bool(reply and reply.get("ok"))


class _FleetTelemetry:
    """Append-only JSONL writer for the supervisor's ``{"event":
    "fleet"}`` scrape records. The fleet supervisor's SCRAPE thread
    and its main supervision loop (autoscale / rollback events) both
    write, so the file handle sits under a lock — interleaved partial
    lines would corrupt the stream. An unwritable path degrades to
    registry-only scraping, mirroring the recorder's contract."""

    def __init__(self, path: Optional[str]):
        self._lock = threading.Lock()
        # ---- guarded by self._lock ----
        self._file = None
        if not path:
            return
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._file = open(path, "a", encoding="utf-8")
        except OSError as e:
            log_warning(f"elastic: cannot open fleet telemetry "
                        f"{path!r} ({e}); fleet events will not be "
                        "written")

    def write(self, event: Dict) -> None:
        line = json.dumps(event) + "\n"
        with self._lock:
            if self._file is None:
                return
            try:
                self._file.write(line)
                self._file.flush()
            except OSError:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


def _drain_spans_into(telem: "_FleetTelemetry") -> None:
    """Restart spans (obs/trace.py) recorded by the supervision loop
    ride its fleet stream on the scrape cadence, like the scrape
    records themselves. Without a stream the spans stay buffered
    (capped) for an embedding caller — the pipeline supervisor calls
    :func:`supervise` in-process and drains into its own event log."""
    if telem._file is None:
        return
    try:
        from ..obs.trace import drain_span_events
        for ev in drain_span_events():
            telem.write(ev)
    except Exception:
        pass


def fleet_telemetry_path(env: Optional[Dict[str, str]] = None) \
        -> Optional[str]:
    """Where a supervisor writes its scrape records: the run's
    telemetry stream (from ``env``, default ``os.environ``) with a
    ``.fleet`` suffix — the serve replicas own the base path (rank 0)
    and its ``.rankN`` suffixes, and ``lightgbm_tpu stats <dir>
    --fleet`` merges all of them."""
    base = (os.environ if env is None else env).get(
        "LIGHTGBM_TPU_TELEMETRY")
    return f"{base}.fleet" if base else None


#: replica-row field <- OpenMetrics sample of the replica's metrics
#: render (serve/daemon.py metrics_families + its registry counters)
_REPLICA_SAMPLES = (
    ("qps", "lightgbm_tpu_serve_qps"),
    ("p50_ms", "lightgbm_tpu_serve_p50_ms"),
    ("p99_ms", "lightgbm_tpu_serve_p99_ms"),
    ("requests_total", "lightgbm_tpu_serve_requests_total"),
    ("rows_total", "lightgbm_tpu_serve_rows_total"),
    ("shed_total", "lightgbm_tpu_serve_shed_total"),
    ("swaps_total", "lightgbm_tpu_serve_swaps_total"),
    ("swap_failures_total", "lightgbm_tpu_serve_swap_failures_total"),
)


def _replica_metrics_row(port: int, timeout: float) -> Dict:
    """One replica's scrape via the NON-consuming ``{"cmd":
    "metrics"}`` verb — ``{"cmd": "stats"}`` would reset the daemon's
    own qps rate window and steal its recompile deltas (the daemon
    caches its last stats window precisely so metrics reads never
    consume it). Empty dict on any failure."""
    from ..obs.export import parse_openmetrics
    reply = replica_rpc(port, {"cmd": "metrics"}, timeout=timeout)
    if not reply or not reply.get("ok"):
        return {}
    try:
        samples = parse_openmetrics(reply["metrics"])
    except (KeyError, TypeError, ValueError):
        return {}
    row: Dict = {}
    for key, name in _REPLICA_SAMPLES:
        fam = samples.get(name)
        if fam:
            row[key] = next(iter(fam.values()))
    info = samples.get("lightgbm_tpu_serve_model_info")
    if info:
        labels = dict(next(iter(info.keys())))
        if labels.get("model"):
            row["model"] = labels["model"]
        if labels.get("sha"):
            row["sha256"] = labels["sha"]
    return row


def _scrape_fleet(fleet: List["_Replica"], health_port: Optional[int],
                  health_timeout: float) -> Dict:
    """One scrape round over the replica fleet: liveness + restart
    generation from the supervisor's own bookkeeping, QPS/p99/shed
    from each live replica's ``{"cmd": "metrics"}`` protocol verb.
    Feeds the supervisor's registry (its /metrics endpoint) and
    returns the ``{"event": "fleet"}`` record.

    Per-replica fetches run CONCURRENTLY: a wedged replica — one that
    accepts TCP but never replies — costs one ``health_timeout`` in
    its own fetch thread, not one per healthy replica queued behind it
    in a serial round. A replica whose process is up but whose metrics
    fetch failed or timed out is marked ``alive: false`` (with
    ``responsive: false``): "alive" in a fleet record means SERVING,
    and a silent socket is not serving."""
    from ..obs.registry import registry
    live: List = []
    results: Dict[int, Dict] = {}
    results_lock = threading.Lock()
    fetchers: List[threading.Thread] = []
    for rep in fleet:
        alive = (not rep.done and rep.relaunch_at is None
                 and rep.proc is not None and rep.proc.poll() is None)
        live.append((rep, alive))
        if alive and health_port is not None:
            def _fetch(rank: int = rep.rank) -> None:
                row = _replica_metrics_row(health_port + rank,
                                           health_timeout)
                with results_lock:
                    results[rank] = row
            t = threading.Thread(target=_fetch, daemon=True)
            t.start()
            fetchers.append(t)
    deadline = time.monotonic() + health_timeout + 1.0
    for t in fetchers:
        t.join(timeout=max(0.1, deadline - time.monotonic()))
    replicas = []
    restarts_total = 0
    for rep, alive in live:
        row: Dict = {"rank": rep.rank, "alive": alive,
                     "restarts": rep.generation}
        if rep.retiring:
            row["retiring"] = True
        restarts_total += rep.generation
        if alive and health_port is not None:
            with results_lock:
                metrics = results.get(rep.rank)
            if metrics:
                row.update(metrics)
            else:
                row["alive"] = False
                row["responsive"] = False
        replicas.append(row)
        try:
            labels = {"rank": rep.rank}
            registry.gauge("fleet_replica_up", **labels).set(
                1.0 if row["alive"] else 0.0)
            registry.gauge("fleet_replica_restarts", **labels).set(
                rep.generation)
            for key, fam in (("qps", "fleet_replica_qps"),
                             ("p99_ms", "fleet_replica_p99_ms"),
                             ("shed_total", "fleet_replica_shed")):
                if row.get(key) is not None:
                    registry.gauge(fam, **labels).set(row[key])
        except Exception:
            pass                  # telemetry must never kill the loop
    return {"event": "fleet", "shape": "replicas",
            "replicas": replicas, "restarts_total": restarts_total,
            "time": time.time()}


def _scrape_world_ranks(nprocs: int, worker_metrics_base: int,
                        timeout: float = 2.0) -> Optional[Dict]:
    """One scrape round over a TRAINING world's per-rank /metrics
    endpoints (rank r binds ``worker_metrics_base + r``): per-rank
    iteration/recompile counts and the cross-rank iteration skew —
    the straggler signal chip-level phase aggregation cannot see once
    a rank's process is wedged. An unreachable endpoint — a wedged
    rank, or a bind failure — is exactly the condition this scrape
    exists to surface, so it records an ``alive: false`` row instead
    of silently shrinking the rank list. None when NO endpoint
    answered (nothing to distinguish 'all wedged' from 'metrics not
    up yet' on the first cadence)."""
    import urllib.request

    from ..obs.export import parse_openmetrics
    from ..obs.registry import registry
    ranks = []
    iterations = []
    any_alive = False
    for rank in range(nprocs):
        url = (f"http://127.0.0.1:{worker_metrics_base + rank}"
               "/metrics")
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                samples = parse_openmetrics(
                    resp.read().decode("utf-8"))
        except (OSError, ValueError):
            ranks.append({"rank": rank, "alive": False})
            try:
                registry.gauge("fleet_rank_up", rank=rank).set(0.0)
            except Exception:
                pass
            continue

        def sample(name: str) -> Optional[float]:
            fam = samples.get("lightgbm_tpu_" + name)
            if not fam:
                return None
            return next(iter(fam.values()))

        any_alive = True
        row: Dict = {"rank": rank, "alive": True}
        for key, metric in (("iterations", "iterations_total"),
                            ("recompiles", "jit_recompiles_total"),
                            ("hbm_bytes_in_use", "hbm_bytes_in_use")):
            value = sample(metric)
            if value is not None:
                row[key] = value
        ranks.append(row)
        try:
            registry.gauge("fleet_rank_up", rank=rank).set(1.0)
        except Exception:
            pass
        if row.get("iterations") is not None:
            iterations.append(row["iterations"])
            try:
                registry.gauge("fleet_rank_iterations",
                               rank=rank).set(row["iterations"])
            except Exception:
                pass
    if not any_alive:
        return None
    skew = int(max(iterations) - min(iterations)) if iterations \
        else None
    if skew is not None:
        try:
            registry.gauge("fleet_iteration_skew").set(skew)
        except Exception:
            pass
    return {"event": "fleet", "shape": "world", "nprocs": nprocs,
            "ranks": ranks, "iteration_skew": skew,
            "time": time.time()}


def strip_one_shot_faults(spec: str) -> str:
    """Drop ``rank_kill``/``stall_rank``/``serve_kill`` tokens from a
    ``LIGHTGBM_TPU_FAULT_INJECT`` value for a relaunch."""
    kept = [tok for tok in spec.split(",")
            if tok.strip()
            and tok.split("@", 1)[0].strip() not in _ONE_SHOT_KINDS]
    return ",".join(kept)


def worker_env(base: Dict[str, str], rank: int, nprocs: int,
               port: int, generation: int = 0) -> Dict[str, str]:
    """The per-rank environment one generation of workers runs with."""
    env = dict(base)
    env["LIGHTGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    env["LIGHTGBM_TPU_NUM_PROCS"] = str(nprocs)
    env["LIGHTGBM_TPU_RANK"] = str(rank)
    env["LIGHTGBM_TPU_RESTART_COUNT"] = str(generation)
    if generation > 0 and env.get("LIGHTGBM_TPU_FAULT_INJECT"):
        env["LIGHTGBM_TPU_FAULT_INJECT"] = strip_one_shot_faults(
            env["LIGHTGBM_TPU_FAULT_INJECT"])
    return env


def _launch_generation(cmd: Sequence[str], nprocs: int, port: int,
                       generation: int, log_dir: str,
                       base_env: Dict[str, str]) -> List[subprocess.Popen]:
    procs = []
    try:
        for rank in range(nprocs):
            log_path = os.path.join(
                log_dir, f"elastic_g{generation}_rank{rank}.log")
            log_file = open(log_path, "ab")
            try:
                procs.append(subprocess.Popen(
                    list(cmd),
                    env=worker_env(base_env, rank, nprocs, port,
                                   generation),
                    stdout=log_file, stderr=subprocess.STDOUT,
                    start_new_session=True))
            finally:
                log_file.close()   # the child holds its own fd now
    except BaseException:
        # a mid-loop failure (EMFILE, deleted log dir) must not leave
        # the already-spawned ranks orphaned, waiting on peers that
        # will never come up
        for p in procs:
            _kill_group(p)
        raise
    return procs


def _wait_generation(procs: List[subprocess.Popen],
                     grace: float,
                     on_poll=None) -> int:
    """Block until the generation resolves: 0 when every rank exited
    cleanly, else the first nonzero exit code (the rest of the world is
    killed after ``grace`` seconds — survivors are either hung in a
    collective or about to watchdog-abort; their state is already
    checkpointed). ``on_poll`` (optional zero-arg callable) runs once
    per poll round — the metrics scrape cadence rides the existing
    supervision loop instead of a thread."""
    while True:
        if on_poll is not None:
            on_poll()
        first_bad: Optional[subprocess.Popen] = None
        alive = 0
        for p in procs:
            rc = p.poll()
            if rc is None:
                alive += 1
            elif rc != 0 and first_bad is None:
                first_bad = p
        if first_bad is not None:
            rank = procs.index(first_bad)
            rc = first_bad.returncode
            log_warning(f"elastic: rank {rank} exited with code "
                        f"{rc}; stopping the world")
            deadline = time.monotonic() + max(0.0, grace)
            while time.monotonic() < deadline and any(
                    p.poll() is None for p in procs):
                time.sleep(_POLL_SECONDS)
            for p in procs:
                if p.poll() is None:
                    _kill_group(p)
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    _kill_group(p)
            # signal deaths carry a NEGATIVE returncode; surface them
            # shell-style (128+signum) so SystemExit doesn't truncate
            # -9 into an unrelated 247
            return (128 - rc) if rc and rc < 0 else (rc or 1)
        if alive == 0:
            return 0
        time.sleep(_POLL_SECONDS)


def supervise(nprocs: int, cmd: Sequence[str], max_restarts: int = 3,
              port: Optional[int] = None, log_dir: str = ".",
              grace: float = 5.0,
              env: Optional[Dict[str, str]] = None,
              max_restarts_per_window: int = 0,
              restart_window_sec: float = 300.0,
              metrics_port: Optional[int] = None,
              scrape_interval: float = 0.0) -> int:
    """Run ``cmd`` as an ``nprocs``-rank world under supervision;
    returns the final exit code (0 = a generation completed cleanly).

    Each generation gets a fresh coordinator port — the previous
    coordinator died with its rank-0 worker, and its socket may linger
    in TIME_WAIT. Worker output goes to
    ``{log_dir}/elastic_g{generation}_rank{rank}.log``. Restarts draw
    from a :class:`RestartBudget` (total cap + optional sliding
    window) and each one waits out a jittered exponential backoff so
    a crash-looping world cannot thrash coordinator ports at full
    speed.
    """
    if nprocs < 1:
        raise ValueError("nprocs must be >= 1")
    if not cmd:
        raise ValueError("no worker command given (pass it after --)")
    base_env = dict(os.environ if env is None else env)
    os.makedirs(log_dir, exist_ok=True)
    budget = RestartBudget(max_restarts, max_restarts_per_window,
                           restart_window_sec)
    # fleet metrics plane (docs/OBSERVABILITY.md): the supervisor
    # serves its own jax-free /metrics at the base port, workers bind
    # base+1+rank (engine.py reads LIGHTGBM_TPU_METRICS_PORT and adds
    # its rank), and the supervision loop scrapes the rank endpoints
    # into {"event": "fleet"} records carrying the iteration skew
    if metrics_port:
        from ..obs.export import ensure_metrics_server
        ensure_metrics_server(metrics_port)
        base_env["LIGHTGBM_TPU_METRICS_PORT"] = str(metrics_port + 1)
    # world-shape scraping reads the rank /metrics endpoints, so it
    # needs metrics_port; without it the .fleet file must not even be
    # created (an empty stray artifact per run otherwise)
    telem = _FleetTelemetry(
        fleet_telemetry_path(base_env)
        if scrape_interval > 0 and metrics_port else None)
    next_scrape = time.monotonic() + max(0.0, scrape_interval)

    def _poll_scrape() -> None:
        nonlocal next_scrape
        if scrape_interval <= 0 or not metrics_port:
            return
        now = time.monotonic()
        if now < next_scrape:
            return
        next_scrape = now + scrape_interval
        event = _scrape_world_ranks(nprocs, metrics_port + 1)
        if event is not None:
            telem.write(event)
        _drain_spans_into(telem)

    generation = 0
    consecutive = 0
    while True:
        gen_port = port if port else _free_port()
        log_info(f"elastic: generation {generation}: launching "
                 f"{nprocs} rank(s), coordinator 127.0.0.1:{gen_port}")
        procs = _launch_generation(cmd, nprocs, gen_port, generation,
                                   log_dir, base_env)
        try:
            rc = _wait_generation(procs, grace,
                                  on_poll=_poll_scrape)
        except BaseException:   # ctrl-C etc.: never leak a world
            for p in procs:
                if p.poll() is None:
                    _kill_group(p)
            telem.close()
            raise
        if rc == 0:
            log_info(f"elastic: generation {generation} completed "
                     "cleanly")
            telem.close()
            return 0
        refusal = budget.admit()
        if refusal is not None:
            log_warning(
                f"elastic: generation {generation} failed (exit {rc}) "
                f"and {refusal} — giving up")
            telem.close()
            return rc
        generation += 1
        consecutive += 1
        try:
            from ..obs.registry import registry
            registry.counter("elastic_restarts").inc()
        except Exception:
            pass
        delay = budget.backoff(consecutive)
        log_info(f"elastic: restarting the world (restart {generation}"
                 f"/{max_restarts}) in {delay:.2f}s; training resumes "
                 "from the newest checkpoint if LIGHTGBM_TPU_CHECKPOINT "
                 "is set")
        t_restart = time.perf_counter()
        time.sleep(delay)
        try:
            # the restart's backoff IS lifecycle latency: span it so
            # the merged trace shows where a chaos-killed generation's
            # wall time went (drained into the fleet stream on the
            # scrape cadence, or by the embedding pipeline supervisor)
            from ..obs.trace import current_context, record_span
            ctx = current_context()
            record_span("restart/world", t_restart,
                        trace_id=ctx["trace_id"] if ctx else None,
                        parent_id=ctx["span_id"] if ctx else None,
                        attrs={"restart": generation, "rc": rc,
                               "backoff_s": round(delay, 3)})
        except Exception:
            pass
        _drain_spans_into(telem)


def _term_group(proc: subprocess.Popen) -> None:
    """SIGTERM a replica's whole process group — the graceful-drain
    signal the serve daemon turns into stop-accepting + answer
    backlogged connections with a draining reply + finish in-flight
    work; fall back to terminating the process alone."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.terminate()
        except OSError:
            pass


class _Replica:
    """One independently-supervised fleet member."""

    __slots__ = ("rank", "proc", "generation", "launched_at",
                 "consecutive_restarts", "ping_failures", "done",
                 "relaunch_at", "restart_t0", "retiring",
                 "retire_deadline")

    def __init__(self, rank: int):
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0
        self.launched_at = 0.0
        self.consecutive_restarts = 0
        self.ping_failures = 0
        self.done = False           # exited 0: intentional, no restart
        # backoff deadline of a scheduled relaunch (None = running):
        # a per-replica NOT-BEFORE time, never an inline sleep — one
        # replica's backoff must not stall supervision of the others
        self.relaunch_at: Optional[float] = None
        # perf_counter when the death/wedge was observed; closes into
        # a restart/replica span (obs/trace.py) at relaunch
        self.restart_t0: Optional[float] = None
        # scale-down drain in progress: SIGTERM sent, any exit code
        # finishes the replica WITHOUT a restart; past the deadline a
        # drain that never ends is killed (a wedge, not a drain)
        self.retiring = False
        self.retire_deadline = 0.0


def _launch_replica(rep: _Replica, cmd: Sequence[str], nprocs: int,
                    log_dir: str, base_env: Dict[str, str]) -> None:
    log_path = os.path.join(
        log_dir, f"elastic_g{rep.generation}_rank{rep.rank}.log")
    log_file = open(log_path, "ab")
    try:
        rep.proc = subprocess.Popen(
            list(cmd),
            env=worker_env(base_env, rep.rank, nprocs, _free_port(),
                           rep.generation),
            stdout=log_file, stderr=subprocess.STDOUT,
            start_new_session=True)
    finally:
        log_file.close()
    rep.launched_at = time.monotonic()
    rep.ping_failures = 0


def supervise_fleet(nprocs: int, cmd: Sequence[str],
                    max_restarts: int = 3,
                    log_dir: str = ".", grace: float = 5.0,
                    env: Optional[Dict[str, str]] = None,
                    max_restarts_per_window: int = 0,
                    restart_window_sec: float = 300.0,
                    health_port: Optional[int] = None,
                    health_interval: float = 2.0,
                    health_fails: int = 3,
                    health_grace: float = 60.0,
                    health_timeout: float = 5.0,
                    metrics_port: Optional[int] = None,
                    scrape_interval: float = 0.0,
                    min_replicas: Optional[int] = None,
                    max_replicas: Optional[int] = None,
                    autoscale_up_qps: float = 0.0,
                    autoscale_down_qps: float = 0.0,
                    autoscale_up_p99_ms: float = 0.0,
                    autoscale_up_cooldown_sec: float = 5.0,
                    autoscale_down_cooldown_sec: float = 15.0,
                    retire_grace_sec: float = 30.0,
                    publish_dir=None,
                    rollback_grace_sec: float = 6.0) -> int:
    """Supervise ``nprocs`` INDEPENDENT replicas (the serving shape):
    a dead or health-check-failing replica is relaunched alone, on a
    per-replica jittered backoff, while the rest keep serving.

    ``health_port``: base port of the replicas' JSON protocol — rank
    ``r`` is pinged on ``health_port + r`` every ``health_interval``
    seconds once its ``health_grace`` startup window (model load +
    compile) has passed; ``health_fails`` consecutive failures mean
    alive-but-wedged, and the replica is killed and relaunched. None
    disables pinging (exit-code supervision only).

    With ``max_replicas`` set (plus ``scrape_interval`` and the QPS /
    p99 thresholds) the fleet AUTOSCALES: the scrape thread feeds an
    :class:`~.autoscale.AutoscalePolicy` and the supervision loop
    spawns fresh replicas on its "up" decisions (``nprocs`` is the
    starting size; ``min_replicas`` defaults to it) and retires the
    highest-rank replica on "down" — a SIGTERM drain, so a scaled-down
    replica answers its in-flight and backlogged requests before
    exiting (``retire_grace_sec`` caps a drain that never ends).
    Scale-ups do not draw from the restart budget, but a fleet whose
    budget is spent (a crash loop) refuses to grow.

    With ``publish_dir`` also set, a :class:`~.autoscale.RollbackGuard`
    watches the newest publication in that store: one that no replica
    adopts while swap failures mount (every canary gate refused it),
    or whose adopter is evicted by post-swap health checks, is rolled
    back to the last-known-good manifest via
    :func:`~.publisher.rollback_publication`
    (``rollback_grace_sec`` is how long the fleet gets to adopt it
    first).

    Returns 0 once every replica has exited cleanly (a graceful
    ``shutdown``), or the last failing exit code when the restart
    budget (shared across the fleet) is exhausted.
    """
    if nprocs < 1:
        raise ValueError("nprocs must be >= 1")
    if not cmd:
        raise ValueError("no worker command given (pass it after --)")
    base_env = dict(os.environ if env is None else env)
    os.makedirs(log_dir, exist_ok=True)
    budget = RestartBudget(max_restarts, max_restarts_per_window,
                           restart_window_sec)
    policy = None
    if max_replicas and int(max_replicas) > 0 \
            and health_port is not None and scrape_interval > 0 \
            and (autoscale_up_qps > 0 or autoscale_up_p99_ms > 0):
        from .autoscale import AutoscalePolicy
        policy = AutoscalePolicy(
            nprocs if min_replicas is None else min_replicas,
            max_replicas,
            up_qps=autoscale_up_qps, down_qps=autoscale_down_qps,
            up_p99_ms=autoscale_up_p99_ms,
            up_cooldown_sec=autoscale_up_cooldown_sec,
            down_cooldown_sec=autoscale_down_cooldown_sec)
    guard = None
    publish_store = None
    if publish_dir and health_port is not None and scrape_interval > 0:
        from .autoscale import RollbackGuard
        from .store import store_for
        publish_store = store_for(publish_dir)
        guard = RollbackGuard(
            refuse_sec=rollback_grace_sec,
            adopt_sec=max(1.0, 2.0 * scrape_interval))
    # fleet metrics plane (docs/OBSERVABILITY.md): the supervisor's
    # own jax-free /metrics at the base port, replica endpoints at
    # base+1+rank via the exported env var; the scrape thread polls
    # each live replica's NON-consuming {"cmd": "metrics"} verb on the
    # scrape cadence into {"event": "fleet"} records (per-replica QPS
    # / p99 / shed / restarts — the autoscaling signal; {"cmd":
    # "stats"} would consume the daemon's own rate window, see
    # _replica_metrics_row)
    if metrics_port:
        from ..obs.export import ensure_metrics_server
        ensure_metrics_server(
            metrics_port,
            extra_families=policy.metrics_families if policy else None)
        base_env["LIGHTGBM_TPU_METRICS_PORT"] = str(metrics_port + 1)
    telem = _FleetTelemetry(
        fleet_telemetry_path(base_env) if scrape_interval > 0
        else None)
    fleet = [_Replica(rank) for rank in range(nprocs)]
    last_rc = 1
    next_ping = time.monotonic() + max(0.0, health_grace)
    next_store_poll = time.monotonic()
    stop_scrape = threading.Event()

    def _scrape_loop() -> None:
        # the SCRAPE THREAD: a wedged replica's fetch timeout lands
        # here, never in the supervision loop; every observation feeds
        # the (lock-guarded) scaling and rollback policies
        while not stop_scrape.wait(max(0.1, scrape_interval)):
            try:
                record = _scrape_fleet(list(fleet), health_port,
                                       health_timeout)
                telem.write(record)
                _drain_spans_into(telem)
                if policy is not None:
                    policy.observe(record["replicas"])
                if guard is not None:
                    guard.observe(record["replicas"])
            except Exception:
                pass             # scraping must never kill the fleet

    scraper = (threading.Thread(target=_scrape_loop, daemon=True,
                                name="fleet-scrape")
               if scrape_interval > 0 else None)

    def _n_active() -> int:
        return sum(1 for rep in fleet
                   if not rep.done and not rep.retiring)

    def _set_active_gauge() -> None:
        try:
            from ..obs.registry import registry
            registry.gauge("fleet_replicas_active").set(_n_active())
        except Exception:
            pass

    def _scale_up(reason: str) -> None:
        if budget.total >= budget.max_restarts:
            log_warning("elastic: autoscale up refused — the restart "
                        "budget is spent; a crash-looping fleet must "
                        "not grow")
            return
        used = {rep.rank for rep in fleet if not rep.done}
        target = next((r for r in range(policy.max_replicas)
                       if r not in used), None)
        if target is None:
            return      # every rank slot is occupied (e.g. draining)
        rep = next((r for r in fleet if r.rank == target), None)
        if rep is None:
            rep = _Replica(target)
            fleet.append(rep)
        else:
            rep.generation += 1          # fresh log file per life
            rep.consecutive_restarts = 0
        rep.done = False
        rep.retiring = False
        rep.relaunch_at = None
        rep.restart_t0 = None
        _launch_replica(rep, cmd, nprocs, log_dir, base_env)
        n_active = _n_active()
        log_info(f"elastic: autoscale up -> {n_active} replicas "
                 f"(spawned rank {target}: {reason})")
        _count("fleet_scale_ups")
        _set_active_gauge()
        telem.write({"event": "autoscale", "action": "up",
                     "rank": target, "replicas": n_active,
                     "reason": reason, "time": time.time()})

    def _scale_down(reason: str, now: float) -> None:
        victims = [rep for rep in fleet
                   if not rep.done and not rep.retiring
                   and rep.relaunch_at is None
                   and rep.proc is not None
                   and rep.proc.poll() is None]
        if not victims:
            return
        rep = max(victims, key=lambda r: r.rank)
        rep.retiring = True
        rep.retire_deadline = now + max(1.0, retire_grace_sec)
        _term_group(rep.proc)
        n_active = _n_active()
        log_info(f"elastic: autoscale down -> {n_active} replicas "
                 f"(draining rank {rep.rank}: {reason})")
        _count("fleet_scale_downs")
        _set_active_gauge()
        telem.write({"event": "autoscale", "action": "down",
                     "rank": rep.rank, "replicas": n_active,
                     "reason": reason, "time": time.time()})

    def _check_rollback() -> None:
        from .publisher import (MANIFEST_SUFFIX, PublishError,
                                latest_manifest_in,
                                rollback_publication)
        try:
            found = latest_manifest_in(publish_store)
        except (OSError, PublishError):
            found = None
        if found is not None:
            guard.note_publication(
                found[0], str(found[1].get("sha256") or ""))
        order = guard.decide()
        if order is None:
            return
        event = {"event": "rollback", "bad_file": order["bad_name"],
                 "bad_sha": order["bad_sha"],
                 "good_file": order["good_name"],
                 "good_sha": order["good_sha"], "time": time.time()}
        log_warning(f"elastic: rolling back publication "
                    f"{order['bad_name']} "
                    f"(sha {str(order['bad_sha'])[:12]}…) — the "
                    "fleet refused or degraded on it")
        try:
            if order["good_name"]:
                new_manifest = rollback_publication(
                    publish_store, order["bad_name"],
                    order["good_name"])
                event["republished"] = new_manifest["file"]
            else:
                # no last-known-good yet: withdrawing the bad
                # publication is all a supervisor can do
                publish_store.delete(order["bad_name"])
                publish_store.delete(
                    order["bad_name"] + MANIFEST_SUFFIX)
                event["republished"] = None
            event["ok"] = True
        except (OSError, PublishError) as e:
            event["ok"] = False
            event["error"] = str(e)
            log_warning(f"elastic: rollback of {order['bad_name']} "
                        f"failed ({e})")
        _count("fleet_rollbacks")
        telem.write(event)

    try:
        for rep in fleet:
            _launch_replica(rep, cmd, nprocs, log_dir, base_env)
        _set_active_gauge()
        if scraper is not None:
            scraper.start()
        while True:
            now = time.monotonic()
            ping_round = health_port is not None and now >= next_ping
            if ping_round:
                next_ping = now + max(0.1, health_interval)
            for rep in list(fleet):
                if rep.done:
                    continue
                if rep.relaunch_at is not None:
                    # backoff pending: relaunch once the per-replica
                    # deadline passes (other replicas keep being
                    # polled/pinged in the meantime)
                    if now >= rep.relaunch_at:
                        rep.relaunch_at = None
                        _launch_replica(rep, cmd, nprocs, log_dir,
                                        base_env)
                        if rep.restart_t0 is not None:
                            t0, rep.restart_t0 = rep.restart_t0, None
                            try:
                                from ..obs.trace import record_span
                                record_span(
                                    "restart/replica", t0,
                                    attrs={"rank": rep.rank,
                                           "generation":
                                               rep.generation})
                            except Exception:
                                pass
                    continue
                if rep.proc is None:
                    continue
                rc = rep.proc.poll()
                needs_restart = False
                if rc is not None:
                    if rep.retiring:
                        # a draining replica's exit ends its life —
                        # never a restart, whatever the code
                        rep.retiring = False
                        rep.done = True
                        if rc == 0:
                            log_info(f"elastic: replica {rep.rank} "
                                     "retired cleanly (drained)")
                        else:
                            log_warning(
                                f"elastic: retiring replica "
                                f"{rep.rank} exited with code {rc} "
                                "during its drain")
                        continue
                    if rc == 0:
                        log_info(f"elastic: replica {rep.rank} exited "
                                 "cleanly")
                        rep.done = True
                        continue
                    last_rc = (128 - rc) if rc < 0 else rc
                    log_warning(f"elastic: replica {rep.rank} exited "
                                f"with code {rc}")
                    needs_restart = True
                elif rep.retiring:
                    # draining: no health pings, no restarts — but a
                    # drain that outlives its deadline is a wedge
                    if now >= rep.retire_deadline:
                        log_warning(
                            f"elastic: replica {rep.rank} did not "
                            f"finish draining within "
                            f"{retire_grace_sec:g}s; killing it")
                        _kill_group(rep.proc)
                        try:
                            rep.proc.wait(timeout=max(1.0, grace))
                        except subprocess.TimeoutExpired:
                            _kill_group(rep.proc)
                        rep.retiring = False
                        rep.done = True
                    continue
                elif ping_round and \
                        now - rep.launched_at >= health_grace:
                    if replica_ping(health_port + rep.rank,
                                    timeout=health_timeout):
                        rep.ping_failures = 0
                        rep.consecutive_restarts = 0
                    else:
                        rep.ping_failures += 1
                        if rep.ping_failures >= max(1, health_fails):
                            log_warning(
                                f"elastic: replica {rep.rank} failed "
                                f"{rep.ping_failures} consecutive "
                                "health checks (alive but wedged); "
                                "killing it for relaunch")
                            if guard is not None:
                                # post-swap health failure: condemn
                                # the publication this replica serves
                                # if it is the one under watch
                                guard.note_eviction(rep.rank)
                            _kill_group(rep.proc)
                            try:
                                rep.proc.wait(timeout=max(1.0, grace))
                            except subprocess.TimeoutExpired:
                                _kill_group(rep.proc)
                            last_rc = 1
                            needs_restart = True
                if not needs_restart:
                    continue
                refusal = budget.admit()
                if refusal is None:
                    # generation bump strips one-shot faults
                    # (worker_env) so an injected serve_kill cannot
                    # re-fire on every relaunch forever
                    rep.generation += 1
                    rep.consecutive_restarts += 1
                    delay = budget.backoff(rep.consecutive_restarts)
                    rep.relaunch_at = now + delay
                    rep.restart_t0 = time.perf_counter()
                    log_info(f"elastic: relaunching replica "
                             f"{rep.rank} (generation "
                             f"{rep.generation}) in {delay:.2f}s")
                else:
                    log_warning(f"elastic: replica {rep.rank} died "
                                f"and {refusal} — stopping the fleet")
                    for other in fleet:
                        if other.proc is not None \
                                and other.proc.poll() is None:
                            _kill_group(other.proc)
                    return last_rc
            if policy is not None:
                decision = policy.decide(_n_active())
                if decision is not None:
                    action, reason = decision
                    if action == "up":
                        _scale_up(reason)
                    else:
                        _scale_down(reason, now)
            if guard is not None and now >= next_store_poll:
                next_store_poll = now + max(0.5, scrape_interval)
                try:
                    _check_rollback()
                except Exception:
                    pass    # rollback must never kill the supervisor
            if all(rep.done for rep in fleet):
                log_info("elastic: every replica exited cleanly")
                if scrape_interval > 0:
                    # final scrape: the restart totals survive into
                    # the stream even when the cadence never fired
                    stop_scrape.set()
                    if scraper is not None:
                        scraper.join(timeout=health_timeout + 2.0)
                    telem.write(_scrape_fleet(fleet, None,
                                              health_timeout))
                    _drain_spans_into(telem)
                return 0
            time.sleep(_POLL_SECONDS)
    except BaseException:          # ctrl-C etc.: never leak replicas
        for rep in fleet:
            if rep.proc is not None and rep.proc.poll() is None:
                _kill_group(rep.proc)
        raise
    finally:
        stop_scrape.set()
        if scraper is not None and scraper.is_alive():
            scraper.join(timeout=2.0)
        telem.close()


_HELP_EPILOG = """\
The worker command runs once per rank with LIGHTGBM_TPU_COORDINATOR /
LIGHTGBM_TPU_NUM_PROCS / LIGHTGBM_TPU_RANK exported; a bare
init_distributed() call inside it joins the world. Export
LIGHTGBM_TPU_CHECKPOINT=<dir> (or pass --checkpoint-dir) so every
restart resumes from the newest snapshot. See docs/RESILIENCE.md
"Distributed failures".

exit codes:
  0  a generation completed cleanly on every rank
  N  the last failing rank's exit code, once restarts are exhausted
     (signal deaths surface shell-style as 128+signum, e.g. 137 for
     SIGKILL)
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu launch",
        usage="python -m lightgbm_tpu launch <nprocs> [options] "
              "-- <worker cmd...>",
        description="Supervised elastic launcher: spawn one training "
                    "process per rank, restart the world from the "
                    "newest checkpoint when a rank dies or a "
                    "collective watchdog aborts.",
        epilog=_HELP_EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("nprocs", type=int, help="number of ranks to spawn")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="world restarts before giving up (default 3)")
    p.add_argument("--max-restarts-per-window", type=int, default=0,
                   help="sliding-window restart cap: give up when this "
                        "many restarts land within --restart-window "
                        "seconds (crash-loop brake; 0 = disabled)")
    p.add_argument("--restart-window", type=float, default=300.0,
                   help="width in seconds of the sliding restart "
                        "window (default 300)")
    p.add_argument("--health-port", type=int, default=None,
                   help="FLEET MODE: supervise ranks as independent "
                        "replicas (restart only the dead one) and "
                        "health-check rank r via the serve daemon's "
                        "{\"cmd\": \"ping\"} on this port + r")
    p.add_argument("--health-interval", type=float, default=2.0,
                   help="seconds between health pings (fleet mode)")
    p.add_argument("--health-fails", type=int, default=3,
                   help="consecutive ping failures before a replica "
                        "is declared wedged and relaunched")
    p.add_argument("--health-grace", type=float, default=60.0,
                   help="startup window in seconds during which a "
                        "(re)launched replica is not pinged (model "
                        "load + compile)")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="autoscaling floor (fleet mode; default: "
                        "nprocs)")
    p.add_argument("--max-replicas", type=int, default=0,
                   help="autoscaling ceiling (fleet mode): with this "
                        "set (plus --scrape-interval and an up "
                        "threshold) the supervisor spawns replicas "
                        "under load and SIGTERM-drains the highest "
                        "rank when traffic subsides (0 = fixed fleet)")
    p.add_argument("--autoscale-up-qps", type=float, default=0.0,
                   help="scale up when fleet-total QPS exceeds this "
                        "per active replica (0 = no QPS trigger)")
    p.add_argument("--autoscale-down-qps", type=float, default=0.0,
                   help="scale down when fleet-total QPS would still "
                        "clear this per replica with one replica "
                        "fewer; keep it below --autoscale-up-qps for "
                        "hysteresis (0 = never scale down)")
    p.add_argument("--autoscale-up-p99-ms", type=float, default=0.0,
                   help="scale up when any replica's p99 latency "
                        "exceeds this many ms (0 = no latency "
                        "trigger)")
    p.add_argument("--autoscale-up-cooldown", type=float, default=5.0,
                   help="seconds after any scaling action before the "
                        "next scale-up (default 5)")
    p.add_argument("--autoscale-down-cooldown", type=float,
                   default=15.0,
                   help="seconds after any scaling action before the "
                        "next scale-down (default 15)")
    p.add_argument("--retire-grace", type=float, default=30.0,
                   help="seconds a scaled-down replica gets to finish "
                        "its SIGTERM drain before being killed "
                        "(default 30)")
    p.add_argument("--publish-dir", default=None,
                   help="publication store target the fleet swaps "
                        "from (a directory or mem:// spec): enables "
                        "the rollback guard — a publication the "
                        "fleet's canary gates refuse, or whose "
                        "adopter fails post-swap health checks, is "
                        "rolled back to last-known-good")
    p.add_argument("--rollback-grace", type=float, default=6.0,
                   help="seconds the fleet gets to adopt a new "
                        "publication before mounting swap failures "
                        "condemn it (default 6)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="fleet metrics plane (docs/OBSERVABILITY.md): "
                        "the supervisor serves its own jax-free "
                        "OpenMetrics /metrics at this port and exports "
                        "LIGHTGBM_TPU_METRICS_PORT=<port+1> so worker "
                        "rank r binds port+1+r (0 = disabled)")
    p.add_argument("--scrape-interval", type=float, default=0.0,
                   help="seconds between fleet scrapes written as "
                        "{\"event\": \"fleet\"} records to "
                        "$LIGHTGBM_TPU_TELEMETRY.fleet: per-replica "
                        "QPS/p99/shed/restarts in fleet mode (via "
                        "the replicas' {\"cmd\": \"metrics\"} verb), "
                        "per-rank iteration skew in world mode — "
                        "world mode reads the worker /metrics "
                        "endpoints, so it also needs --metrics-port "
                        "(0 = disabled)")
    p.add_argument("--port", type=int, default=0,
                   help="fixed coordinator port (default: a fresh free "
                        "port per generation)")
    p.add_argument("--log-dir", default=".",
                   help="directory for per-rank worker logs "
                        "(default: .)")
    p.add_argument("--grace", type=float, default=5.0,
                   help="seconds to let surviving ranks exit on their "
                        "own before killing them (default 5)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="export LIGHTGBM_TPU_CHECKPOINT=<dir> to the "
                        "workers (auto-checkpoint + auto-resume)")
    # NOTE: the worker command is NOT an argparse positional — a
    # REMAINDER positional swallows the supervisor's own options, so
    # main() splits on the `--` separator before parsing
    return p


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # split on the `--` separator OURSELVES: argparse's REMAINDER is
    # greedy and would swallow the supervisor's own options into the
    # worker command
    if "--" in argv:
        split = argv.index("--")
        head, cmd = argv[:split], argv[split + 1:]
    else:
        head, cmd = argv, []
    args = build_parser().parse_args(head)
    if not cmd:
        print("launch: no worker command given (usage: launch <nprocs> "
              "-- <cmd...>)", file=sys.stderr)
        return 2
    env = dict(os.environ)
    if args.checkpoint_dir:
        env["LIGHTGBM_TPU_CHECKPOINT"] = args.checkpoint_dir
    try:
        if args.health_port is not None:
            return supervise_fleet(
                args.nprocs, cmd, max_restarts=args.max_restarts,
                log_dir=args.log_dir, grace=args.grace, env=env,
                max_restarts_per_window=args.max_restarts_per_window,
                restart_window_sec=args.restart_window,
                health_port=args.health_port,
                health_interval=args.health_interval,
                health_fails=args.health_fails,
                health_grace=args.health_grace,
                metrics_port=args.metrics_port or None,
                scrape_interval=args.scrape_interval,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas or None,
                autoscale_up_qps=args.autoscale_up_qps,
                autoscale_down_qps=args.autoscale_down_qps,
                autoscale_up_p99_ms=args.autoscale_up_p99_ms,
                autoscale_up_cooldown_sec=args.autoscale_up_cooldown,
                autoscale_down_cooldown_sec=args.autoscale_down_cooldown,
                retire_grace_sec=args.retire_grace,
                publish_dir=args.publish_dir,
                rollback_grace_sec=args.rollback_grace)
        return supervise(args.nprocs, cmd,
                         max_restarts=args.max_restarts,
                         port=args.port or None, log_dir=args.log_dir,
                         grace=args.grace, env=env,
                         max_restarts_per_window=args.max_restarts_per_window,
                         restart_window_sec=args.restart_window,
                         metrics_port=args.metrics_port or None,
                         scrape_interval=args.scrape_interval)
    except KeyboardInterrupt:
        print("launch: interrupted", file=sys.stderr)
        return 130
