"""tpulint: JAX/TPU-aware static analysis for the boosting hot path.

The regression classes that hurt this codebase most are invisible at
runtime until a profile is taken: eager ``lax`` loops dispatching
op-by-op through the device tunnel (the PROFILE.md 530 ms/iter class),
host-device syncs hiding inside per-iteration code, recompile storms
from unstable trace signatures, use-after-donation, and SPMD
collective-order divergence. This package proves the corresponding
invariants at review time, from the source alone:

- :mod:`~lightgbm_tpu.analysis.astscan` parses every module of the
  package (pure ``ast`` — importing this package never imports jax),
- :mod:`~lightgbm_tpu.analysis.callgraph` builds a cross-module call
  graph and computes **jit-reachability**: the set of functions that
  are only ever entered through a ``jax.jit`` / ``pjit`` / ``shard_map``
  wrapper. This replaces the hand-maintained ``KNOWN_JITTED`` allowlist
  the old ``tests/test_hot_path_lint.py`` carried,
- :mod:`~lightgbm_tpu.analysis.cfg` builds per-function control-flow
  graphs and solves guard-pin and lock-held dataflow over them;
  :mod:`~lightgbm_tpu.analysis.dataflow` adds rank taint, the
  thread-side closure, and float64-producer classification,
- :mod:`~lightgbm_tpu.analysis.rules` runs the pluggable rule set
  (statement-level TPL001-TPL006 plus the CFG-based TPL007-TPL010 from
  :mod:`~lightgbm_tpu.analysis.rules_flow`; see
  docs/STATIC_ANALYSIS.md),
- :mod:`~lightgbm_tpu.analysis.baseline` matches findings against the
  checked-in accepted-findings file (tools/tpulint_baseline.txt),
- :mod:`~lightgbm_tpu.analysis.ircheck` (``lint --ir`` only — the one
  lint mode that imports jax, CPU lowering only, never executing)
  lowers every ``register_jit`` entry point at its declared
  signatures and checks the IR contracts TPL011-TPL014: dtype
  contract, collective bytes vs the committed tools/ir_budgets.json,
  donation honored in the lowered program, recompile surface
  declared.

Entry points: ``python -m lightgbm_tpu lint`` (see
:mod:`~lightgbm_tpu.analysis.cli`), :func:`run_lint` for library use,
and ``tests/test_static_analysis.py`` which gates tier-1 on a clean
tree.
"""

from .callgraph import CallGraph, build_callgraph
from .engine import LintResult, default_scope, package_root, run_lint
from .rules import ALL_RULES, IR_RULES, Finding, rule_by_id

__all__ = [
    "run_lint", "LintResult", "build_callgraph", "CallGraph",
    "Finding", "ALL_RULES", "IR_RULES", "rule_by_id", "default_scope",
    "package_root",
]
