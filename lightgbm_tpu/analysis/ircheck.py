"""IR-contract lint (``python -m lightgbm_tpu lint --ir``).

The AST rules (TPL001-TPL010) see source idioms; this pass sees what
XLA will actually be asked to run. It walks the ``register_jit``
registry, lowers every entry point at the representative abstract
signatures declared in :data:`build_specs`'s per-entry table (seeded
from ``obs/recorder.py``'s ``ENTRY_PHASES`` entries plus the shapes
the tests/benches drive), and enforces four IR rule families:

- **TPL011 dtype contract** — trace under ``jax.experimental
  .enable_x64`` and flag any *strong* float64 aval in the jaxpr
  (including nested jaxprs). Weak-typed rank-0 literal plumbing
  (``jnp.where(m, x, 0.0)`` routing a python float through a scalar
  ``convert_element_type``) is exempt: it lowers to f32 compute and
  pinning every literal would be noise. A ``np.float64`` constant or
  an ``arange``-promoted chain is strong f64 and fails.
- **TPL012 collective budget** — :func:`~lightgbm_tpu.parallel.comms
  .collective_summary` of each entry's jaxpr diffed against the
  committed ``tools/ir_budgets.json`` (justification-required, same
  discipline as ``tools/tpulint_baseline.txt``): the int8 hist wire
  and the reduce-scatter post-reduction cut become
  regressions-by-construction.
- **TPL013 donation honored** — entries whose budget file declares
  ``donate_argnums`` are lowered (``fn.lower``) and the StableHLO must
  carry one ``tf.aliasing_output`` input marker per donated leaf
  (guards the fused scan's score/bag carries).
  ``LIGHTGBM_TPU_FORCE_DONATE=1`` keeps the donation declaration on
  CPU so a CPU-only CI host lowers the same contract the TPU runs.
- **TPL014 recompile surface** — every ``register_jit`` site must
  declare ``max_signatures`` (AST-scanned, so an undeclared entry
  fails review before it ever runs), and the ``serve/predict``
  declaration must cover the pow2 bucket ladder.

Lowering only — nothing is ever executed, no TPU is required, and this
module is imported ONLY under ``--ir`` (the default ``lint`` path
stays jax-free; tests/test_static_analysis.py proves it in a
subprocess). Findings reuse the stable-fid/baseline/SARIF machinery.
"""

from __future__ import annotations

import ast
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .baseline import BaselineEntry
from .rules import Finding

__all__ = ["run_ircheck", "IRCheckResult", "IRSpec", "build_specs",
           "default_budgets_path", "load_budgets", "f64_findings",
           "donation_findings", "budget_findings",
           "register_jit_sites", "recompile_surface_findings",
           "IR_RULE_IDS"]

IR_RULE_IDS = ("TPL011", "TPL012", "TPL013", "TPL014")

#: budget keys TPL012 compares (measured <= committed); any other key
#: in a budget entry (besides justification/donate_argnums) is a typo
#: and reported as a finding rather than silently ignored
_BUDGET_METRICS = ("wire_bytes", "post_reduction_bytes",
                   "n_collectives")
_BUDGET_KEYS = _BUDGET_METRICS + ("justification", "donate_argnums")


def default_budgets_path(root: Optional[str] = None) -> str:
    from .engine import package_root
    root = root or package_root()
    return os.path.join(os.path.dirname(root), "tools",
                        "ir_budgets.json")


def load_budgets(path: str):
    """Parse ``tools/ir_budgets.json``.

    Returns ``(entries, unjustified)``: the committed budget dict and
    the :class:`BaselineEntry` list for entries missing a real
    justification (TODO placeholders count as missing — the same
    discipline ``tools/tpulint_baseline.txt`` enforces)."""
    if not os.path.exists(path):
        return {}, []
    with open(path, encoding="utf-8") as fh:
        raw = json.load(fh)
    entries = raw.get("entries", {})
    unjustified: List[BaselineEntry] = []
    for i, (key, val) in enumerate(sorted(entries.items()), start=1):
        just = str(val.get("justification", "")).strip()
        if not just or just.upper().startswith("TODO"):
            unjustified.append(BaselineEntry(
                fid=f"ir_budgets.json:{key}", justification="",
                lineno=i))
    return entries, unjustified


def ensure_cpu_jax():
    """Import jax pinned to CPU with an 8-way forced host platform
    (the sharded specs need a D=8 mesh) and the donation contract kept
    on CPU. Must run before anything imports jax in this process; the
    CLI routes ``--ir`` here before touching the package."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("LIGHTGBM_TPU_FORCE_DONATE", "1")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


# ---------------------------------------------------------------------
# the per-entry signature table
# ---------------------------------------------------------------------

@dataclass
class IRSpec:
    """One lowering of one registered entry point.

    ``entry`` is ``<register_jit name>@<variant>`` — the budget-file
    key. ``build`` returns ``(fn, args, static_argnums, jit_fn)``:
    ``fn`` is traced with ``jax.make_jaxpr`` (TPL011/TPL012), ``jit_fn``
    (when not None) is the registered jitted wrapper whose ``.lower``
    text TPL013 inspects for aliasing markers."""

    entry: str
    relpath: str         # anchor for entry-level findings
    func: str
    signature: str       # human-readable declared signature
    build: Callable[[dict], tuple]
    donate: Tuple[int, ...] = ()
    lineno: int = 1      # entry-level findings anchor here


def _mk_engine(ctx: dict):
    """Tiny binary engine shared by the fused-step/scan specs —
    constructed (host binning only), never trained."""
    if "engine" in ctx:
        return ctx["engine"]
    import numpy as np
    import lightgbm_tpu as lgb
    rs = np.random.RandomState(0)
    X = rs.randn(256, 8)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.Booster(dict(objective="binary", num_leaves=15,
                           max_bin=63, verbosity=-1),
                      lgb.Dataset(X, label=y))
    ctx["booster"] = bst          # keep alive: engine holds weakrefs
    ctx["engine"] = bst._engine
    return ctx["engine"]


def _engine_scan_args(eng, jnp):
    return (eng.score, jnp.ones((eng.n,), jnp.float32),
            jnp.asarray(0, jnp.int32), jnp.asarray(0.1, jnp.float32),
            jnp.ones((eng.F,), jnp.bool_), eng.bins_T,
            eng.feat_num_bins, eng.feat_nan_bin, eng.label, eng.weight,
            eng.monotone, eng.feat_is_cat, eng.interaction_groups,
            eng.forced, eng._bundle_dev)


def build_specs(jax) -> List[IRSpec]:
    """The signature table: every ``register_jit`` entry point at the
    shapes the tests/benches drive. ``parallel/dp_grow@wide-sharded``
    is the Allstate-wide acceptance shape (F=4228, B=255, D=8,
    ``split_search=sharded``) whose reduce-scatter payload bound
    ``tools/ir_budgets.json`` pins."""
    import jax.numpy as jnp

    def sds(sh, dt):
        return jax.ShapeDtypeStruct(sh, dt)

    def grow_args(F, n):
        return (sds((F, n), jnp.uint8), sds((n,), jnp.float32),
                sds((n,), jnp.float32), sds((n,), jnp.float32),
                sds((F,), jnp.bool_), sds((F,), jnp.int32),
                sds((F,), jnp.int32))

    def b_grow(ctx):
        from ..ops.grow import GrowConfig, grow_tree
        from ..ops.split import SplitParams
        cfg = GrowConfig(num_leaves=31, num_bins=63,
                         split=SplitParams(min_data_in_leaf=5.0),
                         hist_method="scatter")
        fn = getattr(grow_tree, "unwrapped", grow_tree)
        return fn, (cfg,) + grow_args(8, 512), (0,), None

    def _mesh(ctx):
        if "mesh" not in ctx:
            from ..parallel.mesh import make_mesh
            ctx["mesh"] = make_mesh(8, devices=jax.devices("cpu"))
        return ctx["mesh"]

    def b_dp_wide(ctx):
        from ..ops.grow import GrowConfig
        from ..ops.split import SplitParams
        from ..parallel.data_parallel import make_dp_grow_fn
        cfg = GrowConfig(
            num_leaves=7, num_bins=255,
            split=SplitParams(min_data_in_leaf=1.0,
                              min_sum_hessian_in_leaf=1e-6),
            hist_method="scatter", grower="masked",
            split_search="sharded", parallel_mode="data")
        fn = make_dp_grow_fn(cfg, _mesh(ctx))
        return fn, grow_args(4228, 64 * 8), (), None

    def b_dp_narrow(ctx):
        from ..ops.grow import GrowConfig
        from ..ops.split import SplitParams
        from ..parallel.data_parallel import make_dp_grow_fn
        cfg = GrowConfig(
            num_leaves=31, num_bins=63,
            split=SplitParams(min_data_in_leaf=1.0,
                              min_sum_hessian_in_leaf=1e-6),
            hist_method="scatter", parallel_mode="data")
        fn = make_dp_grow_fn(cfg, _mesh(ctx))
        return fn, grow_args(8, 64 * 8), (), None

    def b_fused_scan(ctx):
        eng = _mk_engine(ctx)
        jit_fn = eng._get_scan_fn(4, False)
        fn = getattr(jit_fn, "unwrapped", jit_fn)
        return fn, _engine_scan_args(eng, jnp), (), jit_fn

    def b_fused_iter(ctx):
        eng = _mk_engine(ctx)
        jit_fn = eng._get_fused_fn()
        fn = getattr(jit_fn, "unwrapped", jit_fn)
        a = _engine_scan_args(eng, jnp)
        # step takes (score, it, shrink, row_w, ...) — no bag carry
        args = (a[0], a[2], a[3], jnp.ones((eng.n,), jnp.float32)) \
            + a[4:]
        return fn, args, (), jit_fn

    def _stacked(T, L, W):
        from ..ops.predict import StackedTrees
        return StackedTrees(
            split_feature=sds((T, L - 1), jnp.int32),
            threshold=sds((T, L - 1), jnp.float32),
            threshold_bin=sds((T, L - 1), jnp.int32),
            default_left=sds((T, L - 1), jnp.bool_),
            missing_type=sds((T, L - 1), jnp.int8),
            is_categorical=sds((T, L - 1), jnp.bool_),
            cat_bitset=sds((T, L - 1, W), jnp.uint32),
            left_child=sds((T, L - 1), jnp.int32),
            right_child=sds((T, L - 1), jnp.int32),
            leaf_value=sds((T, L), jnp.float32))

    def b_serve(ctx):
        from ..serve.compile import _predict_scores_padded, bucket_rows
        fn = getattr(_predict_scores_padded, "unwrapped",
                     _predict_scores_padded)
        return fn, (_stacked(8, 16, 1),
                    sds((bucket_rows(10), 8), jnp.float32), 1), (2,), \
            None

    def b_forest_leaves(ctx):
        from ..prediction import _forest_leaves
        fn = getattr(_forest_leaves, "unwrapped", _forest_leaves)
        return fn, (_stacked(8, 16, 1), sds((16, 8), jnp.float32)), \
            (), None

    def b_lambdarank(ctx):
        from ..ranking import _lambdarank_grads
        fn = getattr(_lambdarank_grads, "unwrapped", _lambdarank_grads)
        args = (sds((128,), jnp.float32), sds((8, 16), jnp.int32),
                sds((8, 16), jnp.bool_), sds((128,), jnp.float32),
                sds((128,), jnp.float32), 1.0, 30, True, 8)
        return fn, args, (5, 6, 7, 8), None

    def _tree_args(L):
        return (sds((L - 1,), jnp.int32), sds((L - 1,), jnp.int32),
                sds((L - 1,), jnp.bool_), sds((L - 1,), jnp.int32),
                sds((L - 1,), jnp.int32), sds((L,), jnp.float32),
                sds((8,), jnp.int32), sds((8, 256), jnp.uint8))

    def b_tree_values(ctx):
        from ..models.gbdt import _tree_values_binned
        fn = getattr(_tree_values_binned, "unwrapped",
                     _tree_values_binned)
        return fn, _tree_args(15), (), None

    def b_tree_leaves(ctx):
        from ..models.gbdt import _tree_leaves_binned
        fn = getattr(_tree_leaves_binned, "unwrapped",
                     _tree_leaves_binned)
        a = _tree_args(15)
        return fn, a[:5] + a[6:], (), None

    def b_linear_eval(ctx):
        from ..models.gbdt import _linear_eval
        fn = getattr(_linear_eval, "unwrapped", _linear_eval)
        L, km = 15, 4
        args = (sds((L,), jnp.float32), sds((L, km), jnp.float32),
                sds((L, km), jnp.int32), sds((L,), jnp.int32),
                sds((L,), jnp.float32), sds((16, 8), jnp.float32),
                sds((16,), jnp.int32))
        return fn, args, (), None

    return [
        IRSpec("ops/grow_tree@narrow", "ops/grow.py", "grow_tree_impl",
               "F=8 n=512 B=63 leaves=31 scatter", b_grow),
        IRSpec("parallel/dp_grow@wide-sharded",
               "parallel/data_parallel.py", "make_dp_grow_fn",
               "F=4228 n=512 B=255 D=8 masked sharded", b_dp_wide),
        IRSpec("parallel/dp_grow@narrow-psum",
               "parallel/data_parallel.py", "make_dp_grow_fn",
               "F=8 n=512 B=63 D=8 gathered psum", b_dp_narrow),
        IRSpec("gbdt/fused_scan@W4", "models/gbdt.py",
               "GBDTBooster._get_scan_fn",
               "binary n=256 F=8 window=4 no-bag", b_fused_scan,
               donate=(0, 1)),
        IRSpec("gbdt/fused_iter@default", "models/gbdt.py",
               "GBDTBooster._get_fused_fn",
               "binary n=256 F=8", b_fused_iter, donate=(0,)),
        IRSpec("serve/predict@bucket16", "serve/compile.py",
               "_predict_scores_padded", "T=8 L=16 rows=16 K=1",
               b_serve),
        IRSpec("prediction/forest_leaves@default", "prediction.py",
               "_forest_leaves", "T=8 L=16 rows=16", b_forest_leaves),
        IRSpec("ranking/lambdarank_grads@default", "ranking.py",
               "_lambdarank_grads", "n=128 nq=8 Q=16 trunc=30",
               b_lambdarank),
        IRSpec("gbdt/tree_values_binned@default", "models/gbdt.py",
               "_tree_values_binned", "L=15 F=8 n=256", b_tree_values),
        IRSpec("gbdt/tree_leaves_binned@default", "models/gbdt.py",
               "_tree_leaves_binned", "L=15 F=8 n=256", b_tree_leaves),
        IRSpec("gbdt/linear_eval@default", "models/gbdt.py",
               "_linear_eval", "L=15 km=4 rows=16", b_linear_eval),
    ]


# ---------------------------------------------------------------------
# TPL011: dtype contract
# ---------------------------------------------------------------------

_JAXPR_WRAPPERS = frozenset({"pjit", "scan", "while", "cond",
                             "closed_call", "custom_jvp_call",
                             "custom_vjp_call", "remat", "checkpoint"})


def _strong_f64(aval) -> bool:
    return (getattr(aval, "dtype", None) is not None
            and str(aval.dtype) == "float64"
            and not getattr(aval, "weak_type", False))


def _walk_jaxprs(jaxpr):
    """Yield every eqn of ``jaxpr`` and its nested sub-jaxprs."""
    import jax.extend.core as jcore
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            stack = [val]
            while stack:
                v = stack.pop()
                if isinstance(v, jcore.ClosedJaxpr):
                    yield from _walk_jaxprs(v.jaxpr)
                elif isinstance(v, jcore.Jaxpr):
                    yield from _walk_jaxprs(v)
                elif isinstance(v, (tuple, list)):
                    stack.extend(v)


def _site_of(eqn, fallback, marker: str = "/lightgbm_tpu/"):
    """(relpath, lineno, func) of the user frame that traced ``eqn``
    — the first frame under ``marker`` (the analyzed tree)."""
    try:
        from jax._src import source_info_util
        for fr in source_info_util.user_frames(eqn.source_info):
            fname = fr.file_name.replace(os.sep, "/")
            if marker in fname:
                rel = fname.rsplit(marker, 1)[1]
                if rel.startswith("analysis/"):
                    continue
                return rel, int(fr.start_line or 0), fr.function_name
    except Exception:
        pass
    return fallback


def f64_findings(closed, spec_relpath: str, spec_func: str,
                 entry: str,
                 marker: str = "/lightgbm_tpu/") -> List[Finding]:
    """TPL011 findings for one traced program: one finding per
    (site, primitive-set) carrying strong float64."""
    sites: Dict[Tuple[str, int, str], set] = {}
    for eqn in _walk_jaxprs(closed.jaxpr):
        if eqn.primitive.name in _JAXPR_WRAPPERS:
            continue
        if any(_strong_f64(getattr(v, "aval", None))
               for v in list(eqn.invars) + list(eqn.outvars)):
            key = _site_of(eqn, (spec_relpath, 1, spec_func),
                           marker=marker)
            sites.setdefault(key, set()).add(eqn.primitive.name)
    out = []
    for (rel, line, func), prims in sorted(sites.items()):
        out.append(Finding(
            rule="TPL011", relpath=rel, lineno=line, col=0, func=func,
            symbol="ir-f64",
            message=(f"strong float64 in lowered IR of {entry} "
                     f"({', '.join(sorted(prims))}): pin the dtype — "
                     f"an np.float64 constant or a default-int/float "
                     f"promotion widens the traced program 2x on the "
                     f"wire and falls off the TPU fast path")))
    return out


# ---------------------------------------------------------------------
# TPL012: collective budget
# ---------------------------------------------------------------------

def budget_findings(summary: dict, budget: Optional[dict],
                    spec: "IRSpec") -> List[Finding]:
    """Diff one entry's measured collective summary against its
    committed budget entry (None = no entry committed)."""
    out = []

    def f(message):
        out.append(Finding(
            rule="TPL012", relpath=spec.relpath, lineno=spec.lineno,
            col=0,
            func=spec.func, symbol="ir-budget", message=message))

    if summary["n_collectives"] == 0 and budget is None:
        return out
    if budget is None:
        f(f"{spec.entry} lowers {summary['n_collectives']} "
          f"collective(s) ({', '.join(summary['prims'])}; "
          f"wire {summary['wire_bytes']} B, post-reduction "
          f"{summary['post_reduction_bytes']} B) but has no committed "
          f"budget in tools/ir_budgets.json — add a justified entry")
        return out
    for key in sorted(budget):
        if key not in _BUDGET_KEYS:
            f(f"{spec.entry}: unknown budget key {key!r} in "
              f"tools/ir_budgets.json (have: "
              f"{', '.join(_BUDGET_KEYS)})")
    for metric in _BUDGET_METRICS:
        if metric not in budget:
            continue
        allowed = int(budget[metric])
        measured = int(summary[metric])
        if measured > allowed:
            f(f"{spec.entry}: {metric} {measured} exceeds the "
              f"committed budget {allowed} "
              f"({', '.join(summary['prims']) or 'no collectives'}) — "
              f"either the regression is real (fix it) or re-lower "
              f"and re-justify the budget "
              f"(docs/STATIC_ANALYSIS.md#tpl012)")
    return out


# ---------------------------------------------------------------------
# TPL013: donation honored
# ---------------------------------------------------------------------

def donation_marker_count(lowered_text: str) -> int:
    """Input->output aliasing markers in a lowered module's StableHLO
    (one ``tf.aliasing_output`` input attribute per donated leaf)."""
    return lowered_text.count("tf.aliasing_output")


def donation_findings(jit_fn, args, expected: Sequence[int],
                      spec: "IRSpec") -> List[Finding]:
    lowered = jit_fn.lower(*args)
    n = donation_marker_count(lowered.as_text())
    if n >= len(expected):
        return []
    return [Finding(
        rule="TPL013", relpath=spec.relpath, lineno=spec.lineno, col=0,
        func=spec.func, symbol="ir-donation",
        message=(f"{spec.entry}: donate_argnums "
                 f"{tuple(expected)} declared but the lowered program "
                 f"carries {n}/{len(expected)} tf.aliasing_output "
                 f"markers — the carry buffers will be copied, not "
                 f"reused (doubles the score/bag HBM footprint per "
                 f"fused step)"))]


# ---------------------------------------------------------------------
# TPL014: recompile surface
# ---------------------------------------------------------------------

def register_jit_sites(pkg_root: str) -> List[dict]:
    """AST scan for ``register_jit(...)`` call sites in the package:
    ``{"relpath", "lineno", "func", "name", "declared"}`` per site."""
    sites = []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__", "analysis")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, pkg_root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=rel)
            except SyntaxError:
                continue
            funcs = []
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    funcs.append((node.lineno,
                                  getattr(node, "end_lineno",
                                          node.lineno), node.name))
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = node.func
                name = callee.attr if isinstance(callee, ast.Attribute) \
                    else getattr(callee, "id", "")
                if name != "register_jit":
                    continue
                entry = ""
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    entry = node.args[0].value
                declared = any(k.arg == "max_signatures"
                               for k in node.keywords)
                enclosing = "<module>"
                best = None
                for lo, hi, fn_name in funcs:
                    if lo <= node.lineno <= hi and \
                            (best is None or hi - lo < best):
                        enclosing, best = fn_name, hi - lo
                sites.append({"relpath": rel, "lineno": node.lineno,
                              "func": enclosing, "name": entry,
                              "declared": declared})
    return sites


def recompile_surface_findings(pkg_root: str) -> List[Finding]:
    out = []
    for site in register_jit_sites(pkg_root):
        if site["declared"]:
            continue
        out.append(Finding(
            rule="TPL014", relpath=site["relpath"],
            lineno=site["lineno"], col=0, func=site["func"],
            symbol="ir-sigs",
            message=(f"register_jit({site['name']!r}) declares no "
                     f"max_signatures — every entry point must commit "
                     f"its recompile surface so telemetry "
                     f"(jit_cache_sizes) and lint can flag a "
                     f"recompile storm against it")))
    # the serve ladder: the declaration must cover every pow2 bucket
    try:
        from ..obs import jit_declarations
        from ..serve.compile import n_serve_buckets
        declared = jit_declarations().get("serve/predict")
        buckets = n_serve_buckets()
        if declared is not None and declared < buckets:
            out.append(Finding(
                rule="TPL014", relpath="serve/compile.py", lineno=1,
                col=0, func="_predict_scores_padded", symbol="ir-sigs",
                message=(f"serve/predict declares max_signatures="
                         f"{declared} but bucket_rows emits {buckets} "
                         f"pow2 buckets — warmup alone overruns the "
                         f"declared recompile surface")))
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------

@dataclass
class IRCheckResult:
    findings: List[Finding]
    stale_budget: List[BaselineEntry] = field(default_factory=list)
    unjustified_budget: List[BaselineEntry] = field(default_factory=list)
    entries_run: List[str] = field(default_factory=list)
    elapsed: float = 0.0


def run_ircheck(rules: Optional[Sequence[str]] = None,
                entries: Optional[Sequence[str]] = None,
                budgets_path: Optional[str] = None) -> IRCheckResult:
    """Lower every entry in the signature table and run the IR rules.

    ``rules`` filters to a subset of :data:`IR_RULE_IDS`;
    ``entries`` filters specs by full ``name@variant`` or bare
    registry name. Returns raw findings (fids are assigned by the
    engine alongside the AST findings)."""
    t0 = time.perf_counter()
    want = set(rules) & set(IR_RULE_IDS) if rules else set(IR_RULE_IDS)
    jax = ensure_cpu_jax()
    from jax.experimental import enable_x64
    from ..parallel.comms import collective_summary

    budgets_path = budgets_path or default_budgets_path()
    budgets, unjustified = load_budgets(budgets_path)

    specs = build_specs(jax)
    if entries:
        wanted = set(entries)
        specs = [s for s in specs
                 if s.entry in wanted
                 or s.entry.split("@", 1)[0] in wanted]
        if not specs:
            raise ValueError(
                f"--ir-entry matched nothing (have: "
                f"{', '.join(s.entry for s in build_specs(jax))})")

    ctx: dict = {}
    findings: List[Finding] = []
    seen_keys = set()
    for spec in specs:
        fn, args, static_argnums, jit_fn = spec.build(ctx)
        closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(
            *args)
        if "TPL011" in want:
            with enable_x64():
                closed64 = jax.make_jaxpr(
                    fn, static_argnums=static_argnums)(*args)
            findings.extend(f64_findings(closed64, spec.relpath,
                                         spec.func, spec.entry))
        budget = budgets.get(spec.entry)
        if budget is not None:
            seen_keys.add(spec.entry)
        if "TPL012" in want:
            findings.extend(budget_findings(
                collective_summary(closed), budget, spec))
        expected_donate = tuple(budget.get("donate_argnums",
                                           spec.donate)) \
            if budget else spec.donate
        if "TPL013" in want and expected_donate and jit_fn is not None:
            dyn_args = args[len(static_argnums):] \
                if static_argnums == (0,) else args
            findings.extend(donation_findings(
                jit_fn, dyn_args, expected_donate, spec))

    if "TPL014" in want and not entries:
        from .engine import package_root
        findings.extend(recompile_surface_findings(package_root()))

    # budget-file staleness mirrors the baseline discipline: a key no
    # spec lowers anymore must be deleted, not rot as false assurance
    all_entries = {s.entry for s in build_specs(jax)}
    stale = [BaselineEntry(fid=f"ir_budgets.json:{key}",
                           justification="", lineno=i)
             for i, key in enumerate(sorted(set(budgets) - all_entries),
                                     start=1)]
    return IRCheckResult(findings=findings, stale_budget=stale,
                         unjustified_budget=unjustified,
                         entries_run=[s.entry for s in specs],
                         elapsed=time.perf_counter() - t0)
