"""XLA cost attribution: in-band roofline numbers for every compile.

docs/ROOFLINE.md justifies each perf decision against hand-curated
flops/bytes numbers from offline traces. This module makes that
accounting always-on: every jitted entry point registered through
:func:`~lightgbm_tpu.obs.jit_tracker.register_jit` is wrapped in a
:class:`CostTracked` proxy that notices each XLA cache miss (a miss IS
a compilation) and captures, once per new call signature:

- ``flops`` / ``bytes_accessed`` from the XLA HLO cost model
  (``fn.lower(...).cost_analysis()`` — the lowering is a re-trace,
  microseconds-to-milliseconds, NOT a second compile; set
  ``LIGHTGBM_TPU_COST_OPTIMIZED=1`` to pay one extra compile per
  signature for post-optimization numbers instead),
- ``wall_ms`` — the first call's host wall time (trace + compile +
  first dispatch),
- the device peaks (:func:`device_peaks`) and the resulting
  cost-model-optimal runtime ``optimal_ms = max(flops/peak_flops,
  bytes/peak_bw)`` — the live roofline denominator.

Each capture emits one ``{"event": "compile"}`` record (drained into
the telemetry JSONL stream by the recorder / serve daemon, summarized
by ``lightgbm_tpu stats``) and feeds the registry families
``xla_compiles{entry=}`` / ``xla_flops{entry=}`` /
``xla_bytes_accessed{entry=}`` / ``xla_compile_ms{entry=}``.

Hot-path cost: two C++ ``_cache_size()`` reads and one
``perf_counter`` pair per call — no host sync, no device work, no
lock. The capture itself (the only expensive part) runs exactly once
per compile, which already cost orders of magnitude more.

Threading contract (tpulint TPL008 over obs/): the pending-event list
is appended from whatever thread dispatched the compile (trainer loop,
serve batcher worker) and drained from recorder/daemon threads — every
touch goes through ``_events_lock``. The jax work (lowering) always
happens OUTSIDE that lock (TPL006).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .registry import registry as _global_registry

__all__ = ["CostTracked", "drain_compile_events",
           "compile_events_snapshot", "device_peaks",
           "roofline_optimal_ms", "cost_wrap_enabled",
           "DEVICE_PEAKS"]

#: dense peak compute (flops/s, bf16 systolic) and HBM bandwidth
#: (bytes/s) per device generation — the denominators of
#: docs/ROOFLINE.md, keyed by substrings of ``device_kind``. Override
#: with LIGHTGBM_TPU_PEAK_TFLOPS / LIGHTGBM_TPU_PEAK_GBPS for parts
#: not tabulated here.
DEVICE_PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ("v5 lite", 197e12, 819e9),   # v5e ("TPU v5 lite")
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6", 918e12, 1640e9),       # Trillium
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)

#: pending {"event": "compile"} records awaiting a drain; bounded so a
#: process nobody scrapes (a bare serve replica without telemetry)
#: never grows without limit
_EVENTS_CAP = 1024
_events_lock = threading.Lock()
_events: List[Dict[str, Any]] = []


def cost_wrap_enabled() -> bool:
    """LIGHTGBM_TPU_COST_ATTRIBUTION=0 is the kill switch: entry
    points register un-wrapped (recompile counting still works; no
    per-call bookkeeping, no compile events)."""
    return os.environ.get("LIGHTGBM_TPU_COST_ATTRIBUTION",
                          "1") not in ("0", "off", "false")


# -- device peaks ------------------------------------------------------

# resolved once per process; (kind, peak_flops, peak_bytes_per_sec),
# entries None when unknown. Guarded by _peaks_lock.
_peaks_lock = threading.Lock()
_peaks: Optional[Tuple[Optional[str], Optional[float],
                       Optional[float]]] = None


def _resolve_peaks() -> Tuple[Optional[str], Optional[float],
                              Optional[float]]:
    kind: Optional[str] = None
    try:
        import jax
        kind = str(jax.devices()[0].device_kind)
    except Exception:
        pass
    flops = bw = None
    if kind:
        low = kind.lower()
        for sub, f, b in DEVICE_PEAKS:
            if sub in low:
                flops, bw = f, b
                break
    env_f = os.environ.get("LIGHTGBM_TPU_PEAK_TFLOPS")
    env_b = os.environ.get("LIGHTGBM_TPU_PEAK_GBPS")
    try:
        if env_f:
            flops = float(env_f) * 1e12
        if env_b:
            bw = float(env_b) * 1e9
    except ValueError:
        pass
    return kind, flops, bw


def device_peaks() -> Tuple[Optional[str], Optional[float],
                            Optional[float]]:
    """(device_kind, peak_flops_per_sec, peak_bytes_per_sec) of the
    first local device; Nones where unknown (CPU has no tabulated
    peaks — the roofline column renders n/a there)."""
    global _peaks
    with _peaks_lock:
        if _peaks is not None:
            return _peaks
    resolved = _resolve_peaks()        # may import jax: outside lock
    with _peaks_lock:
        if _peaks is None:
            _peaks = resolved
        return _peaks


def roofline_optimal_ms(flops: Optional[float],
                        bytes_accessed: Optional[float],
                        peak_flops: Optional[float],
                        peak_bytes_per_sec: Optional[float]) \
        -> Optional[float]:
    """Cost-model-optimal runtime in ms at the device peaks: the
    roofline max of the compute time and the memory time. None when
    either side of the division is unknown."""
    candidates = []
    if flops is not None and peak_flops:
        candidates.append(flops / peak_flops)
    if bytes_accessed is not None and peak_bytes_per_sec:
        candidates.append(bytes_accessed / peak_bytes_per_sec)
    if not candidates:
        return None
    return max(candidates) * 1e3


# -- signature description --------------------------------------------

def _describe_leaf(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(x, (bool, int, float, str)) or x is None:
        return repr(x)[:32]
    return type(x).__name__


def _describe_args(args: tuple, kwargs: dict) -> str:
    """Short human signature of a call: avals of the array leaves plus
    static scalars, capped — diagnostic text, never parsed."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves((args, kwargs))
    except Exception:
        leaves = list(args) + list(kwargs.values())
    parts = [_describe_leaf(leaf) for leaf in leaves[:24]]
    if len(leaves) > 24:
        parts.append(f"+{len(leaves) - 24} more")
    return ",".join(parts)


# -- the capture -------------------------------------------------------

def _cost_analysis(fn: Callable, args: tuple, kwargs: dict) \
        -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from the XLA HLO cost model for this
    call signature. Default: ``lower().cost_analysis()`` — a re-trace,
    not a compile. LIGHTGBM_TPU_COST_OPTIMIZED=1 compiles the lowered
    program once more for post-optimization numbers (expensive:
    doubles compile time; measurement sessions only)."""
    lowered = fn.lower(*args, **kwargs)
    if os.environ.get("LIGHTGBM_TPU_COST_OPTIMIZED", "") \
            not in ("", "0"):
        ca = lowered.compile().cost_analysis()
    else:
        ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed")
    return (None if flops is None else float(flops),
            None if bytes_accessed is None else float(bytes_accessed))


def _capture(name: str, fn: Callable, args: tuple, kwargs: dict,
             wall_ms: float, compiles: int) -> None:
    """Build and enqueue one compile record. Runs once per cache miss,
    right after the compile that already cost seconds; every jax call
    here stays outside the events lock (TPL006)."""
    flops = bytes_accessed = None
    try:
        flops, bytes_accessed = _cost_analysis(fn, args, kwargs)
    except Exception:
        # donated buffers, lowering quirks: the event still records
        # the compile itself, just without the cost model numbers
        pass
    kind, peak_flops, peak_bw = device_peaks()
    event = {
        "event": "compile",
        "entry": name,
        "signature": _describe_args(args, kwargs),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "wall_ms": round(wall_ms, 3),
        "compiles": int(compiles),
        "device_kind": kind,
        "peak_flops": peak_flops,
        "peak_bytes_per_sec": peak_bw,
        "optimal_ms": roofline_optimal_ms(flops, bytes_accessed,
                                          peak_flops, peak_bw),
        "time": time.time(),
    }
    with _events_lock:
        _events.append(event)
        if len(_events) > _EVENTS_CAP:
            del _events[:len(_events) - _EVENTS_CAP]
    reg = _global_registry
    reg.counter("xla_compiles", entry=name).inc(compiles)
    reg.histogram("xla_compile_ms", entry=name).observe(wall_ms)
    if flops is not None:
        reg.gauge("xla_flops", entry=name).set(flops)
    if bytes_accessed is not None:
        reg.gauge("xla_bytes_accessed", entry=name).set(bytes_accessed)


def drain_compile_events() -> List[Dict[str, Any]]:
    """Locked snapshot-and-clear of the pending compile records (the
    ``faults.drain_events`` contract: a concurrent append can never be
    lost between a copy and a clear)."""
    global _events
    with _events_lock:
        drained, _events = _events, []
    return drained


def compile_events_snapshot() -> List[Dict[str, Any]]:
    """Non-destructive copy of the pending records (tests, bench)."""
    with _events_lock:
        return list(_events)


class CostTracked:
    """Call-through proxy over one jitted entry point.

    ``__call__`` detects XLA cache misses by diffing the function's
    compile-cache size around the call — the same signal the
    recompile watcher polls — and runs the cost capture once per
    miss. Everything else (``_cache_size``, ``lower``, AOT attrs)
    proxies to the wrapped function, so the jit tracker and existing
    callers never branch on whether an entry point is wrapped.
    """

    __slots__ = ("_fn", "_name", "__weakref__")

    def __init__(self, name: str, fn: Callable):
        self._fn = fn
        self._name = name

    @property
    def unwrapped(self) -> Callable:
        return self._fn

    @property
    def entry_name(self) -> str:
        return self._name

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            before = int(fn._cache_size())
        except Exception:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        try:
            grew = int(fn._cache_size()) - before
        except Exception:
            grew = 0
        if grew > 0:
            _capture(self._name, fn, args, kwargs,
                     (time.perf_counter() - t0) * 1e3, grew)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self) -> str:
        return f"CostTracked({self._name!r}, {self._fn!r})"
