# tpulint fixture: TPL008 negative — the same span recorder as
# obs/tpl008_trace_pos.py with every touch of the buffer, the drop
# counter and the current-trace cell under ONE _spans_lock common to
# the recording threads and the drain thread (the locked
# snapshot-and-clear contract of obs/trace.py). No EXPECT lines.
import threading

_spans_lock = threading.Lock()
_spans = []
_spans_dropped = 0
_current = None
_SPANS_CAP = 4096


def record_span(name, dur):
    global _spans_dropped
    ev = {"event": "span", "name": name, "dur": dur}
    with _spans_lock:
        if len(_spans) < _SPANS_CAP:
            _spans.append(ev)
        else:
            _spans_dropped += 1
    return ev


def set_current_trace(trace_id):
    global _current
    with _spans_lock:
        _current = trace_id


def _drain_loop(sink):
    while True:
        global _spans_dropped
        with _spans_lock:
            out = list(_spans)
            _spans.clear()
            _spans_dropped = 0
        for ev in out:
            sink(ev)


def start(sink):
    threading.Thread(target=_drain_loop, args=(sink,),
                     daemon=True).start()
    threading.Thread(target=record_span, args=("serve/request", 0.01),
                     daemon=True).start()
    set_current_trace("t" * 16)
    return record_span("train/iteration", 0.1)
