"""Batched tree traversal (prediction) as XLA gathers.

Re-design of Tree::Predict / the branchy per-row traversal
(/root/reference/include/LightGBM/tree.h:134,338-410 and
src/boosting/gbdt_prediction.cpp) as a vectorized node-pointer iteration:
every row walks the tree simultaneously via gathers on the flat tree
tensors; a ``lax.while_loop`` runs until all rows hit a leaf.

Missing-value routing matches the reference's NumericalDecision
(tree.h:338-360): missing_type none -> NaN treated as 0; zero -> |v| <=
kZeroThreshold or NaN follows the default arm; nan -> NaN follows the
default arm (encoded in decision_type bits, see models/tree.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["predict_leaf_binned", "predict_leaf_raw", "StackedTrees"]

K_ZERO_THRESHOLD = 1e-35

# missing_type codes (match decision_type bits 2-3 in the model format)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class StackedTrees(NamedTuple):
    """A whole forest as stacked tensors: leading axis = tree index.

    Leaves are referenced as ``~leaf`` in child arrays (tree.h convention).
    """
    split_feature: jnp.ndarray   # [T, L-1] i32
    threshold: jnp.ndarray       # [T, L-1] f64/f32 real-valued thresholds
    threshold_bin: jnp.ndarray   # [T, L-1] i32
    default_left: jnp.ndarray    # [T, L-1] bool
    missing_type: jnp.ndarray    # [T, L-1] i8
    is_categorical: jnp.ndarray  # [T, L-1] bool
    cat_bitset: jnp.ndarray      # [T, L-1, W] u32 category membership bitsets
    left_child: jnp.ndarray      # [T, L-1] i32
    right_child: jnp.ndarray     # [T, L-1] i32
    leaf_value: jnp.ndarray      # [T, L] f32
    # linear leaves (None for constant-leaf forests)
    lin_const: jnp.ndarray = None   # [T, L] f32
    lin_nfeat: jnp.ndarray = None   # [T, L] i32
    lin_feats: jnp.ndarray = None   # [T, L, km] i32 (real feature ids)
    lin_coef: jnp.ndarray = None    # [T, L, km] f32


def _traverse(n: int, decide_fn, left_child, right_child):
    """Run node-pointer iteration until every row reaches a leaf."""
    node0 = jnp.zeros((n,), jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        go_left = decide_fn(idx)
        nxt = jnp.where(go_left, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = lax.while_loop(cond, body, node0)
    return ~node  # leaf indices


def predict_leaf_binned(split_feature, threshold_bin, default_left,
                        left_child, right_child, feat_nan_bin,
                        bins_T, is_cat=None, cat_masks=None) -> jnp.ndarray:
    """Leaf index per row for one tree over the *binned* matrix [F, n].

    Used for train/valid score updates during boosting, where data is
    already binned (the ScoreUpdater::AddScore analog, score_updater.hpp).
    ``is_cat``/``cat_masks`` ([nn] bool, [nn, B] bool) route categorical
    nodes by bin membership instead of the bin threshold.
    """
    n = bins_T.shape[1]
    rows = jnp.arange(n)

    def decide(idx):
        sf = split_feature[idx]
        v = bins_T[sf, rows].astype(jnp.int32)
        nb = feat_nan_bin[sf]
        num_left = jnp.where((nb >= 0) & (v == nb), default_left[idx],
                             v <= threshold_bin[idx])
        if is_cat is None:
            return num_left
        return jnp.where(is_cat[idx], cat_masks[idx, v], num_left)

    return _traverse(n, decide, left_child, right_child)


def _cat_contains(bitset_row: jnp.ndarray, value: jnp.ndarray) -> jnp.ndarray:
    """Test value membership in a u32 bitset (FindInBitset analog)."""
    W = bitset_row.shape[-1]
    word = value // 32
    bit = value % 32
    in_range = (value >= 0) & (word < W)
    w = jnp.take_along_axis(bitset_row, jnp.maximum(word, 0)[..., None],
                            axis=-1)[..., 0]
    return in_range & ((w >> bit.astype(jnp.uint32)) & 1).astype(jnp.bool_)


def predict_leaf_raw(tree: StackedTrees, ti: int | jnp.ndarray,
                     X: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per row for tree ``ti`` over raw features ``[n, F]``."""
    n = X.shape[0]
    sf = tree.split_feature[ti]
    thr = tree.threshold[ti]
    dl = tree.default_left[ti]
    mt = tree.missing_type[ti]
    is_cat = tree.is_categorical[ti]
    bitset = tree.cat_bitset[ti]

    def decide(idx):
        f = sf[idx]
        v = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        m = mt[idx]
        is_nan = jnp.isnan(v)
        v0 = jnp.where(is_nan, 0.0, v)
        # numerical decision with missing routing (tree.h:338-360)
        is_zero = jnp.abs(v0) <= K_ZERO_THRESHOLD
        missing = jnp.where(m == MISSING_NAN, is_nan,
                            jnp.where(m == MISSING_ZERO, is_zero | is_nan,
                                      jnp.zeros_like(is_nan)))
        num_left = jnp.where(missing, dl[idx], v0 <= thr[idx])
        # categorical decision: membership in bitset -> left (tree.h:402)
        iv = jnp.where(is_nan | (v < 0), -1, v).astype(jnp.int32)
        cat_left = _cat_contains(bitset[idx], iv)
        return jnp.where(is_cat[idx], cat_left, num_left)

    return _traverse(n, decide, tree.left_child[ti], tree.right_child[ti])


def predict_forest_raw(tree: StackedTrees, X: jnp.ndarray,
                       num_trees: int) -> jnp.ndarray:
    """Sum of leaf values over trees [0, num_trees) -> raw scores [n]."""

    def body(i, acc):
        leaves = predict_leaf_raw(tree, i, X)
        return acc + tree.leaf_value[i][leaves]

    init = jnp.zeros((X.shape[0],), tree.leaf_value.dtype)
    return lax.fori_loop(0, num_trees, body, init)
