"""Out-of-core streaming ingestion (lightgbm_tpu/data/, docs/DATA.md).

Acceptance surface of the two-pass pipeline:

1. parity — a chunked construct (array / generator / Sequence / CSV /
   Arrow sources, chunk sizes that do and don't divide n) produces
   BIT-IDENTICAL BinMappers, binned matrices and 10-round models vs
   the in-memory path;
2. the checkpoint data fingerprint accumulated during pass 2 equals
   the eager digest, so resume works across ingestion modes and still
   refuses different data;
3. obs wiring — the `ingest` JSONL event, its `stats` row, and the
   registry counters;
4. memory — a `slow` subprocess proof that peak RSS stays O(chunk) on
   a dataset 10x the chunk size (the raw float matrix would not fit
   the asserted budget);
5. distributed — a 2-process kv-transport world where each rank
   ingests its shard through a chunk source (`mp`/`slow`), and the
   chaos leg: `rank_kill@-1` during the pass-1 mapper sync must
   watchdog-abort naming the collective, and the supervised relaunch
   re-ingests cleanly.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.basic import LightGBMError
from lightgbm_tpu.data import (ArrayChunkSource, ArrowChunkSource,
                               GeneratorChunkSource, dataset_digest)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "max_bin": 63}


def _make(n=4000, f=8, seed=3, nan_frac=0.05):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    if nan_frac:
        X[rs.rand(n, f) < nan_frac] = np.nan
    y = (np.nansum(X[:, : max(1, f // 2)], axis=1) > 0).astype(
        np.float64)
    return X, y


def _mappers(ds):
    return [m.to_dict() for m in ds.mappers]


def _assert_construct_parity(d_eager, d_stream):
    d_eager.construct()
    d_stream.construct()
    assert _mappers(d_eager) == _mappers(d_stream)
    np.testing.assert_array_equal(d_eager.host_bins(),
                                  d_stream.host_bins())
    assert d_stream.host_bins().dtype == d_eager.host_bins().dtype
    np.testing.assert_array_equal(
        np.asarray(d_eager.get_label()), np.asarray(d_stream.get_label()))
    np.testing.assert_array_equal(d_eager.used_feature_indices(),
                                  d_stream.used_feature_indices())


# ---------------------------------------------------------------------
# 1. streaming <-> eager parity
# ---------------------------------------------------------------------

@pytest.mark.parametrize("chunk", [1000, 999, 8192])
def test_array_source_bit_identical_to_eager(chunk):
    """Chunk sizes that divide n, don't divide n, and exceed n."""
    X, y = _make()
    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=chunk),
                      params=dict(PARAMS))
    _assert_construct_parity(d_e, d_s)
    stats = d_s._ingest_stats
    assert stats["rows"] == len(y)
    assert stats["chunks"] == -(-len(y) // chunk)


def test_trained_model_identical_over_10_rounds():
    X, y = _make()
    b_e = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y,
                                              params=dict(PARAMS)),
                    num_boost_round=10)
    b_s = lgb.train(dict(PARAMS),
                    lgb.Dataset(ArrayChunkSource(X, label=y,
                                                 chunk_rows=999),
                                params=dict(PARAMS)),
                    num_boost_round=10)
    assert b_e.model_to_string() == b_s.model_to_string()


def test_known_length_subsampled_mappers_bit_identical():
    """bin_construct_sample_cnt < n: the streaming pass gathers the
    EXACT rng.choice row set the eager constructor draws, so mappers
    match bit-for-bit even on a strict subsample."""
    X, y = _make(n=5000)
    params = dict(PARAMS, bin_construct_sample_cnt=700)
    d_e = lgb.Dataset(X, label=y, params=dict(params))
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=640),
                      params=dict(params))
    _assert_construct_parity(d_e, d_s)


def test_generator_factory_unknown_length_parity():
    X, y = _make()

    def factory():
        for lo in range(0, len(y), 640):
            yield X[lo:lo + 640], y[lo:lo + 640]

    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    d_s = lgb.Dataset(GeneratorChunkSource(factory), params=dict(PARAMS))
    _assert_construct_parity(d_e, d_s)


def test_bare_callable_is_accepted_as_factory():
    X, y = _make(n=1200)

    def chunks():
        yield X[:500], y[:500]
        yield X[500:], y[500:]

    d_s = lgb.Dataset(chunks, params=dict(PARAMS))
    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    _assert_construct_parity(d_e, d_s)


def test_csv_path_streams_with_ingest_chunk_rows(tmp_path):
    X, y = _make(n=3000, f=6, nan_frac=0.0)
    path = str(tmp_path / "train.csv")
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.6g")
    d_e = lgb.Dataset(path, params=dict(PARAMS))
    d_s = lgb.Dataset(path, params=dict(PARAMS, ingest_chunk_rows=700))
    _assert_construct_parity(d_e, d_s)
    assert d_s._ingest_stats["source"] == "CSVChunkSource"
    # two_round's streamed result agrees too (same sampling seed)
    d_t = lgb.Dataset(path, params=dict(PARAMS, two_round=True))
    _assert_construct_parity(d_t, d_s)


def test_csv_header_and_named_label_column(tmp_path):
    X, y = _make(n=800, f=4, nan_frac=0.0)
    path = str(tmp_path / "named.csv")
    with open(path, "w") as fh:
        fh.write("a,target,b,c,d\n")
        block = np.column_stack([X[:, 0], y, X[:, 1:]])
        np.savetxt(fh, block, delimiter=",", fmt="%.6g")
    params = dict(PARAMS, header=True, label_column="name:target",
                  ingest_chunk_rows=300)
    d_s = lgb.Dataset(path, params=params)
    d_s.construct()
    np.testing.assert_array_equal(np.asarray(d_s.get_label()), y)
    assert d_s.get_feature_name() == ["a", "b", "c", "d"]
    d_e = lgb.Dataset(path, params=dict(PARAMS, header=True,
                                        label_column="name:target"))
    d_e.construct()
    np.testing.assert_array_equal(d_e.host_bins(), d_s.host_bins())


def test_sequence_inputs_route_through_streaming():
    X, y = _make(n=900, f=4)

    class ArrSeq(lgb.Sequence):
        batch_size = 128

        def __init__(self, arr):
            self.arr = arr

        def __getitem__(self, idx):
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    d_s = lgb.Dataset([ArrSeq(X[:400]), ArrSeq(X[400:])], label=y,
                      params=dict(PARAMS))
    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    _assert_construct_parity(d_e, d_s)
    assert d_s._ingest_stats["source"] == "SequenceChunkSource"


def test_streaming_valid_set_binned_against_reference():
    X, y = _make()
    Xv, yv = _make(n=700, seed=11)
    d_tr = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=512),
                       params=dict(PARAMS))
    d_v = d_tr.create_valid(ArrayChunkSource(Xv, label=yv,
                                             chunk_rows=128))
    bst = lgb.train(dict(PARAMS), d_tr, num_boost_round=5,
                    valid_sets=[d_v])
    assert bst.current_iteration() == 5
    d_v_eager = lgb.Dataset(X, label=y, params=dict(PARAMS)) \
        .create_valid(Xv, label=yv)
    d_v_eager.construct()
    np.testing.assert_array_equal(d_v.host_bins(), d_v_eager.host_bins())


def test_weight_chunks_and_label_override():
    X, y = _make(n=1000)
    w = np.random.RandomState(0).rand(1000) + 0.5
    src = ArrayChunkSource(X, label=y, weight=w, chunk_rows=300)
    d_s = lgb.Dataset(src, params=dict(PARAMS))
    d_s.construct()
    np.testing.assert_array_equal(np.asarray(d_s.get_weight()), w)
    # an explicit label argument overrides the source's labels — and
    # the fingerprint must follow the override
    y2 = 1.0 - y
    d_o = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=300),
                      label=y2, params=dict(PARAMS))
    d_o.construct()
    np.testing.assert_array_equal(np.asarray(d_o.get_label()), y2)
    assert d_o._data_digest == dataset_digest(y2, d_o.host_bins())


def test_categorical_ctor_arg_takes_precedence_over_params():
    """Eager resolution lets the categorical_feature ARGUMENT win over
    the params spec; streaming must match or bit-parity (and the
    cross-mode checkpoint digest) breaks."""
    rs = np.random.RandomState(5)
    n = 1200
    X = np.column_stack([rs.randint(0, 6, n).astype(float),
                         rs.randint(0, 6, n).astype(float),
                         rs.randn(n)])
    y = (X[:, 2] > 0).astype(np.float64)
    params = dict(PARAMS, categorical_feature="1")
    d_e = lgb.Dataset(X, label=y, params=dict(params),
                      categorical_feature=[0])
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=500),
                      params=dict(params), categorical_feature=[0])
    _assert_construct_parity(d_e, d_s)


def test_custom_source_with_float32_labels_digest_parity():
    """A RowChunkSource subclass yielding float32 labels (never passed
    through a built-in adapter): the incremental digest must hash the
    float64-normalized bytes, or cross-mode resume refuses identical
    data."""
    from lightgbm_tpu.data import RowChunk, RowChunkSource

    X, y = _make(n=900)

    class F32Source(RowChunkSource):
        def num_rows(self):
            return len(y)

        def chunks(self):
            for lo in range(0, len(y), 250):
                yield RowChunk(X[lo:lo + 250].astype(np.float32),
                               y[lo:lo + 250].astype(np.float32))

    d_s = lgb.Dataset(F32Source(), params=dict(PARAMS))
    d_s.construct()
    d_e = lgb.Dataset(X.astype(np.float32), label=y,
                      params=dict(PARAMS))
    d_e.construct()
    np.testing.assert_array_equal(d_e.host_bins(), d_s.host_bins())
    assert d_s._data_digest == dataset_digest(
        np.asarray(d_e.get_label(), np.float64), d_e.host_bins())


def test_categorical_int_indices_parity():
    rs = np.random.RandomState(7)
    n = 1500
    X = np.column_stack([rs.randint(0, 8, n).astype(float),
                         rs.randn(n), rs.randn(n)])
    y = (X[:, 1] + (X[:, 0] > 3) > 0).astype(np.float64)
    params = dict(PARAMS, categorical_feature=[0])
    d_e = lgb.Dataset(X, label=y, params=dict(params),
                      categorical_feature=[0])
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=400),
                      params=dict(params), categorical_feature=[0])
    _assert_construct_parity(d_e, d_s)


def _has_pyarrow():
    try:
        import pyarrow  # noqa: F401
        return True
    except ImportError:
        return False


@pytest.mark.skipif(not _has_pyarrow(), reason="pyarrow not installed")
def test_arrow_table_and_parquet_sources(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    X, y = _make(n=1100, f=5, nan_frac=0.0)
    table = pa.table({"label": y,
                      **{f"f{j}": X[:, j] for j in range(X.shape[1])}})
    src = ArrowChunkSource(table, chunk_rows=256, label_column="label")
    d_s = lgb.Dataset(src, params=dict(PARAMS))
    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    _assert_construct_parity(d_e, d_s)
    assert d_s.get_feature_name() == [f"f{j}" for j in range(5)]

    pq_path = str(tmp_path / "train.parquet")
    pq.write_table(table, pq_path, row_group_size=300)
    src2 = ArrowChunkSource(pq_path, chunk_rows=256,
                            label_column="label")
    assert src2.num_rows() == 1100
    d_p = lgb.Dataset(src2, params=dict(PARAMS))
    _assert_construct_parity(d_e, d_p)

    # path streaming honors cfg.label_column (name: and index forms) —
    # ignoring it would train on the label as a feature
    d_q = lgb.Dataset(pq_path, params=dict(
        PARAMS, ingest_chunk_rows=256, label_column="name:label"))
    _assert_construct_parity(d_e, d_q)
    assert d_q.get_feature_name() == [f"f{j}" for j in range(5)]
    d_i = lgb.Dataset(pq_path, params=dict(PARAMS,
                                           ingest_chunk_rows=256))
    _assert_construct_parity(d_e, d_i)  # default: first schema column


# ---------------------------------------------------------------------
# 2. error surface
# ---------------------------------------------------------------------

def test_generator_object_rejected_with_clear_error():
    X, y = _make(n=500)
    gen = iter([(X, y)])  # consumable once: useless for two passes
    with pytest.raises(LightGBMError):
        lgb.Dataset(GeneratorChunkSource(gen), params=dict(PARAMS))


def test_inconsistent_feature_width_raises():
    def factory():
        yield np.zeros((10, 4)), np.zeros(10)
        yield np.zeros((10, 5)), np.zeros(10)

    with pytest.raises(LightGBMError, match="features"):
        lgb.Dataset(GeneratorChunkSource(factory),
                    params=dict(PARAMS)).construct()


def test_labels_must_be_consistent_across_chunks():
    def factory():
        yield np.zeros((10, 3)), np.zeros(10)
        yield np.zeros((10, 3))

    with pytest.raises(LightGBMError, match="labels"):
        lgb.Dataset(GeneratorChunkSource(factory),
                    params=dict(PARAMS)).construct()


def test_array_source_rejects_mismatched_metadata_lengths():
    """A LONGER label slices cleanly against every chunk, so without
    an up-front check it would be silently truncated."""
    X, _ = _make(n=100)
    with pytest.raises(LightGBMError, match="Length of label"):
        ArrayChunkSource(X, label=np.zeros(150))
    with pytest.raises(LightGBMError, match="Length of weight"):
        ArrayChunkSource(X, label=np.zeros(100), weight=np.ones(80))


def test_weights_must_be_consistent_across_chunks():
    def factory():
        yield np.zeros((10, 3)), np.zeros(10), np.ones(10)
        yield np.zeros((10, 3)), np.zeros(10)

    with pytest.raises(LightGBMError, match="weights"):
        lgb.Dataset(GeneratorChunkSource(factory),
                    params=dict(PARAMS)).construct()


def test_declared_row_count_must_match_stream():
    X, y = _make(n=300)

    def factory():
        yield X, y

    with pytest.raises(LightGBMError, match="declared"):
        lgb.Dataset(GeneratorChunkSource(factory, num_rows=400),
                    params=dict(PARAMS)).construct()


def test_empty_source_raises():
    with pytest.raises(LightGBMError, match="no rows"):
        lgb.Dataset(GeneratorChunkSource(lambda: iter(())),
                    params=dict(PARAMS)).construct()


def test_missing_label_raises():
    X, _ = _make(n=200)
    with pytest.raises(LightGBMError, match="Label"):
        lgb.Dataset(ArrayChunkSource(X, chunk_rows=100),
                    params=dict(PARAMS)).construct()


def test_linear_tree_streaming_retains_raw_and_matches_eager():
    """linear_tree needs raw values: pass 2 retains the used-column
    f32 matrix at the eager path's exact cost (Sequence inputs used
    to materialize for this; streaming must not regress it)."""
    X, y = _make(n=1200, nan_frac=0.0)
    y = X[:, 0] * 2.0 + y
    params = dict(PARAMS, objective="regression", linear_tree=True)
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=500),
                      params=dict(params))
    d_e = lgb.Dataset(X, label=y, params=dict(params))
    d_s.construct()
    d_e.construct()
    np.testing.assert_array_equal(d_s.raw_numeric(), d_e.raw_numeric())
    b_s = lgb.train(dict(params),
                    lgb.Dataset(ArrayChunkSource(X, label=y,
                                                 chunk_rows=500),
                                params=dict(params)),
                    num_boost_round=5)
    b_e = lgb.train(dict(params), lgb.Dataset(X, label=y,
                                              params=dict(params)),
                    num_boost_round=5)
    assert b_s.model_to_string() == b_e.model_to_string()


def test_set_label_after_streaming_construct_refreshes_digest(tmp_path):
    """set_label() on a constructed streaming dataset must invalidate
    the precomputed fingerprint, or two runs differing only via
    set_label would share a digest and the checkpoint guard would
    resume across them."""
    X, y = _make(n=600)
    ds = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=200),
                     params=dict(PARAMS))
    ds.construct()
    assert ds._data_digest is not None
    ds.set_label(1.0 - y)
    assert ds._data_digest is None  # checkpoint layer rehashes


def test_libsvm_path_falls_back_to_eager(tmp_path):
    path = str(tmp_path / "train.svm")
    with open(path, "w") as fh:
        for i in range(200):
            fh.write(f"{i % 2} 0:{i * 0.1:.3f} 2:{(200 - i) * 0.5:.3f}\n")
    ds = lgb.Dataset(path, params=dict(PARAMS, ingest_chunk_rows=64))
    ds.construct()  # streamed loaders cannot do ragged rows: eager path
    assert getattr(ds, "_ingest_stats", None) is None
    assert ds.num_data() == 200


def test_ingest_chunk_rows_param_validation():
    with pytest.raises(ValueError):
        lgb.Config.from_params({"ingest_chunk_rows": -1})
    assert lgb.Config.from_params(
        {"ingest_chunk_rows": "4096"}).ingest_chunk_rows == 4096


# ---------------------------------------------------------------------
# 3. checkpoint fingerprint: incremental digest == eager digest
# ---------------------------------------------------------------------

def test_streaming_digest_equals_eager_digest():
    X, y = _make()
    d_e = lgb.Dataset(X, label=y, params=dict(PARAMS))
    d_e.construct()
    d_s = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=999),
                      params=dict(PARAMS))
    d_s.construct()
    assert d_s._data_digest == dataset_digest(
        np.asarray(d_e.get_label(), np.float64), d_e.host_bins())


def test_resume_works_across_ingestion_modes_and_refuses_other_data(
        tmp_path):
    X, y = _make(n=1500)
    ck = str(tmp_path / "ckpts")
    params = dict(PARAMS, seed=3)

    def stream_ds():
        return lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=400),
                           params=dict(params))

    lgb.train(dict(params), stream_ds(), num_boost_round=4,
              callbacks=[lgb.checkpoint(ck)])
    # resume the STREAMING run from an EAGER dataset of the same data:
    # the incremental pass-2 digest must match the eager fingerprint
    resumed = lgb.train(dict(params),
                        lgb.Dataset(X, label=y, params=dict(params)),
                        num_boost_round=8, resume_from=ck)
    uninterrupted = lgb.train(dict(params), stream_ds(),
                              num_boost_round=8)
    assert resumed.model_to_string() == uninterrupted.model_to_string()
    # ...and a streaming dataset of DIFFERENT data is refused
    X2, y2 = _make(n=1500, seed=99)
    with pytest.raises(LightGBMError, match="different training data"):
        lgb.train(dict(params),
                  lgb.Dataset(ArrayChunkSource(X2, label=y2,
                                               chunk_rows=400),
                              params=dict(params)),
                  num_boost_round=8, resume_from=ck)


# ---------------------------------------------------------------------
# 4. obs wiring: ingest event, stats row, counters
# ---------------------------------------------------------------------

def test_ingest_event_and_stats_row(tmp_path):
    from lightgbm_tpu.obs.recorder import (render_stats_table,
                                           summarize_events)
    X, y = _make(n=1200)
    telem = str(tmp_path / "t.jsonl")
    ds = lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=300),
                     params=dict(PARAMS))
    lgb.train(dict(PARAMS), ds, num_boost_round=3,
              callbacks=[lgb.telemetry(telem)])
    events = [json.loads(line) for line in open(telem)]
    ingest_events = [e for e in events if e["event"] == "ingest"]
    assert len(ingest_events) == 1
    ev = ingest_events[0]
    assert ev["rows"] == 1200 and ev["chunks"] == 4
    assert ev["pass1_s"] >= 0 and ev["pass2_s"] >= 0
    summary = summarize_events(telem)
    assert summary["ingest"]["rows"] == 1200
    assert summary["iterations"] == 3
    table = render_stats_table(summary)
    assert "ingest" in table and "1200 rows / 4 chunks" in table


def test_ingest_registry_counters():
    from lightgbm_tpu.obs.registry import registry
    X, y = _make(n=800)
    before_chunks = registry.counter("ingest_chunks").value
    before_rows = registry.counter("ingest_rows").value
    lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=200),
                params=dict(PARAMS)).construct()
    assert registry.counter("ingest_chunks").value == before_chunks + 4
    assert registry.counter("ingest_rows").value == before_rows + 800


def test_ingest_phases_visible_in_timer():
    from lightgbm_tpu.utils.timer import Timer
    X, y = _make(n=600)
    Timer.enable()
    try:
        lgb.Dataset(ArrayChunkSource(X, label=y, chunk_rows=200),
                    params=dict(PARAMS)).construct()
        snap = Timer.snapshot()
    finally:
        Timer.enable(False)
    assert "ingest/pass1" in snap and "ingest/pass2" in snap


# ---------------------------------------------------------------------
# 5. the data/ package stays jax-free
# ---------------------------------------------------------------------

def test_data_package_never_imports_jax():
    """The ingestion path must stay jax-import-lazy: importing the
    package AND running the full two-pass pipeline directly (sources +
    ingest_dataset on a single process) must not pull jax in. (The
    Dataset facade inevitably imports jax — ``basic`` does at module
    level — which is exactly why data/ raises through a lazy error
    helper instead of importing ``LightGBMError`` eagerly.)"""
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from lightgbm_tpu.config import Config\n"
        "from lightgbm_tpu.data import (ArrayChunkSource,\n"
        "                               ingest_dataset)\n"
        "X = np.random.RandomState(0).randn(500, 4)\n"
        "y = (X[:, 0] > 0).astype(np.float64)\n"
        "cfg = Config.from_params({'max_bin': 63,\n"
        "                          'ingest_chunk_rows': 128})\n"
        "res = ingest_dataset(ArrayChunkSource(X, label=y), cfg, set())\n"
        "assert res.n == 500 and res.bins.shape == (500, 4)\n"
        "assert res.digest is not None\n"
        "assert 'jax' not in sys.modules, 'ingestion imported jax!'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------
# 6. memory budget: peak RSS stays O(chunk), never O(raw matrix)
# ---------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(600)
def test_peak_rss_bounded_by_chunk_footprint_not_dataset(tmp_path):
    """A dataset >= 10x the chunk size constructs within a budget the
    raw float matrix could not fit (tests/ingest_mem_worker.py runs in
    a subprocess so ru_maxrss is clean)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(TESTS_DIR, "ingest_mem_worker.py")],
        capture_output=True, text=True, timeout=540, cwd=REPO_DIR)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    # the raw matrix alone would add >= raw_mb over baseline; the
    # streaming construct must stay under half of it
    assert report["delta_mb"] < report["raw_mb"] / 2, report
    assert report["delta_mb"] < report["budget_mb"], report


# ---------------------------------------------------------------------
# 7. distributed: 2-process shard ingestion + chaos
# ---------------------------------------------------------------------

def _worker_env(tmp_path, port, rank, fault="", extra=None):
    from _mp_utils import worker_base_env
    env = worker_base_env({
        "LIGHTGBM_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "LIGHTGBM_TPU_NUM_PROCS": "2",
        "LIGHTGBM_TPU_RANK": str(rank),
        "LIGHTGBM_TPU_FAULT_INJECT": fault,
        "LIGHTGBM_TPU_FAULT_RANK": "1",
        "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": "15",
        "LIGHTGBM_TPU_INIT_BACKOFF": "0.05",
    })
    if extra:
        env.update(extra)
    return env


@pytest.mark.mp
@pytest.mark.slow
@pytest.mark.timeout(420)
def test_two_process_streaming_shards_match_eager_distributed(tmp_path):
    """Each rank ingests its shard through a chunk source; the gathered
    global dataset — and the trained model — must be identical to the
    eager distributed_dataset path (the worker asserts bins/mappers
    in-process and rank 0 writes both models)."""
    from _mp_utils import drain_all, free_port, spawn_worker
    port = free_port()
    worker = os.path.join(TESTS_DIR, "ingest_worker.py")
    procs = [
        spawn_worker([worker, str(tmp_path)],
                     _worker_env(tmp_path, port, rank))
        for rank in (0, 1)
    ]
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            drain_all(procs, "2-process streaming ingest hung")
        outs.append(out.decode(errors="replace"))
    for rank, (p, text) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{text}"
        assert "INGEST_PARITY_OK" in text, text
    m_stream = (tmp_path / "model_stream.txt").read_bytes()
    m_eager = (tmp_path / "model_eager.txt").read_bytes()
    assert m_stream == m_eager


@pytest.mark.mp
@pytest.mark.slow
@pytest.mark.timeout(600)
def test_rank_kill_during_pass1_sync_aborts_then_relaunch_reingests(
        tmp_path):
    """The chaos tie-in: rank_kill@-1 kills rank 1 right before the
    pass-1 mapper sync; the survivor must watchdog-abort NAMING the
    collective (no hang), and the supervised relaunch — with the
    one-shot fault stripped — re-ingests and trains to completion."""
    from _mp_utils import worker_base_env
    worker = os.path.join(TESTS_DIR, "ingest_worker.py")
    outdir = tmp_path / "chaos"
    outdir.mkdir()
    env = worker_base_env({
        "JAX_PLATFORMS": "cpu",
        "LIGHTGBM_TPU_FAULT_INJECT": "rank_kill@-1",
        "LIGHTGBM_TPU_FAULT_RANK": "1",
        "LIGHTGBM_TPU_COLLECTIVE_TIMEOUT": "15",
        "LIGHTGBM_TPU_INIT_BACKOFF": "0.05",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "launch", "2",
         "--max-restarts", "2", "--log-dir", str(outdir),
         "--grace", "30", "--",
         sys.executable, worker, str(outdir)],
        env=env, cwd=REPO_DIR, capture_output=True, text=True,
        timeout=540)
    logs = {name: (outdir / name).read_text(errors="replace")
            for name in os.listdir(outdir) if name.endswith(".log")}
    detail = "\n".join(f"--- {k} ---\n{v[-2000:]}"
                       for k, v in sorted(logs.items()))
    assert proc.returncode == 0, \
        f"{proc.stdout}\n{proc.stderr}\n{detail}"
    g0 = logs.get("elastic_g0_rank0.log", "")
    # generation 0: the survivor aborted naming the stuck collective
    assert "WORKER ABORT" in g0, detail
    assert "spmd/sync_bin_mappers" in g0, detail
    # generation 1: fault stripped, full re-ingest + training finished
    g1 = logs.get("elastic_g1_rank0.log", "")
    assert "INGEST_PARITY_OK" in g1, detail
    assert "DONE" in g1, detail
    assert (tmp_path / "chaos" / "model_stream.txt").exists(), detail
