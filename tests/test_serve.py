"""Production inference serving (lightgbm_tpu/serve/, docs/SERVING.md).

Layers under test:

1. Forest compiler (serve/compile.py): compiled-vs-eager prediction
   equivalence across every tree type (numeric, categorical,
   linear-tree, multiclass raw scores), power-of-two bucketing, the
   recompile-counter-flat-after-warmup contract (TPL003's serving
   invariant), and the donated hot-swap upload.
2. Micro-batcher (serve/batcher.py): request coalescing, concurrent
   submits, backpressure, hot swap with zero dropped in-flight
   requests.
3. Daemon (serve/daemon.py): the JSON-lines protocol as a pure
   function (fast), the jax-free CLI parse contract (subprocess, like
   `lint`), serve telemetry summarization + the stats CLI row, and —
   `slow`-marked because they spin real sockets/worlds — the live
   socket server, watch-dir hot swap, a launch-supervised replica
   chaos kill, and the bench.py --serve acceptance record.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs import RecompileWatcher  # noqa: E402
from lightgbm_tpu.serve.batcher import (  # noqa: E402
    MicroBatcher, QueueFullError)
from lightgbm_tpu.serve.compile import (  # noqa: E402
    bucket_rows, compile_forest)

from tests._mp_utils import REPO_DIR, free_port, kill_group  # noqa: E402
from tests.conftest import make_synthetic_binary  # noqa: E402

RS = np.random.RandomState(31)


def _train(params, X, y, rounds=5, **ds_kwargs):
    ds = lgb.Dataset(X, label=y,
                     params={"verbosity": -1,
                             **ds_kwargs.pop("ds_params", {})},
                     **ds_kwargs)
    return lgb.train({"verbosity": -1, **params}, ds,
                     num_boost_round=rounds)


@pytest.fixture(scope="module")
def binary_model():
    X, y = make_synthetic_binary(n=600, f=8, seed=3)
    return _train({"objective": "binary", "num_leaves": 15}, X, y), X


@pytest.fixture(scope="module")
def multiclass_model():
    X, _ = make_synthetic_binary(n=500, f=6, seed=5)
    y = (np.abs(X[:, 0]) + X[:, 1] > 0.6).astype(int) \
        + (X[:, 2] > 0.5).astype(int)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "num_leaves": 7}, X, y.astype(np.float64), rounds=4)
    return bst, X


@pytest.fixture(scope="module")
def categorical_model():
    n = 500
    Xn = RS.randn(n, 3)
    cat = RS.randint(0, 6, n).astype(np.float64)
    X = np.column_stack([Xn, cat])
    y = ((Xn[:, 0] > 0) ^ (cat >= 3)).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1},
                     categorical_feature=[3])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5)
    return bst, X


@pytest.fixture(scope="module")
def linear_model():
    X, _ = make_synthetic_binary(n=500, f=5, seed=11)
    y = X @ RS.randn(5) + 0.05 * RS.randn(500)
    bst = _train({"objective": "regression", "num_leaves": 7,
                  "linear_tree": True}, X, y)
    return bst, X


def _fresh(bst):
    """An uncompiled clone: the eager baseline path."""
    return lgb.Booster(model_str=bst.model_to_string())


# ---------------------------------------------------------------------
# 1. forest compiler
# ---------------------------------------------------------------------

def test_bucket_rows():
    assert bucket_rows(1) == 16
    assert bucket_rows(16) == 16
    assert bucket_rows(17) == 32
    assert bucket_rows(1000) == 1024
    assert bucket_rows(10 ** 9, max_bucket=4096) == 4096
    assert bucket_rows(5, min_bucket=1, max_bucket=8) == 8
    with pytest.raises(ValueError):
        bucket_rows(0)


@pytest.mark.parametrize("fixture,raw", [
    ("binary_model", False), ("binary_model", True),
    ("multiclass_model", False), ("multiclass_model", True),
    ("categorical_model", False), ("linear_model", False),
])
def test_compiled_matches_eager(fixture, raw, request):
    """Equivalence across tree types: the compiled bucketed program
    and the eager library path answer identically (same f32 ops, same
    order) for ad-hoc batch sizes, including padded ones."""
    bst, X = request.getfixturevalue(fixture)
    eager = _fresh(bst)
    cf = compile_forest(bst, max_batch_rows=256)
    for n in (1, 7, 33, 123):
        Xq = X[:n]
        want = eager.predict(Xq, raw_score=raw)
        got = cf.predict(Xq, raw_score=raw)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-9,
                                   err_msg=f"{fixture} n={n} raw={raw}")


def test_booster_predict_routes_through_compiled(binary_model):
    bst, X = binary_model
    eager_pred = _fresh(bst).predict(X[:50])
    cf = bst.compile(max_batch_rows=256)
    assert bst._compiled_forest is cf
    np.testing.assert_allclose(bst.predict(X[:50]), eager_pred,
                               rtol=0, atol=1e-9)
    # chunking: a request larger than max_batch_rows splits cleanly
    np.testing.assert_allclose(bst.predict(X[:300]),
                               _fresh(bst).predict(X[:300]),
                               rtol=0, atol=1e-9)


def test_recompile_counter_flat_after_warmup(binary_model):
    """THE serving contract: after bucket warmup, 10 varied batch
    sizes cause ZERO recompiles of any registered jit entry point."""
    bst, X = binary_model
    cf = bst.compile(max_batch_rows=1024)
    cf.warmup()
    watch = RecompileWatcher()
    for n in (1, 3, 17, 100, 255, 256, 257, 512, 700, 1000):
        Xq = RS.randn(n, X.shape[1])
        bst.predict(Xq)          # routed through the compiled forest
        cf.predict_raw(Xq.astype(np.float32))
    assert watch.delta() == 0, (
        "a batch size recompiled after warmup — the shape-bucket "
        "invariant is broken")


def test_compiled_bypassed_when_booster_grows():
    """Training past a compilation silently bypasses it: predict must
    answer from ALL trees via the eager path, never from the stale
    compiled forest."""
    X, y = make_synthetic_binary(n=400, f=6, seed=9)
    ds = lgb.Dataset(X, label=y, params={"verbosity": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=3)
    cf = bst.compile()
    before = bst.predict(X[:20])
    np.testing.assert_allclose(before, _fresh(bst).predict(X[:20]),
                               rtol=0, atol=1e-9)
    bst.update()                       # grow one more tree in place
    assert bst.num_trees() == 4
    assert not cf.matches(0, bst.num_trees(), bst.num_trees())
    # explicit full range: must answer from all 4 trees via the eager
    # fallback, never from the stale 3-tree compilation
    full = bst.predict(X[:20], num_iteration=4)
    np.testing.assert_allclose(
        full, _fresh(bst).predict(X[:20], num_iteration=4),
        rtol=0, atol=1e-9)
    assert not np.allclose(full, before), \
        "the extra tree changed nothing — bypass not actually proven"


def test_compile_respects_num_iteration(binary_model):
    bst, X = binary_model
    cf = compile_forest(bst, num_iteration=2)
    want = _fresh(bst).predict(X[:40], num_iteration=2)
    np.testing.assert_allclose(cf.predict(X[:40]), want,
                               rtol=0, atol=1e-9)
    # and the routed path only engages for a matching range
    bst.compile(num_iteration=2)
    np.testing.assert_allclose(
        bst.predict(X[:40], num_iteration=2), want, rtol=0, atol=1e-9)
    np.testing.assert_allclose(
        bst.predict(X[:40]), _fresh(bst).predict(X[:40]),
        rtol=0, atol=1e-9)


def test_feature_count_mismatch_raises(binary_model):
    bst, X = binary_model
    cf = compile_forest(bst)
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        cf.predict_raw(np.zeros((4, X.shape[1] + 2), np.float32))


def test_hot_swap_donated_upload(binary_model):
    """compile_forest(reuse=...) adopts the old forest's buffers when
    layouts match and must answer with the NEW model either way."""
    bst, X = binary_model
    cf_a = compile_forest(bst, max_batch_rows=256)
    a_pred = cf_a.predict(X[:30])
    # same shape config -> same stacked layout -> donated upload
    y2 = (X[:, 1] > 0).astype(np.float64)
    bst_b = _train({"objective": "binary", "num_leaves": 15}, X, y2)
    cf_b = compile_forest(bst_b, max_batch_rows=256, reuse=cf_a)
    assert cf_a._stacked is None, "donated forest must be dead"
    np.testing.assert_allclose(cf_b.predict(X[:30]),
                               _fresh(bst_b).predict(X[:30]),
                               rtol=0, atol=1e-9)
    assert not np.allclose(cf_b.predict(X[:30]), a_pred)
    # different layout (more leaves) -> plain transfer, same contract
    bst_c = _train({"objective": "binary", "num_leaves": 31}, X, y2,
                   rounds=7)
    cf_c = compile_forest(bst_c, max_batch_rows=256, reuse=cf_b)
    np.testing.assert_allclose(cf_c.predict(X[:30]),
                               _fresh(bst_c).predict(X[:30]),
                               rtol=0, atol=1e-9)


def test_dead_forest_raises_and_booster_falls_back(binary_model):
    """A forest whose buffers a newer compilation took over must raise
    on direct use — and a booster still caching it must fall back to
    the eager path, never serve donated garbage or silent zeros."""
    bst, X = binary_model
    want = _fresh(bst).predict(X[:10])
    cf_old = bst.compile(max_batch_rows=256)
    y2 = (X[:, 1] > 0).astype(np.float64)
    bst_b = _train({"objective": "binary", "num_leaves": 15}, X, y2)
    compile_forest(bst_b, max_batch_rows=256, reuse=cf_old)
    assert cf_old._dead
    with pytest.raises(RuntimeError, match="donated"):
        cf_old.predict_raw(X[:4].astype(np.float32))
    assert not cf_old.matches(cf_old.lo, cf_old.hi, cf_old.total_trees)
    np.testing.assert_allclose(bst.predict(X[:10]), want,
                               rtol=0, atol=1e-9)


def test_zero_row_predict(binary_model):
    bst, X = binary_model
    cf = compile_forest(bst, max_batch_rows=256)
    out = cf.predict_raw(np.empty((0, X.shape[1]), np.float32))
    assert out.shape == (0, 1)
    bst.compile(max_batch_rows=256)
    assert bst.predict(np.empty((0, X.shape[1]))).shape == (0,)


# ---------------------------------------------------------------------
# 2. micro-batcher
# ---------------------------------------------------------------------

def test_batcher_resolves_concurrent_requests(binary_model):
    bst, X = binary_model
    cf = compile_forest(bst, max_batch_rows=256)
    cf.warmup()
    mb = MicroBatcher(cf, batch_window_ms=2.0, max_batch_rows=256)
    try:
        sizes = [1, 5, 9, 17, 3, 40]
        futs = {}
        for i, n in enumerate(sizes):
            futs[i] = (mb.submit(X[i: i + n]), X[i: i + n])
        for i, (fut, Xq) in futs.items():
            got = fut.result(timeout=30)
            np.testing.assert_allclose(
                got, cf.predict_raw(Xq), rtol=0, atol=1e-9)
        st = mb.stats()
        assert st["requests_total"] == len(sizes)
        assert st["rows_total"] == sum(sizes)
        assert st["queue_depth_rows"] == 0
        assert st["p50_ms"] is not None
    finally:
        mb.close()


def test_batcher_backpressure(binary_model):
    bst, X = binary_model
    cf = compile_forest(bst, max_batch_rows=256)

    class _Slow:
        n_features = cf.n_features

        def __init__(self):
            self.release = threading.Event()

        def predict_raw(self, Xq):
            self.release.wait(30)
            return cf.predict_raw(Xq)

    slow = _Slow()
    # budget 32: the in-flight batch (8 rows, still pending until it
    # finishes) + one queued 16-row request fit; the next 16 do not
    mb = MicroBatcher(slow, batch_window_ms=0.0, max_batch_rows=8,
                      queue_max_rows=32)
    try:
        first = mb.submit(X[:8])      # occupies the worker
        time.sleep(0.05)
        second = mb.submit(X[:16])    # queued within budget
        with pytest.raises(QueueFullError):
            mb.submit(X[:16])
        assert mb.stats()["rejected_total"] == 1
        slow.release.set()
        first.result(timeout=30)
        second.result(timeout=30)
    finally:
        slow.release.set()
        mb.close()


def test_batcher_feature_mismatch(binary_model):
    bst, _ = binary_model
    cf = compile_forest(bst)
    mb = MicroBatcher(cf)
    try:
        with pytest.raises(ValueError, match="features"):
            mb.submit(np.zeros((2, cf.n_features + 1), np.float32))
    finally:
        mb.close()


def test_hot_swap_zero_dropped_requests(binary_model):
    """Requests in flight across a swap ALL resolve; post-swap answers
    come from the new model."""
    bst, X = binary_model
    cf_a = compile_forest(bst, max_batch_rows=256)
    cf_a.warmup(64)
    y2 = (X[:, 1] > 0).astype(np.float64)
    bst_b = _train({"objective": "binary", "num_leaves": 15}, X, y2)
    cf_b = compile_forest(bst_b, max_batch_rows=256)
    cf_b.warmup(64)
    a_ref = cf_a.predict_raw(X[:4])
    b_ref = cf_b.predict_raw(X[:4])
    assert not np.allclose(a_ref, b_ref)

    mb = MicroBatcher(cf_a, batch_window_ms=0.5, max_batch_rows=64)
    results = []
    res_lock = threading.Lock()
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                fut = mb.submit(X[:4])
            except QueueFullError:
                continue
            out = fut.result(timeout=30)
            with res_lock:
                results.append(out)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        mb.swap(cf_b)
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        mb.close()
    assert results, "hammer threads produced nothing"
    matched = 0
    for out in results:
        is_a = np.allclose(out, a_ref, atol=1e-9)
        is_b = np.allclose(out, b_ref, atol=1e-9)
        assert is_a or is_b, "a request resolved to NEITHER model"
        matched += is_b
    assert matched, "no request ever answered from the swapped model"
    # the tail of the stream must be the new model
    np.testing.assert_allclose(results[-1], b_ref, rtol=0, atol=1e-9)
    assert mb.stats()["swaps_total"] == 1


# ---------------------------------------------------------------------
# 3. daemon protocol (pure-function fast tests)
# ---------------------------------------------------------------------

def _make_state(bst, tmp_path=None, telemetry=None):
    from lightgbm_tpu.serve.daemon import ServeState
    cf = compile_forest(bst, max_batch_rows=256)
    cf.warmup(64)
    mb = MicroBatcher(cf, batch_window_ms=0.5, max_batch_rows=256)
    state = ServeState(mb, cf.model_id, "test-model",
                       telemetry_path=telemetry)
    return state, cf


def test_handle_request_protocol(binary_model):
    from lightgbm_tpu.serve.daemon import handle_request
    bst, X = binary_model
    state, cf = _make_state(bst)
    try:
        r = handle_request({"cmd": "ping"}, state)
        assert r["ok"] and r["model"] == cf.model_id
        assert r["pid"] == os.getpid()

        r = handle_request({"rows": X[:3].tolist()}, state)
        np.testing.assert_allclose(r["predictions"],
                                   _fresh(bst).predict(X[:3]),
                                   rtol=0, atol=1e-9)
        assert r["n"] == 3 and r["model"] == cf.model_id

        r = handle_request({"features": X[0].tolist()}, state)
        assert len(r["predictions"]) == 1

        r = handle_request({"rows": X[:3].tolist(), "raw": True},
                           state)
        np.testing.assert_allclose(
            r["predictions"],
            _fresh(bst).predict(X[:3], raw_score=True),
            rtol=0, atol=1e-9)

        st = handle_request({"cmd": "stats"}, state)
        assert st["ok"] and st["requests_total"] >= 3
        assert "qps" in st and "hbm" in st and "recompiles" in st

        assert "error" in handle_request({"cmd": "nope"}, state)
        assert "error" in handle_request({"rows": "zzz"}, state)
        assert "error" in handle_request({"rows": []}, state)
        assert "error" in handle_request(["not", "a", "dict"], state)
        assert "error" in handle_request({}, state)

        r = handle_request({"cmd": "shutdown"}, state)
        assert r["shutting_down"] and state.shutdown_event.is_set()
    finally:
        state.close()


def test_handle_request_overload_maps_to_error(binary_model):
    from lightgbm_tpu.serve.daemon import handle_request
    bst, X = binary_model
    state, _ = _make_state(bst)
    try:
        def full(_rows):
            raise QueueFullError("serve queue full: test")
        state.batcher.submit = full
        r = handle_request({"rows": X[:2].tolist()}, state)
        assert r.get("overloaded") and "error" in r
    finally:
        state.close()


def test_watcher_poll_swaps_and_survives_corrupt_model(
        binary_model, tmp_path):
    from lightgbm_tpu.serve.daemon import _Watcher
    bst, X = binary_model
    state, cf = _make_state(bst)
    try:
        model_a = str(tmp_path / "a.txt")
        bst.save_model(model_a)
        from lightgbm_tpu.serve.daemon import _artifact_key
        watcher = _Watcher(
            state, str(tmp_path), 0.1,
            dict(num_iteration=-1, min_bucket=16, max_batch_rows=256),
            _artifact_key(model_a), 64)
        assert watcher.poll_once() is False     # nothing new

        y2 = (X[:, 1] > 0).astype(np.float64)
        bst_b = _train({"objective": "binary", "num_leaves": 15},
                       X, y2)
        time.sleep(0.05)
        bst_b.save_model(str(tmp_path / "b.txt"))
        os.utime(str(tmp_path / "b.txt"),
                 (time.time() + 2, time.time() + 2))
        assert watcher.poll_once() is True
        assert state.model_id() == \
            compile_forest(bst_b).model_id
        fut = state.batcher.submit(X[:4].astype(np.float32))
        np.testing.assert_allclose(
            fut.result(timeout=30),
            _fresh(bst_b).predict(X[:4], raw_score=True)[:, None],
            rtol=0, atol=1e-9)

        # corrupt artifact: swap fails, old model keeps serving
        with open(tmp_path / "c.txt", "w") as fh:
            fh.write("this is not a model\n")
        os.utime(str(tmp_path / "c.txt"),
                 (time.time() + 4, time.time() + 4))
        before = state.model_id()
        assert watcher.poll_once() is False
        assert state.model_id() == before
        assert state.stats()["swap_failures"] == 1
    finally:
        state.close()


def test_serve_telemetry_and_stats_cli(binary_model, tmp_path):
    from lightgbm_tpu.obs import render_stats_table, summarize_events
    bst, X = binary_model
    telem = str(tmp_path / "serve.jsonl")
    state, cf = _make_state(bst, telemetry=telem)
    try:
        from lightgbm_tpu.serve.daemon import handle_request
        handle_request({"rows": X[:5].tolist()}, state)
        state.emit_serve_event()
        handle_request({"rows": X[:2].tolist()}, state)
        state.emit_serve_event()
    finally:
        state.close()
    summ = summarize_events(telem)
    assert summ["iterations"] == 0
    assert summ["serve_events"] == 2
    assert summ["serve"]["requests_total"] == 2
    assert summ["serve"]["rows_total"] == 7
    assert summ["serve"]["model"] == cf.model_id
    table = render_stats_table(summ)
    assert "serve" in table and cf.model_id in table
    # the stats CLI accepts a serve-only stream (no iteration events)
    from lightgbm_tpu.cli import main as cli_main
    assert cli_main(["stats", telem]) == 0
    assert cli_main(["stats", str(tmp_path / "missing.jsonl")]) == 1


def test_serve_cli_is_jax_free_until_model_load(tmp_path):
    """`python -m lightgbm_tpu serve --help` and bad-path errors must
    not import jax (the lint/launch contract, subprocess-proved)."""
    code = (
        "import sys\n"
        "from lightgbm_tpu.serve.daemon import main\n"
        "rc = main(['--help'])\n"
        "assert rc == 0, rc\n"
        "rc = main(['/nonexistent/model.txt'])\n"
        "assert rc == 1, rc\n"
        "assert 'jax' not in sys.modules, 'serve CLI imported jax!'\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_DIR,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    assert "usage: python -m lightgbm_tpu serve" in proc.stdout


# ---------------------------------------------------------------------
# 4. live socket / supervised-replica tests (slow: real sockets)
# ---------------------------------------------------------------------

def _rpc(fh, obj):
    fh.write(json.dumps(obj) + "\n")
    fh.flush()
    line = fh.readline()
    assert line, "daemon closed the connection unexpectedly"
    return json.loads(line)


def _read_ready(proc, tries=200):
    """Skim the daemon's stdout for the serve_ready JSON line (library
    log lines may precede it)."""
    for _ in range(tries):
        line = proc.stdout.readline()
        if not line:
            raise AssertionError("daemon exited before serve_ready")
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if obj.get("event") == "serve_ready":
            return obj
    raise AssertionError("no serve_ready line in daemon output")


def _connect(port, timeout=60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            s = socket.create_connection(("127.0.0.1", port),
                                         timeout=10)
            return s, s.makefile("rw")
        except OSError as e:
            last = e
            time.sleep(0.2)
    raise AssertionError(f"could not connect to daemon on :{port}: "
                         f"{last}")


@pytest.mark.slow
def test_daemon_socket_end_to_end(binary_model, tmp_path):
    bst, X = binary_model
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    telem = str(tmp_path / "serve.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", "0", "--watch-dir", str(tmp_path),
         "--telemetry", telem, "--stats-interval", "0.5",
         "--watch-interval", "0.2", "--warmup-rows", "64",
         "--max-batch-rows", "256"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO_DIR, start_new_session=True)
    try:
        ready = _read_ready(proc)
        s, fh = _connect(ready["port"])
        try:
            r = _rpc(fh, {"rows": X[:5].tolist()})
            np.testing.assert_allclose(r["predictions"],
                                       _fresh(bst).predict(X[:5]),
                                       rtol=0, atol=1e-9)
            assert _rpc(fh, {"cmd": "ping"})["ok"]

            # hot swap through the watch dir (atomic save_model)
            y2 = (X[:, 1] > 0).astype(np.float64)
            bst_b = _train({"objective": "binary", "num_leaves": 15},
                           X, y2)
            time.sleep(0.2)
            bst_b.save_model(str(tmp_path / "model_v2.txt"))
            os.utime(str(tmp_path / "model_v2.txt"),
                     (time.time() + 2, time.time() + 2))
            want_b = _fresh(bst_b).predict(X[:5])
            deadline = time.time() + 60
            swapped = False
            while time.time() < deadline and not swapped:
                r = _rpc(fh, {"rows": X[:5].tolist()})
                swapped = np.allclose(r["predictions"], want_b,
                                      atol=1e-9)
                if not swapped:
                    time.sleep(0.2)
            assert swapped, "daemon never hot-swapped to model_v2"

            st = _rpc(fh, {"cmd": "stats"})
            assert st["swaps_total"] == 1
            r = _rpc(fh, {"cmd": "shutdown"})
            assert r["shutting_down"]
        finally:
            s.close()
        assert proc.wait(timeout=60) == 0
        with open(telem) as fhh:
            events = [json.loads(ln) for ln in fhh if ln.strip()]
        assert any(e.get("event") == "serve" and e.get("swaps_total")
                   for e in events)
    finally:
        if proc.poll() is None:
            kill_group(proc)


@pytest.mark.slow
def test_replica_kill_under_launch_recovers(binary_model, tmp_path):
    """Chaos: two serve replicas under the elastic supervisor; SIGKILL
    one -> the supervisor restarts the world -> both ports answer
    again (docs/SERVING.md multi-replica operation)."""
    bst, X = binary_model
    model = str(tmp_path / "model.txt")
    bst.save_model(model)
    base = free_port()
    sup = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "launch", "2",
         "--max-restarts", "2", "--grace", "1",
         "--log-dir", str(tmp_path), "--",
         sys.executable, "-m", "lightgbm_tpu", "serve", model,
         "--port", str(base), "--warmup-rows", "64",
         "--max-batch-rows", "256"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=REPO_DIR, start_new_session=True)
    want = _fresh(bst).predict(X[:3])
    try:
        pids = {}
        for rank in (0, 1):
            s, fh = _connect(base + rank, timeout=120)
            r = _rpc(fh, {"cmd": "ping"})
            pids[rank] = r["pid"]
            r = _rpc(fh, {"rows": X[:3].tolist()})
            np.testing.assert_allclose(r["predictions"], want,
                                       rtol=0, atol=1e-9)
            s.close()

        os.kill(pids[1], signal.SIGKILL)      # chaos: kill replica 1

        # the supervisor tears the world down and relaunches; the old
        # connections die, fresh ones must eventually answer with NEW
        # pids on the same ports
        deadline = time.time() + 180
        new_pid = None
        while time.time() < deadline:
            try:
                s, fh = _connect(base + 1, timeout=20)
                r = _rpc(fh, {"cmd": "ping"})
                if r.get("pid") not in (None, pids[1]):
                    new_pid = r["pid"]
                    r = _rpc(fh, {"rows": X[:3].tolist()})
                    np.testing.assert_allclose(
                        r["predictions"], want, rtol=0, atol=1e-9)
                    s.close()
                    break
                s.close()
            except (AssertionError, OSError, ValueError):
                pass
            time.sleep(0.5)
        assert new_pid is not None, (
            "replica 1 never came back under the supervisor")
        # replica 0 was also restarted and serves
        s, fh = _connect(base, timeout=120)
        r = _rpc(fh, {"rows": X[:3].tolist()})
        np.testing.assert_allclose(r["predictions"], want,
                                   rtol=0, atol=1e-9)
        s.close()
    finally:
        kill_group(sup)
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass


@pytest.mark.slow
def test_bench_serve_mode_contract(tmp_path):
    """Acceptance: bench.py --serve emits the serve block with
    compiled rows/sec >= the eager baseline and p50/p99 present."""
    env = {"PATH": os.environ.get("PATH", "/usr/bin:/bin"),
           "HOME": os.environ.get("HOME", "/root"),
           "BENCH_PLATFORM": "cpu", "BENCH_ROWS": "4000",
           "BENCH_VALID": "1000", "BENCH_ITERS": "2",
           "BENCH_AUC_ITERS": "5", "BENCH_LEAVES": "15",
           "BENCH_BINS": "31", "BENCH_SERVE": "1",
           "BENCH_DEADLINE": "700"}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_DIR, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    serve = rec["serve"]
    assert serve["recompiles_after_warmup"] == 0
    assert serve["p50_ms"] > 0 and serve["p99_ms"] >= serve["p50_ms"]
    assert serve["rows_per_sec_compiled"] >= \
        serve["rows_per_sec_eager"], serve
