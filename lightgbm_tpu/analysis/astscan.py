"""Per-module AST scanning (pure stdlib — importing this never pulls jax).

One :class:`ModuleScan` per source file records everything the
call-graph builder and the rules need:

- every function/method definition (including nested closures) with its
  dotted qualname (``GBDTBooster._get_fused_fn.step``),
- the import table (local alias -> absolute dotted path),
- module-level aliases (``grow_tree = jax.jit(grow_tree_impl, ...)``),
- ``# tpulint:`` pragmas (``hot`` hot-path markers and
  ``disable=TPLNNN`` inline suppressions).

Scanning is purely lexical/structural; resolution across modules
happens in :mod:`~lightgbm_tpu.analysis.callgraph`.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["FuncInfo", "JitWrap", "ModuleScan", "dotted_of",
           "jit_wrap_kind", "literal_int_tuple", "literal_str_tuple"]

#: names that wrap a python function into a traced/compiled entry point.
#: Matched on the *basename* of the resolved dotted path so that local
#: compatibility shims (e.g. parallel/data_parallel.py's ``shard_map``
#: wrapper around the moving jax API) count as tracing wrappers too.
_JIT_BASENAMES = {"jit", "pjit", "shard_map"}

_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*(.+?)\s*$")


@dataclass
class JitWrap:
    """One jit/pjit/shard_map wrapping of a function."""
    kind: str                                   # "jit" | "shard_map"
    lineno: int
    static_argnums: Optional[Tuple[int, ...]] = None
    static_argnames: Optional[Tuple[str, ...]] = None
    donate_argnums: Optional[Tuple[int, ...]] = None


@dataclass
class FuncInfo:
    """A function or method definition."""
    relpath: str                                # "ops/grow.py"
    qual: str                                   # "Class.meth.inner"
    name: str
    lineno: int
    end_lineno: int
    node: ast.AST
    params: Tuple[str, ...]                     # positional-or-kw order
    class_name: Optional[str] = None            # innermost class
    parent_qual: Optional[str] = None           # enclosing function
    decorator_wrap: Optional[JitWrap] = None    # @jax.jit-style
    wrappers: List[JitWrap] = field(default_factory=list)
    is_hot: bool = False                        # "# tpulint: hot"

    @property
    def key(self) -> Tuple[str, str]:
        return (self.relpath, self.qual)


def dotted_of(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None (raw, unresolved
    against the import table — callers resolve the root)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """A literal int or tuple-of-ints, else None (dynamic)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, int) \
                    and not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def literal_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def jit_wrap_kind(dotted: Optional[str]) -> Optional[str]:
    """Classify a resolved dotted callable as a tracing wrapper."""
    if not dotted:
        return None
    base = dotted.rsplit(".", 1)[-1]
    if base not in _JIT_BASENAMES:
        return None
    return "shard_map" if base == "shard_map" else "jit"


def _wrap_from_call_kwargs(kind: str, lineno: int,
                           keywords) -> JitWrap:
    w = JitWrap(kind=kind, lineno=lineno)
    for kw in keywords or ():
        if kw.arg == "static_argnums":
            w.static_argnums = literal_int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            w.static_argnames = literal_str_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            w.donate_argnums = literal_int_tuple(kw.value)
    return w


class ModuleScan:
    """Phase-1 scan of one source file."""

    def __init__(self, relpath: str, source: str, module: str):
        self.relpath = relpath
        self.module = module                    # dotted module name
        # a package __init__ IS its package: relative imports resolve
        # against the module itself, not its parent
        self.is_package = relpath.endswith("__init__.py")
        self.tree = ast.parse(source, filename=relpath)
        self.lines = source.splitlines()
        self.funcs: Dict[str, FuncInfo] = {}
        # class name -> base-class dotted names (raw, unresolved):
        # TPL008 seeds socketserver/http.server request-handler
        # subclasses as thread-side (their do_*/handle methods run on
        # the serving stack's daemon threads, not the main path)
        self.class_bases: Dict[str, Tuple[str, ...]] = {}
        self.imports: Dict[str, str] = {}       # module-level aliases
        # module-level name -> ("func", qual) | ("wrapper", qual, JitWrap)
        self.aliases: Dict[str, tuple] = {}
        # class attr wrappers: (class, attr) -> (target_qual, JitWrap)
        self.attr_wrappers: Dict[Tuple[str, str], tuple] = {}
        self.hot_lines: Set[int] = set()
        self.disable_lines: Dict[int, Set[str]] = {}
        # "# tpulint: threadsafe <why>" — line -> justification text.
        # TPL008 accepts the mark only with a non-empty why (an
        # acceptance without a reason is just a suppressed race).
        self.threadsafe_lines: Dict[int, str] = {}
        # "# tpulint: replicated-cond <why>" — line -> justification.
        # TPL010 accepts a device collective under a traced lax.cond
        # only with a non-empty why naming the replicated-predicate
        # argument (a bare mark is just a suppressed deadlock).
        self.replicated_cond_lines: Dict[int, str] = {}
        self._scan_pragmas()
        self._collect(self.tree, [], [], None)
        self._collect_module_imports()
        self._collect_module_aliases()

    # -- pragmas -------------------------------------------------------
    def _scan_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if not m:
                continue
            body = m.group(1)
            # marker tokens are read from the FRONT of the pragma body
            # only; the first non-marker token starts the free-text
            # justification (so a justification containing the word
            # "hot" never hot-marks the line)
            for token in body.split():
                if token == "hot":
                    self.hot_lines.add(i)
                elif token.startswith("disable="):
                    rules = {r.strip() for r in
                             token[len("disable="):].split(",") if r}
                    self.disable_lines.setdefault(i, set()).update(rules)
                elif token == "threadsafe":
                    # everything after the marker is the required why
                    why = body.split("threadsafe", 1)[1].strip()
                    self.threadsafe_lines[i] = why
                    break
                elif token == "replicated-cond":
                    why = body.split("replicated-cond", 1)[1].strip()
                    self.replicated_cond_lines[i] = why
                    break
                else:
                    break

    def suppressed(self, rule: str, lineno: int) -> bool:
        """A ``disable=`` pragma on the finding's line or the line
        directly above it suppresses the rule there."""
        for ln in (lineno, lineno - 1):
            if rule in self.disable_lines.get(ln, ()):
                return True
        return False

    # -- defs ----------------------------------------------------------
    def _collect(self, node, quals: List[str], classes: List[str],
                 parent_qual: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self.class_bases[child.name] = tuple(
                    dotted_of(b) or "" for b in child.bases)
                self._collect(child, quals + [child.name],
                              classes + [child.name], parent_qual)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = ".".join(quals + [child.name])
                a = child.args
                params = tuple(p.arg for p in
                               (a.posonlyargs + a.args))
                info = FuncInfo(
                    relpath=self.relpath, qual=qual, name=child.name,
                    lineno=child.lineno,
                    end_lineno=getattr(child, "end_lineno",
                                       child.lineno),
                    node=child, params=params,
                    class_name=classes[-1] if classes else None,
                    parent_qual=parent_qual,
                    decorator_wrap=self._decorator_wrap(child),
                )
                deco_line = min([child.lineno]
                                + [d.lineno for d in
                                   child.decorator_list])
                if self.hot_lines & {child.lineno, child.lineno - 1,
                                     deco_line, deco_line - 1}:
                    info.is_hot = True
                self.funcs[qual] = info
                self._collect(child, quals + [child.name], classes,
                              qual)
            else:
                self._collect(child, quals, classes, parent_qual)

    def _decorator_wrap(self, fn) -> Optional[JitWrap]:
        """``@jax.jit`` / ``@partial(jax.jit, ...)`` / ``@jax.jit(...)``
        decorators. Raw dotted names only — the callgraph re-checks the
        basename rule, which is import-alias-proof in practice because
        jit/pjit/shard_map are never locally renamed to something
        else."""
        for deco in fn.decorator_list:
            kind = jit_wrap_kind(dotted_of(deco))
            if kind:
                return JitWrap(kind=kind, lineno=deco.lineno)
            if isinstance(deco, ast.Call):
                fk = jit_wrap_kind(dotted_of(deco.func))
                if fk:  # @jax.jit(static_argnums=...)
                    return _wrap_from_call_kwargs(fk, deco.lineno,
                                                  deco.keywords)
                base = dotted_of(deco.func) or ""
                if base.rsplit(".", 1)[-1] == "partial" and deco.args:
                    inner = jit_wrap_kind(dotted_of(deco.args[0]))
                    if inner:  # @functools.partial(jax.jit, ...)
                        return _wrap_from_call_kwargs(
                            inner, deco.lineno, deco.keywords)
        return None

    # -- imports -------------------------------------------------------
    def _collect_module_imports(self) -> None:
        for node in ast.walk(self.tree):
            for name, dotted in self.import_bindings(node):
                self.imports.setdefault(name, dotted)

    def import_bindings(self, node) -> List[Tuple[str, str]]:
        """(local name, absolute dotted) pairs introduced by an
        import statement (anywhere — function-local imports included)."""
        out: List[Tuple[str, str]] = []
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".", 1)[0]
                dotted = alias.name if alias.asname else \
                    alias.name.split(".", 1)[0]
                out.append((local, dotted))
        elif isinstance(node, ast.ImportFrom):
            base = self._resolve_from_base(node)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                dotted = f"{base}.{alias.name}" if base else alias.name
                out.append((local, dotted))
        return out

    def _resolve_from_base(self, node: ast.ImportFrom) -> str:
        if node.level == 0:
            return node.module or ""
        # relative: level 1 = the containing package (the module
        # itself for a package __init__), each further level one up
        parts = self.module.split(".")
        if not self.is_package:
            parts = parts[:-1]
        up = node.level - 1
        if up:
            parts = parts[:-up] if up < len(parts) else []
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    # -- module-level aliases ------------------------------------------
    def _collect_module_aliases(self) -> None:
        for node in self.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            got = self._wrap_or_func(node.value)
            if got is None:
                continue
            if isinstance(target, ast.Name):
                self.aliases[target.id] = got
        # class-body / method-body `self.x = jax.jit(...)` wrappers
        for info in self.funcs.values():
            if info.class_name is None:
                continue
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                got = self._wrap_or_func(node.value)
                if got is not None and got[0] == "wrapper":
                    self.attr_wrappers[(info.class_name, t.attr)] = \
                        (got[1], got[2])

    def _wrap_or_func(self, value: ast.AST):
        """Classify an assignment RHS: a known local function, or a
        jit-wrapping of one (possibly nested in register_jit(...))."""
        if isinstance(value, ast.Name) and value.id in self.funcs:
            return ("func", value.id)
        if isinstance(value, ast.Call):
            base = dotted_of(value.func) or ""
            if base.rsplit(".", 1)[-1] == "register_jit":
                for arg in value.args:
                    inner = self._wrap_or_func(arg)
                    if inner is not None and inner[0] == "wrapper":
                        return inner
                return None
            kind = jit_wrap_kind(base)
            if kind and value.args:
                target = value.args[0]
                if isinstance(target, ast.Name):
                    w = _wrap_from_call_kwargs(kind, value.lineno,
                                               value.keywords)
                    return ("wrapper", target.id, w)
        return None
