"""Label-keyed, thread-safe metric primitives.

The shape follows the prometheus client-library contract (counter /
gauge / histogram families keyed by a label set) because that is the
vocabulary every downstream consumer of these numbers already speaks,
but storage is plain Python: a metric family is a dict from a sorted
``(key, value)`` label tuple to one instrument object.

Thread safety: callbacks may fire from user threads and the deferred
tree materialization path runs off async device copies, so every
mutation takes the registry's single RLock. Instruments are tiny (a few
floats); one lock for the whole registry keeps the disabled/idle cost at
zero and the enabled cost far below any phase being measured.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "bump_counter"]

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, object]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("Counter can only increase")
        with self._lock:
            self.value += amount

    def snapshot(self) -> float:
        return self.value


class Gauge:
    """Last-set value (plus the running max, for peak-style gauges)."""

    __slots__ = ("_lock", "value", "max_value")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value: Optional[float] = None
        self.max_value: Optional[float] = None

    def set(self, value: Optional[float]) -> None:
        with self._lock:
            self.value = value
            if value is not None and (self.max_value is None
                                      or value > self.max_value):
                self.max_value = value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value = (self.value or 0.0) + amount
            if self.max_value is None or self.value > self.max_value:
                self.max_value = self.value

    def snapshot(self) -> Dict[str, Optional[float]]:
        return {"value": self.value, "max": self.max_value}


class Histogram:
    """Streaming distribution: count / total / min / max / mean.

    Used for both time histograms (seconds observed per phase) and value
    histograms (leaves per tree, gain per split). Full bucketing is more
    than the consumers need — the stats CLI and the JSONL events report
    count/total/mean — so only the moments are kept.
    """

    __slots__ = ("_lock", "count", "total", "min", "max")

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value

    def snapshot(self) -> Dict[str, object]:
        return {"count": self.count, "total": self.total,
                "min": self.min, "max": self.max,
                "mean": (self.total / self.count) if self.count else None}


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Process-wide metric store: ``kind:name{labels} -> instrument``."""

    def __init__(self):
        self._lock = threading.RLock()
        # name -> (kind, {label_key -> instrument})
        self._families: Dict[str, Tuple[str, Dict[LabelKey, object]]] = {}

    def _get(self, kind: str, name: str,
             labels: Optional[Dict[str, object]]):
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (kind, {})
                self._families[name] = fam
            elif fam[0] != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0]}, "
                    f"requested as {kind}")
            inst = fam[1].get(key)
            if inst is None:
                inst = _KINDS[kind](self._lock)
                fam[1][key] = inst
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def reset(self) -> None:
        with self._lock:
            self._families.clear()

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """JSON-ready ``{name: {kind, series: [{labels, ...stats}]}}``."""
        out: Dict[str, Dict[str, object]] = {}
        with self._lock:
            for name, (kind, series) in self._families.items():
                rows = []
                for key, inst in series.items():
                    snap = inst.snapshot()
                    if not isinstance(snap, dict):
                        snap = {"value": snap}
                    rows.append({"labels": dict(key), **snap})
                out[name] = {"kind": kind, "series": rows}
        return out


#: process-global default registry (the telemetry recorder feeds it)
registry = MetricsRegistry()


def bump_counter(name: str, value: float = 1, **labels) -> None:
    """Best-effort counter bump for supervision/publishing paths that
    must never fail on telemetry (elastic supervisor, model
    publisher): any registry error is swallowed."""
    try:
        registry.counter(name, **labels).inc(value)
    except Exception:
        pass
