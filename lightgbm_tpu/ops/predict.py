"""Batched tree traversal (prediction) as an in-order node sweep.

Re-design of Tree::Predict / the branchy per-row traversal
(/root/reference/include/LightGBM/tree.h:134,338-410 and
src/boosting/gbdt_prediction.cpp): one ``fori_loop`` over nodes in
creation order (parents always precede children) decides each node for
ALL rows at once from the node's scalar attributes, so no [n]-sized
gathers from node tables ever occur — XLA:TPU serializes those per
element (benchmarks/PROFILE.md), and the sweep is also ~2.4x faster
than the gather walk on CPU.

Missing-value routing matches the reference's NumericalDecision
(tree.h:338-360): missing_type none -> NaN treated as 0; zero -> |v| <=
kZeroThreshold or NaN follows the default arm; nan -> NaN follows the
default arm (encoded in decision_type bits, see models/tree.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["predict_leaf_binned", "predict_leaf_raw", "StackedTrees"]

K_ZERO_THRESHOLD = 1e-35

# missing_type codes (match decision_type bits 2-3 in the model format)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class StackedTrees(NamedTuple):
    """A whole forest as stacked tensors: leading axis = tree index.

    Leaves are referenced as ``~leaf`` in child arrays (tree.h convention).
    """
    split_feature: jnp.ndarray   # [T, L-1] i32
    threshold: jnp.ndarray       # [T, L-1] f64/f32 real-valued thresholds
    threshold_bin: jnp.ndarray   # [T, L-1] i32
    default_left: jnp.ndarray    # [T, L-1] bool
    missing_type: jnp.ndarray    # [T, L-1] i8
    is_categorical: jnp.ndarray  # [T, L-1] bool
    cat_bitset: jnp.ndarray      # [T, L-1, W] u32 category membership bitsets
    left_child: jnp.ndarray      # [T, L-1] i32
    right_child: jnp.ndarray     # [T, L-1] i32
    leaf_value: jnp.ndarray      # [T, L] f32
    # linear leaves (None for constant-leaf forests)
    lin_const: jnp.ndarray = None   # [T, L] f32
    lin_nfeat: jnp.ndarray = None   # [T, L] i32
    lin_feats: jnp.ndarray = None   # [T, L, km] i32 (real feature ids)
    lin_coef: jnp.ndarray = None    # [T, L, km] f32


def _traverse(n: int, decide_node_fn, left_child, right_child):
    """Route every row to its leaf by ONE in-order sweep over nodes.

    Internal node k is created by split k, so a node's index is always
    greater than its parent's (models/tree.py follows the reference's
    Tree::Split numbering) — processing nodes 0..nn-1 in order
    therefore visits each row's path nodes in path order, and a single
    ``fori_loop`` replaces the per-level pointer chase. Crucially,
    each step uses SCALAR node attributes (``decide_node_fn(i)``
    evaluates node i's decision for all rows at once), so there are no
    [n]-sized gathers from node tables — XLA:TPU executes those one
    element at a time (benchmarks/PROFILE.md), which made the old
    per-level walk ~1.6 s per million rows; this sweep is pure vector
    selects.
    """
    nn = left_child.shape[0]
    node0 = jnp.zeros((n,), jnp.int32)

    def body(i, node):
        go_left = decide_node_fn(i)
        nxt = jnp.where(go_left, left_child[i], right_child[i])
        return jnp.where(node == i, nxt, node)

    node = lax.fori_loop(0, nn, body, node0)
    return ~node  # leaf indices


def predict_leaf_binned(split_feature, threshold_bin, default_left,
                        left_child, right_child, feat_nan_bin,
                        bins_T, is_cat=None, cat_masks=None) -> jnp.ndarray:
    """Leaf index per row for one tree over the *binned* matrix [F, n].

    Used for train/valid score updates during boosting, where data is
    already binned (the ScoreUpdater::AddScore analog, score_updater.hpp).
    ``is_cat``/``cat_masks`` ([nn] bool, [nn, B] bool) route categorical
    nodes by bin membership instead of the bin threshold.
    """
    n = bins_T.shape[1]

    def decide(i):
        sf = split_feature[i]
        v = lax.dynamic_index_in_dim(bins_T, sf, keepdims=False) \
            .astype(jnp.int32)                                # [n]
        nb = feat_nan_bin[sf]
        num_left = jnp.where((nb >= 0) & (v == nb), default_left[i],
                             v <= threshold_bin[i])
        if is_cat is None:
            return num_left

        def cat_branch():
            # bin membership via the node's [B] mask: one-hot compare
            # (a cat_masks[i, v] gather would serialize per element).
            # This caller is never vmapped, so lax.cond genuinely
            # skips the [n, B] pass on numeric nodes
            B = cat_masks.shape[1]
            return jnp.any((v[:, None] == jnp.arange(B)[None, :])
                           & cat_masks[i][None, :], axis=1)

        return lax.cond(is_cat[i], cat_branch, lambda: num_left)

    return _traverse(n, decide, left_child, right_child)


def predict_leaf_raw(tree: StackedTrees, ti: int | jnp.ndarray,
                     X: jnp.ndarray) -> jnp.ndarray:
    """Leaf index per row for tree ``ti`` over raw features ``[n, F]``."""
    n = X.shape[0]
    X_T = X.T  # [F, n]: node sweeps slice whole contiguous columns
    sf = tree.split_feature[ti]
    thr = tree.threshold[ti]
    dl = tree.default_left[ti]
    mt = tree.missing_type[ti]
    is_cat = tree.is_categorical[ti]
    bitset = tree.cat_bitset[ti]

    def decide(i):
        v = lax.dynamic_index_in_dim(X_T, sf[i], keepdims=False)  # [n]
        m = mt[i]
        is_nan = jnp.isnan(v)
        v0 = jnp.where(is_nan, 0.0, v)
        # numerical decision with missing routing (tree.h:338-360)
        is_zero = jnp.abs(v0) <= K_ZERO_THRESHOLD
        missing = jnp.where(m == MISSING_NAN, is_nan,
                            jnp.where(m == MISSING_ZERO, is_zero | is_nan,
                                      jnp.zeros_like(is_nan)))
        num_left = jnp.where(missing, dl[i], v0 <= thr[i])

        def cat_branch():
            # membership in the node's u32 bitset (tree.h:402): the
            # word lookup unrolls over the W (small) bitset words —
            # a per-row bitset[word] gather would serialize. NOTE:
            # under _forest_leaves' vmap the cond lowers to a select
            # and this branch runs for numeric nodes too; at W words
            # it is a handful of [n] selects, which is still far
            # cheaper than any gather formulation
            iv = jnp.where(is_nan | (v < 0), -1, v).astype(jnp.int32)
            word = iv // 32
            bit = (iv % 32).astype(jnp.uint32)
            bits = bitset[i]                          # [W] u32
            W = bits.shape[0]
            w = jnp.zeros((n,), jnp.uint32)
            for k in range(W):
                w = jnp.where(word == k, bits[k], w)
            return (iv >= 0) & (word < W) \
                & (((w >> bit) & 1) != 0)

        return lax.cond(is_cat[i], cat_branch, lambda: num_left)

    return _traverse(n, decide, tree.left_child[ti], tree.right_child[ti])

# NOTE: the old `predict_forest_raw` (a fori_loop-of-trees scorer) was
# removed by tpulint TPL001: prediction.py's vmapped `_forest_leaves`
# replaced every caller long ago, leaving it dead — and a dead eager
# loop is one import away from dispatching op-by-op. Its KNOWN_JITTED
# allowlist entry was stale (nothing jitted it), and its eager-scope
# references also demoted `predict_leaf_raw`/`_traverse` out of the
# derived jit-reachable set. `python -m lightgbm_tpu lint` guards the
# replacement path.
