"""Categorical feature training (the reference's categorical split path:
feature_histogram.cpp FindBestThresholdCategoricalInner, tree.h
SplitCategorical; behavioral spec mirrored from
tests/python_package_test/test_engine.py categorical tests)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_data(n=3000, seed=0):
    rs = np.random.RandomState(seed)
    cat = rs.randint(0, 30, n).astype(np.float64)
    num = rs.randn(n)
    y = ((cat < 10).astype(float) * 2.0 + 0.3 * num
         + 0.1 * rs.randn(n) > 1.0).astype(np.float64)
    return np.column_stack([cat, num]), y


def test_categorical_splits_learned():
    X, y = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.2, "verbose": -1},
                    ds, num_boost_round=20)
    model = bst.model_to_string()
    assert "num_cat=1" in model or "num_cat=2" in model
    pred = bst.predict(X)
    assert ((pred > 0.5) == y).mean() > 0.9


def test_categorical_model_roundtrip():
    X, y = _cat_data(seed=1)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst.predict(X), bst2.predict(X), rtol=1e-6)


def test_categorical_onehot_path():
    """Features with <= max_cat_to_onehot bins use the one-hot scan."""
    rs = np.random.RandomState(2)
    n = 2000
    cat = rs.randint(0, 4, n).astype(np.float64)
    y = (cat == 2).astype(np.float64)
    ds = lgb.Dataset(cat.reshape(-1, 1), label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbose": -1},
                    ds, num_boost_round=5)
    pred = bst.predict(cat.reshape(-1, 1))
    assert ((pred > 0.5) == y).mean() > 0.99
    # one-hot: the winning left set is a single category
    t0 = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert t0["decision_type"] == "=="


def test_categorical_unseen_category_routes_right():
    X, y = _cat_data(seed=3)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    Xu = X.copy()
    Xu[:5, 0] = 999  # category never seen in training
    pred = bst.predict(Xu)
    assert np.isfinite(pred).all()


def test_categorical_valid_set_scoring_consistent():
    """Binned valid-set scoring must match raw-feature prediction."""
    X, y = _cat_data(seed=4)
    Xv, yv = _cat_data(seed=5)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    dv = ds.create_valid(Xv, label=yv)
    evals = {}
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "metric": "binary_logloss", "verbose": -1},
                    ds, num_boost_round=10, valid_sets=[dv],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(evals)])
    from lightgbm_tpu.metrics import create_metrics
    pred = bst.predict(Xv)
    eps = 1e-15
    p = np.clip(pred, eps, 1 - eps)
    ll = -np.mean(yv * np.log(p) + (1 - yv) * np.log(1 - p))
    assert abs(evals["v"]["binary_logloss"][-1] - ll) < 1e-5


def test_pandas_categorical_dtype():
    pd = pytest.importorskip("pandas")
    X, y = _cat_data(seed=6)
    df = pd.DataFrame({"c": pd.Categorical([f"g{int(v)}" for v in X[:, 0]]),
                       "x": X[:, 1]})
    ds = lgb.Dataset(df, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbose": -1}, ds, num_boost_round=10)
    pred = bst.predict(df)
    assert ((pred > 0.5) == y).mean() > 0.85


def test_relaxed_cat_grouping_accuracy_parity():
    """Quantify the documented min_data_per_group relaxation
    (split.py _cat_split_eval): on realistic skewed categorical data,
    the sorted-subset search with the relaxed (necessary-condition)
    grouping must match one-hot-encoded training within a small AUC
    margin — the relaxation admits extra candidate prefixes but must
    not cost accuracy."""
    rs = np.random.RandomState(17)
    n, ncat = 6000, 24
    cat = rs.choice(ncat, n, p=np.r_[[0.3], np.full(ncat - 1,
                                                    0.7 / (ncat - 1))])
    effect = rs.randn(ncat) * 0.8
    xnum = rs.randn(n, 2)
    logit = effect[cat] + 0.5 * xnum[:, 0] + 0.3 * rs.randn(n)
    y = (logit > 0).astype(float)
    tr = slice(0, 5000)
    te = slice(5000, n)

    def auc(y_, p_):
        o = np.argsort(p_)
        r = np.empty(len(p_)); r[o] = np.arange(1, len(p_) + 1)
        np_ = y_.sum(); nn = len(y_) - np_
        return (r[y_ > 0].sum() - np_ * (np_ + 1) / 2) / (np_ * nn)

    Xc = np.column_stack([cat.astype(float), xnum])
    bst_cat = lgb.train({"objective": "binary", "verbosity": -1,
                         "num_leaves": 31, "min_data_per_group": 50},
                        lgb.Dataset(Xc[tr], label=y[tr],
                                    categorical_feature=[0]),
                        num_boost_round=30)
    auc_cat = auc(y[te], bst_cat.predict(Xc[te]))

    onehot = np.zeros((n, ncat))
    onehot[np.arange(n), cat] = 1.0
    Xo = np.column_stack([onehot, xnum])
    bst_oh = lgb.train({"objective": "binary", "verbosity": -1,
                        "num_leaves": 31, "enable_bundle": False},
                       lgb.Dataset(Xo[tr], label=y[tr]),
                       num_boost_round=30)
    auc_oh = auc(y[te], bst_oh.predict(Xo[te]))
    assert auc_cat > auc_oh - 0.01, (auc_cat, auc_oh)
