"""Plotting utilities.

Covers the plotting surface of the reference
(python-package/lightgbm/plotting.py: plot_importance,
plot_split_value_histogram, plot_metric, plot_tree, create_tree_digraph)
with the same signatures, but organized around a single shared
``_decorate_axes`` helper instead of per-function axes boilerplate.
matplotlib is imported lazily; graphviz is optional and raises at call
time when absent, as in the reference.
"""

from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError

__all__ = ["plot_importance", "plot_split_value_histogram", "plot_metric",
           "plot_tree", "create_tree_digraph"]


def _pair(value, name: str) -> Tuple:
    """Validate a 2-tuple plot bound (figsize / xlim / ylim)."""
    if not isinstance(value, tuple) or len(value) != 2:
        raise TypeError(f"{name} must be a tuple of 2 elements.")
    return value


def _new_axes(ax, figsize, dpi):
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    if figsize is not None:
        _pair(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def _decorate_axes(ax, *, xlim=None, ylim=None, title=None, xlabel=None,
                   ylabel=None, grid=True) -> None:
    """Apply the common bound/label/grid decoration in one place."""
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)


def _to_booster(booster) -> Booster:
    if isinstance(booster, Booster):
        return booster
    if hasattr(booster, "booster_"):
        return booster.booster_
    raise TypeError("booster must be Booster or fitted LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim: Optional[Tuple] = None,
                    ylim: Optional[Tuple] = None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar plot of feature importances."""
    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = getattr(booster, "importance_type", "split")
    importance = bst.feature_importance(importance_type=importance_type)
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    ranked = sorted(zip(bst.feature_name(), importance),
                    key=lambda pair: pair[1])
    if ignore_zero:
        ranked = [pair for pair in ranked if pair[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        ranked = ranked[-max_num_features:]
    labels = [pair[0] for pair in ranked]
    values = [pair[1] for pair in ranked]

    ax = _new_axes(ax, figsize, dpi)
    positions = np.arange(len(values))
    ax.barh(positions, values, align="center", height=height, **kwargs)
    fmt = (f"{{:.{precision}f}}"
           if importance_type == "gain" and precision is not None
           else "{}")
    for pos, val in zip(positions, values):
        ax.text(val + 1, pos, fmt.format(val), va="center")
    ax.set_yticks(positions)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _pair(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1 if values else 1)
    if ylim is not None:
        _pair(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    _decorate_axes(ax, xlim=xlim, ylim=ylim, title=title, xlabel=xlabel,
                   ylabel=ylabel, grid=grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8,
                               xlim: Optional[Tuple] = None,
                               ylim: Optional[Tuple] = None,
                               title: Optional[str] = "Split value histogram "
                               "for feature with @index/name@ @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of one feature's split thresholds across the model."""
    bst = _to_booster(booster)
    if isinstance(feature, str):
        fidx = bst.feature_name().index(feature)
    else:
        fidx = int(feature)
    thresholds = [
        tree.threshold[node]
        for tree in bst._models
        for node in range(tree.num_nodes)
        if tree.split_feature[node] == fidx
        and not tree.is_categorical_node(node)]
    if not thresholds:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")
    counts, edges = np.histogram(thresholds, bins=bins or "auto")
    centers = (edges[:-1] + edges[1:]) / 2.0

    ax = _new_axes(ax, figsize, dpi)
    ax.bar(centers, counts, width=width_coef * np.diff(edges),
           align="center", **kwargs)
    if xlim is not None:
        _pair(xlim, "xlim")
    if ylim is not None:
        _pair(ylim, "ylim")
    else:
        ylim = (0, max(counts) * 1.1)
    if title is not None:
        kind = "name" if isinstance(feature, str) else "index"
        title = title.replace("@feature@", str(feature)) \
                     .replace("@index/name@", kind)
    _decorate_axes(ax, xlim=xlim, ylim=ylim, title=title, xlabel=xlabel,
                   ylabel=ylabel, grid=grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim: Optional[Tuple] = None, ylim: Optional[Tuple] = None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@", figsize=None, dpi=None,
                grid: bool = True):
    """Plot metric curves from a record_evaluation dict or fitted sklearn
    estimator."""
    if isinstance(booster, dict):
        history = deepcopy(booster)
    elif hasattr(booster, "evals_result_"):
        history = deepcopy(booster.evals_result_)
    else:
        raise TypeError(
            "booster must be dict (from record_evaluation) or LGBMModel")
    if not history:
        raise ValueError("eval results cannot be empty.")

    names = list(history.keys()) if dataset_names is None \
        else [n for n in dataset_names if n in history]
    if not names:
        raise ValueError("eval results cannot be empty.")

    first_metrics = history[names[0]]
    if metric is None:
        if len(first_metrics) > 1:
            raise ValueError(
                "more than one metric available, pick one with the "
                "'metric' parameter")
        metric = next(iter(first_metrics))
    elif metric not in first_metrics:
        raise ValueError("No given metric in eval results.")

    ax = _new_axes(ax, figsize, dpi)
    lo, hi, length = float("inf"), float("-inf"), 0
    for name in names:
        curve = history[name][metric]
        ax.plot(range(len(curve)), curve, label=name)
        lo = min(lo, min(curve))
        hi = max(hi, max(curve))
        length = max(length, len(curve))
    ax.legend(loc="best")

    if xlim is not None:
        _pair(xlim, "xlim")
    else:
        xlim = (0, length)
    if ylim is not None:
        _pair(ylim, "ylim")
    else:
        pad = 0.05 * (hi - lo + 1e-12)
        ylim = (lo - pad, hi + pad)
    if ylabel is not None:
        ylabel = ylabel.replace("@metric@", metric)
    _decorate_axes(ax, xlim=xlim, ylim=ylim, title=title, xlabel=xlabel,
                   ylabel=ylabel, grid=grid)
    return ax


def _node_text(tree, node: int, is_leaf: bool, show_info: List[str],
               precision: int, feature_names: List[str]) -> str:
    """Multi-line node label for the digraph."""
    if is_leaf:
        lines = [f"leaf {node}",
                 f"value: {tree.leaf_value[node]:.{precision}f}"]
        if "leaf_count" in show_info:
            lines.append(f"count: {int(tree.leaf_count[node])}")
        if "leaf_weight" in show_info:
            lines.append(f"weight: {tree.leaf_weight[node]:.{precision}f}")
        return "\n".join(lines)
    f = tree.split_feature[node]
    fname = feature_names[f] if f < len(feature_names) else f"f{f}"
    if tree.is_categorical_node(node):
        lines = [f"{fname} in categories"]
    else:
        lines = [f"{fname} <= {tree.threshold[node]:.{precision}f}"]
    if "split_gain" in show_info:
        lines.append(f"gain: {tree.split_gain[node]:.{precision}f}")
    if "internal_value" in show_info:
        lines.append(f"value: {tree.internal_value[node]:.{precision}f}")
    if "internal_count" in show_info:
        lines.append(f"count: {int(tree.internal_count[node])}")
    return "\n".join(lines)


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal", **kwargs):
    """Build a graphviz Digraph of one tree."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "You must install graphviz and restart your session to "
            "plot tree.") from e

    bst = _to_booster(booster)
    if tree_index < 0 or tree_index >= len(bst._models):
        raise IndexError("tree_index is out of range.")
    tree = bst._models[tree_index]
    feature_names = bst.feature_name()
    show_info = show_info or []
    precision = 3 if precision is None else precision

    graph = Digraph(**kwargs)
    graph.attr("graph", nodesep="0.05", ranksep="0.3",
               rankdir="LR" if orientation == "horizontal" else "TB")

    def add(node: int, parent: Optional[str]) -> None:
        if node < 0:  # leaf
            leaf = ~node
            name = f"leaf{leaf}"
            graph.node(name, _node_text(tree, leaf, True, show_info,
                                        precision, feature_names))
        else:
            name = f"split{node}"
            graph.node(name, _node_text(tree, node, False, show_info,
                                        precision, feature_names))
            add(int(tree.left_child[node]), name)
            add(int(tree.right_child[node]), name)
        if parent is not None:
            graph.edge(parent, name)

    if tree.num_leaves <= 1:
        graph.node("leaf0", _node_text(tree, 0, True, show_info,
                                       precision, feature_names))
    else:
        add(0, None)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: Optional[int] = 3,
              orientation: str = "horizontal", **kwargs):
    """Render one tree with matplotlib via graphviz."""
    import matplotlib.image as mpimg
    from io import BytesIO

    ax = _new_axes(ax, figsize, dpi)
    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation, **kwargs)
    img = mpimg.imread(BytesIO(graph.pipe(format="png")))
    ax.imshow(img)
    ax.axis("off")
    return ax
