"""Lint engine: scan -> callgraph -> rules -> baseline filter.

``run_lint`` is the one library entry point; the CLI
(:mod:`~lightgbm_tpu.analysis.cli`) and the tier-1 test
(tests/test_static_analysis.py) are thin layers over it. Pure stdlib —
no jax import anywhere on this path.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set

from .baseline import (BaselineEntry, assign_ids, format_baseline,
                       load_baseline)
from .callgraph import CallGraph, scan_package
from .rules import ALL_RULES, Finding, LintContext, rule_by_id

__all__ = ["run_lint", "LintResult", "default_scope", "package_root",
           "default_baseline_path"]

#: rule scope: the boosting hot path (ISSUE scope floor: models/,
#: ops/, parallel/, engine.py, resilience/ — plus obs/ for TPL006,
#: data/ for the ingestion pipeline's pass-1/pass-2 host collectives
#: (TPL007) and jax-laziness, serve/ for the inference daemon's
#: batcher/watcher thread contract (TPL006/TPL008) and its bucketed
#: jit program (TPL003), pipeline.py for the lifecycle supervisor's
#: load-generator thread contract (TPL006/TPL008; the publisher rides
#: the resilience/ scope), and the per-iteration device-code modules
#: at package root).
#: the contract pass (TPL015-TPL018) widened the scope to everything
#: that emits events, bumps metrics, or reads LIGHTGBM_TPU_* env vars:
#: utils/ plus the remaining package-root modules. Verified to add
#: zero TPL001-TPL010 findings.
_SCOPE_DIRS = ("models/", "ops/", "parallel/", "resilience/", "obs/",
               "data/", "serve/", "utils/")
_SCOPE_FILES = ("engine.py", "ranking.py", "prediction.py",
                "metrics.py", "objectives.py", "shap.py",
                "pipeline.py", "basic.py", "cli.py", "config.py",
                "callback.py")


def package_root() -> str:
    """Directory of the ``lightgbm_tpu`` package being analyzed."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline_path(root: Optional[str] = None) -> str:
    root = root or package_root()
    return os.path.join(os.path.dirname(root), "tools",
                        "tpulint_baseline.txt")


def default_scope(relpaths: Sequence[str]) -> Set[str]:
    out = set()
    for rel in relpaths:
        if rel in _SCOPE_FILES or rel.startswith(_SCOPE_DIRS):
            out.add(rel)
    return out


@dataclass
class LintResult:
    findings: List[Finding]                  # non-baselined, sorted
    baselined: List[Finding]
    stale_baseline: List[BaselineEntry]
    suppressed: List[Finding]                # pragma-disabled
    files: Set[str]
    graph: CallGraph
    elapsed: float
    unjustified_baseline: List[BaselineEntry] = field(
        default_factory=list)
    # --ir only: budget-file discipline (tools/ir_budgets.json keys
    # that no spec lowers anymore / that lack a real justification)
    # and the entry points the IR pass actually lowered
    stale_budget: List[BaselineEntry] = field(default_factory=list)
    unjustified_budget: List[BaselineEntry] = field(
        default_factory=list)
    ir_entries: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def write_baseline(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(format_baseline(self.findings + self.baselined))


def run_lint(root: Optional[str] = None,
             package: str = "lightgbm_tpu",
             scope: Optional[Set[str]] = None,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             files: Optional[List[str]] = None,
             ir: bool = False,
             ir_entries: Optional[Sequence[str]] = None) -> LintResult:
    """Run the analyzer.

    Args:
      root: package directory to scan (default: this installation's
        ``lightgbm_tpu``). The whole package is always parsed for the
        call graph; ``scope`` limits where rules REPORT.
      scope: relpaths rules run over (default: the hot-path scope).
      rules: rule ids to run (default: all AST rules; the IR rules
        TPL011-TPL014 additionally require ``ir=True``).
      baseline_path: accepted-findings file ("": no baseline;
        None: tools/tpulint_baseline.txt when present).
      files: restrict parsing to these package-relative files
        (fixture tests use this).
      ir: also lower every registered entry point and run the IR
        rules (TPL011-TPL014). This — and ONLY this — imports jax
        (lazily, pinned to CPU, lowering only); the default path
        stays pure stdlib.
      ir_entries: restrict the IR pass to these entry points
        (``name@variant`` or bare registry name).
    """
    t0 = time.perf_counter()
    root = root or package_root()
    scans = scan_package(root, package=package, files=files)
    graph = CallGraph(scans)
    relpaths = [s.relpath for s in scans]
    narrowed_scope = scope is not None
    if scope is None:
        scope = default_scope(relpaths) if files is None else \
            set(relpaths)
    ctx = LintContext(graph=graph, scans=graph.scans, scope=scope,
                      root=root)

    active = ALL_RULES
    if rules:
        wanted = []
        for rid in rules:
            rule = rule_by_id(rid)
            if rule is None:
                from .rules import IR_RULES
                raise ValueError(
                    f"unknown rule {rid!r} (have: "
                    f"{', '.join(r.id for r in ALL_RULES + IR_RULES)})")
            wanted.append(rule)
        active = wanted

    findings: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in active:
        for f in rule.run(ctx):
            scan = graph.scans.get(f.relpath)
            if scan is not None and scan.suppressed(f.rule, f.lineno):
                suppressed.append(f)
            else:
                findings.append(f)

    stale_budget: List[BaselineEntry] = []
    unjustified_budget: List[BaselineEntry] = []
    ir_entries_run: List[str] = []
    ir_ids_run: Set[str] = set()
    if ir:
        # lazy on purpose: this is the ONLY place the lint path may
        # import jax, and only under an explicit --ir
        from .ircheck import IR_RULE_IDS, run_ircheck
        ir_rules = [rid for rid in (rules or IR_RULE_IDS)
                    if rid in IR_RULE_IDS]
        if ir_rules:
            ir_result = run_ircheck(rules=ir_rules, entries=ir_entries)
            findings.extend(ir_result.findings)
            stale_budget = ir_result.stale_budget
            unjustified_budget = ir_result.unjustified_budget
            ir_entries_run = ir_result.entries_run
            # staleness of baselined IR findings is only decidable
            # when the full entry table was lowered
            if not ir_entries:
                ir_ids_run = set(ir_rules)
    assign_ids(findings + suppressed)

    if baseline_path is None:
        cand = default_baseline_path(root)
        baseline_path = cand if os.path.exists(cand) else ""
    entries = load_baseline(baseline_path) if baseline_path else []
    by_fid = {e.fid: e for e in entries}
    kept: List[Finding] = []
    baselined: List[Finding] = []
    seen_fids = set()
    for f in findings:
        seen_fids.add(f.fid)
        (baselined if f.fid in by_fid else kept).append(f)
    # staleness is only decidable for rules that actually ran AND (on
    # an explicitly narrowed run: --changed, fixture slices) files the
    # rules reported over — a slice must not report (or --strict-fail
    # on) baseline entries it could never have re-produced. A FULL run
    # applies no path filter on purpose: an entry whose file was
    # deleted or renamed must still surface as stale, or --strict
    # would let it rot invisibly forever.
    # IR rules are excluded from the AST active set by construction
    # (they live in IR_RULES, not ALL_RULES); their baselined entries
    # only count as stale when the IR pass lowered the full table
    active_ids = {r.id for r in active
                  if not getattr(r, "ir_only", False)} | ir_ids_run

    def _fid_path(fid: str) -> str:
        parts = fid.split(":", 2)
        return parts[1] if len(parts) >= 2 else ""

    stale = [e for e in entries
             if e.fid not in seen_fids
             and e.fid.split(":", 1)[0] in active_ids
             and (not narrowed_scope
                  or _fid_path(e.fid) in scope
                  or e.fid.split(":", 1)[0] in ir_ids_run)]
    unjustified = [e for e in entries if not e.justification]
    kept.sort(key=lambda f: f.sort_key())
    baselined.sort(key=lambda f: f.sort_key())
    return LintResult(findings=kept, baselined=baselined,
                      stale_baseline=stale, suppressed=suppressed,
                      files=set(relpaths) & scope, graph=graph,
                      elapsed=time.perf_counter() - t0,
                      unjustified_baseline=unjustified,
                      stale_budget=stale_budget,
                      unjustified_budget=unjustified_budget,
                      ir_entries=ir_entries_run)
