"""Measure the fused-iteration fast path end-to-end at bench scale
(10.5M x 28, 255 leaves/bins) on the real chip, with three arms and two
FLIP gates:

- eager vs fused: wall per train_one_iter (fused gate forced off vs on).
- fused vs fused+pallas: the pallas-vs-mxu delta at THIS shape is the
  decision gate for flipping hist_method="auto" to pallas on TPU
  (docs/PALLAS.md).
- fused vs fused+scan: the multi-iteration scan window
  (Config.fused_scan_iters, docs/FUSED.md) traces SCAN_W iterations
  into one program; its gate decides flipping fused_scan_iters="auto"
  off 1. Each arm also prints a dispatch-gap decomposition: on-device
  program time (the boosting/fused_iter|fused_scan Timer phases) vs
  host driver time per iteration (wall minus device phases — dispatch,
  tree-pack fetch and Python driver, the ~15% of a Higgs iteration the
  scan exists to delete). The acceptance proxy off-chip: driver
  time/iter inside a window drops >= 5x vs the per-iteration fused
  arm; the on-chip verdict is wall it/s at this shape. NB: the CPU
  backend executes per-iteration programs synchronously inside the
  dispatch call, so off-chip the per-iteration arms' driver column is
  an UPPER bound (driver + compute); the scan arm's pop-driver number
  is exact on both backends (pure host work, no device traffic).

Run:  python benchmarks/fused_iter_bench.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import time

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.models.gbdt import GBDTBooster
from lightgbm_tpu.utils.timer import Timer

N = int(os.environ.get("BENCH_FUSED_ROWS", "10500000"))  # smoke knob
F = 28
SCAN_W = int(os.environ.get("BENCH_SCAN_ITERS", "10"))
rs = np.random.RandomState(0)
X = rs.randn(N, F).astype(np.float32)
coef = rs.randn(F).astype(np.float32)
y = ((X @ coef) > 0).astype(np.float64)
t0 = time.perf_counter()
ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
ds.construct()
print(f"construct: {time.perf_counter() - t0:.1f} s", flush=True)
del X

PARAMS = {"objective": "binary",
          "num_leaves": int(os.environ.get("BENCH_FUSED_LEAVES", "255")),
          "max_bin": 255, "learning_rate": 0.1, "verbosity": -1}

# Host-driver time = time spent INSIDE train_one_iter calls minus the
# in-call device-blocking phase (the scan's window-boundary batched
# fetch, timed under boosting/fused_scan). Per-iteration dispatches
# return async, so their in-call time IS the dispatch + Python driver
# overhead the scan deletes; the device wait then accrues at the final
# block_until_ready and lands in (wall - driver). The phase list is
# THE one the tracing plane's per-iteration host-gap derivation
# subtracts (obs/trace.py record_iteration_spans) — same source of
# truth, so the bench arms and the span attrs can never disagree.
from lightgbm_tpu.obs.trace import BLOCKING_PHASES as _BLOCKING_PHASES


def _phase_total(snap, labels):
    return sum(snap.get(lb, {}).get("total", 0.0) for lb in labels)


def run(tag, fused, iters=10, hist_method=None, scan=0):
    if not fused:
        orig = GBDTBooster._fused_ok
        GBDTBooster._fused_ok = lambda self: False
    try:
        params = dict(PARAMS)
        if hist_method:
            params["hist_method"] = hist_method
        if scan:
            params["fused_scan_iters"] = scan
        bst = lgb.Booster(params=params, train_set=ds)
        eng = bst._engine
        if scan:
            # direct train_one_iter driving (no engine loop): the
            # bench owns the cadence, so it grants the lookahead the
            # train() loop would have computed
            eng._scan_horizon = iters
        t0 = time.perf_counter()
        eng.train_one_iter()
        eng.score.block_until_ready()
        print(f"{tag}: warmup (incl compile) "
              f"{time.perf_counter() - t0:.1f} s", flush=True)
        if scan:
            # restart the window grid so the measured loop covers
            # whole windows (the warmup window is popped out first)
            while eng._scan_pend is not None:
                eng.train_one_iter()
            eng._scan_horizon = iters
        was_enabled = Timer.enabled()
        Timer.enable()
        base = Timer.snapshot()
        t_calls = 0.0
        t0 = time.perf_counter()
        for _ in range(iters):
            tc = time.perf_counter()
            eng.train_one_iter()
            t_calls += time.perf_counter() - tc
        eng.score.block_until_ready()
        wall = time.perf_counter() - t0
        snap = Timer.snapshot()
        Timer.enable(was_enabled)
        blocking = _phase_total(snap, _BLOCKING_PHASES) \
            - _phase_total(base, _BLOCKING_PHASES)
        dt = wall / iters
        driver = max(t_calls - blocking, 0.0) / iters
        print(f"{tag}: {dt * 1e3:.1f} ms/iter = {1 / dt:.3f} iters/sec "
              f"(vs_baseline {1 / dt / (500 / 130.094):.3f})", flush=True)
        print(f"{tag}: decomposition on-device+wait "
              f"{(wall / iters - driver) * 1e3:.2f} ms/iter, host "
              f"driver {driver * 1e3:.2f} ms/iter (inter-iteration "
              f"gap)", flush=True)
        # one machine-readable line per flip-gate arm: the span-
        # derived host-gap decomposition next to the wall number, so
        # the revive battery's greps AND the trace plane's host_gap_s
        # attrs reconcile against the same record
        print(json.dumps({
            "event": "bench_arm", "arm": tag, "iters": iters,
            "ms_per_iter": round(dt * 1e3, 3),
            "iters_per_sec": round(1 / dt, 4),
            "device_ms_per_iter": round((wall / iters - driver) * 1e3,
                                        3),
            "host_gap_ms_per_iter": round(driver * 1e3, 3),
            "blocking_phases": list(_BLOCKING_PHASES)}), flush=True)
        return dt, driver
    finally:
        if not fused:
            GBDTBooster._fused_ok = orig


eager, _ = run("eager", fused=False)
fused, fused_driver = run("fused", fused=True)
print(f"speedup: {eager / fused:.3f}x", flush=True)

scan, scan_driver = run(f"fused+scan{SCAN_W}", fused=True, iters=SCAN_W,
                        scan=SCAN_W)
gap_ratio = fused_driver / scan_driver if scan_driver > 0 else float("inf")
print(f"scan vs fused: {fused / scan:.3f}x wall, driver gap "
      f"{fused_driver * 1e3:.2f} -> {scan_driver * 1e3:.2f} ms/iter "
      f"({gap_ratio:.1f}x lower) — "
      f"{'FLIP fused_scan_iters auto to ' + str(SCAN_W) if scan < fused else 'keep per-iteration'} "
      "(record the verdict in docs/FUSED.md + PROFILE.md)",
      flush=True)

from lightgbm_tpu.ops.pallas_hist import pallas_available  # noqa: E402

if pallas_available():
    pallas, _ = run("fused+pallas", fused=True, hist_method="pallas")
    print(f"pallas vs mxu (fused): {fused / pallas:.3f}x — "
          f"{'FLIP auto to pallas' if pallas < fused else 'keep mxu'} "
          "(record the verdict in docs/PALLAS.md + PROFILE.md)",
          flush=True)
else:
    print("pallas arm SKIPPED (unavailable)", flush=True)
