# tpulint fixture: TPL009 positive — float64-producing numpy values
# flowing into jit-reachable functions (silent per-call downcast under
# x64-off; full-program f64 promotion under x64-on).
import jax
import numpy as np


@jax.jit
def traced(x):
    return x * 2.0


def f64_by_default_ctor(n):
    # np.zeros with no dtype is float64
    # EXPECT: TPL009
    return traced(np.zeros((n,)))


def f64_explicit_dtype(values):
    # EXPECT: TPL009
    return traced(np.asarray(values, np.float64))


def f64_through_a_local(n):
    thresholds = np.linspace(0.0, 1.0, n)
    # EXPECT: TPL009
    return traced(thresholds)


def f64_astype(x):
    # EXPECT: TPL009
    return traced(x.astype("float64"))
