"""TPL012 positive: a psum whose measured wire bytes exceed the
committed budget. tests/test_ircheck.py traces ``build``'s program,
summarizes its collectives (``parallel.comms.collective_summary``) and
diffs them against ``BUDGET`` via ``analysis.ircheck.budget_findings``
— the finding anchors at the BUDGET line (the committed number under
review), pinned by the EXPECT marker above it."""


def build(jax, jnp):
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.parallel.data_parallel import shard_map
    from lightgbm_tpu.parallel.mesh import DATA_AXIS, make_mesh
    mesh = make_mesh(8, devices=jax.devices("cpu"))
    fn = shard_map(lambda x: jax.lax.psum(x, DATA_AXIS), mesh,
                   in_specs=P(DATA_AXIS), out_specs=P(),
                   check_rep=False)
    return fn, (jnp.ones((8, 32), jnp.float32),)


# the per-shard psum operand is (1, 32) f32 = 128 wire bytes; this
# budget admits only 16, so the measured payload exceeds it
# EXPECT: TPL012
BUDGET = {"wire_bytes": 16, "justification": "deliberately too small"}
