# tpulint fixture: TPL008 pragma suppression — an Event handshake
# already orders the shared write, and the `# tpulint: threadsafe`
# mark carries the REQUIRED why (a bare mark does not suppress: see
# obs/tpl008_pos.py). Negative fixture: no EXPECT lines.
import threading

_box = {}


# tpulint: threadsafe Event handshake — _box is written before
def _worker(done):
    _box["value"] = 42
    done.set()


def run():
    done = threading.Event()
    worker = threading.Thread(target=_worker, args=(done,))
    worker.start()
    done.wait()
    return _box["value"]
