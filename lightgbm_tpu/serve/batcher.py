"""Shape-bucketed micro-batcher: async request queue -> device batches.

One worker thread owns the device: requests (arbitrary row counts) are
queued by caller threads, coalesced inside a bounded batching window
(``batch_window_ms``, capped at ``max_batch_rows``), run through the
compiled forest's bucketed program, and the per-request slices resolve
each caller's Future. Backpressure is a hard row budget
(``queue_max_rows``): a submit that would exceed it fails fast with
:class:`QueueFullError` instead of growing an unbounded queue — the
daemon surfaces that as an ``overloaded`` error line.

Load shedding (docs/SERVING.md "Overload policy") sits between
healthy operation and that hard wall: when ``shed_queue_rows`` > 0
and the pending backlog exceeds it, the worker sheds the OLDEST
queued requests — resolving their futures with a typed
:class:`SheddingError` the daemon maps to a ``{"shed": true}`` reply
— until the backlog is back under the threshold; fresh arrivals keep
being served at bounded latency instead of every caller timing out
together. ``shed_p99_ms`` > 0 additionally sheds any request that
has already waited past that latency budget at dequeue time (its
deadline is blown; finishing it would only steal capacity from
requests that can still meet theirs). Both thresholds default to 0 =
disabled: shedding is an explicit operational choice.

Threading contract (enforced by tpulint TPL006/TPL008 over serve/):
every mutable field shared between the worker and callers is touched
only under ``self._lock``, the request handoff itself rides a
``queue.Queue``, and the jax dispatch (``forest.predict_raw``) always
runs OUTSIDE the lock — a device stall must never block ``submit`` or
``stats``.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

__all__ = ["MicroBatcher", "QueueFullError", "SheddingError"]

#: latency samples kept for the p50/p99 window (newest-wins ring)
_LATENCY_WINDOW = 4096

_STOP = object()


class QueueFullError(RuntimeError):
    """Backpressure: the batcher's pending-row budget is exhausted."""


class SheddingError(RuntimeError):
    """Load shedding: the request was accepted but dropped by the
    overload policy (queue depth or per-request latency budget breach)
    before reaching the device — the typed signal for "retry later /
    against another replica", distinct from the hard
    :class:`QueueFullError` admission rejection."""


class _Request:
    __slots__ = ("rows", "future", "t_submit", "trace", "t_dequeue")

    def __init__(self, rows: np.ndarray, future: Future,
                 t_submit: float, trace=None):
        self.rows = rows
        self.future = future
        self.t_submit = t_submit
        # optional trace context dict ({"trace_id", "span_id"}) carried
        # from the protocol line; when set, the worker stamps dequeue /
        # dispatch timestamps so the daemon can emit queue-wait /
        # batch-window / dispatch spans for exactly the sampled requests
        self.trace = trace
        self.t_dequeue = None


class _SwapCmd:
    """A model swap riding the request queue: applied by the worker in
    FIFO order, i.e. at a point where no batch is in flight — the only
    moment the old forest's device buffers may be donated to the new
    model's upload."""

    __slots__ = ("build", "future")

    def __init__(self, build):
        self.build = build          # build(old_forest) -> new forest
        self.future = Future()


class MicroBatcher:
    """Coalesce concurrent predict requests into device batches.

    ``forest`` is anything with ``predict_raw(X) -> [n, K]`` and an
    ``n_features`` attribute — in production a
    :class:`~lightgbm_tpu.serve.compile.CompiledForest`. ``swap()``
    replaces it atomically: requests already dequeued finish on the
    model they started with, everything after answers from the new one,
    and nothing is ever dropped.
    """

    def __init__(self, forest, batch_window_ms: float = 2.0,
                 max_batch_rows: int = 16384,
                 queue_max_rows: int = 131072,
                 shed_queue_rows: int = 0,
                 shed_p99_ms: float = 0.0):
        if batch_window_ms < 0:
            raise ValueError("batch_window_ms must be >= 0")
        if max_batch_rows < 1 or queue_max_rows < 1:
            raise ValueError("max_batch_rows and queue_max_rows must "
                             "be >= 1")
        if shed_queue_rows < 0 or shed_p99_ms < 0:
            raise ValueError("shed_queue_rows and shed_p99_ms must be "
                             ">= 0 (0 disables shedding)")
        if shed_queue_rows and shed_queue_rows >= queue_max_rows:
            # the same invariant Config enforces — re-checked here so
            # the serve CLI's flags (which never build a Config) cannot
            # silently configure shedding that can never fire
            raise ValueError(
                "shed_queue_rows (soft shed threshold) must stay below "
                f"queue_max_rows (hard admission wall) to ever fire "
                f"({shed_queue_rows} >= {queue_max_rows})")
        self._forest = forest
        self._window_s = float(batch_window_ms) / 1e3
        self._max_batch_rows = int(max_batch_rows)
        self._queue_max_rows = int(queue_max_rows)
        self._shed_queue_rows = int(shed_queue_rows)
        self._shed_p99_ms = float(shed_p99_ms)
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        # ---- all fields below are guarded by self._lock ----
        self._pending_rows = 0
        self._requests_total = 0
        self._rows_total = 0
        self._batches_total = 0
        self._swaps_total = 0
        self._rejected_total = 0
        self._shed_total = 0
        self._shed_rows = 0
        self._latencies = deque(maxlen=_LATENCY_WINDOW)
        self._closed = False
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True,
            name="lightgbm-tpu-serve-batcher")
        self._worker.start()

    # -- caller side ---------------------------------------------------
    def submit(self, rows, trace=None) -> Future:
        """Enqueue ``rows`` ([n, F] or [F]); the Future resolves to the
        raw-score matrix ``[n, K]``. Raises :class:`QueueFullError`
        when the pending-row budget would be exceeded. ``trace`` is an
        optional span context dict propagated from the protocol — the
        resolved Future then carries ``trace``/``trace_times``
        (submit, dequeue, dispatch, done perf_counter stamps) for the
        daemon's per-request spans."""
        rows = np.ascontiguousarray(np.asarray(rows, np.float32))
        if rows.ndim == 1:
            rows = rows[None, :]
        nf = getattr(self._current_forest(), "n_features", None)
        if nf is not None and rows.shape[1] != nf:
            raise ValueError(
                f"request has {rows.shape[1]} features, the served "
                f"model expects {nf}")
        n = rows.shape[0]
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._pending_rows + n > self._queue_max_rows:
                self._rejected_total += 1
                depth = self._pending_rows
                raise QueueFullError(
                    f"serve queue full: {depth} rows pending, request "
                    f"of {n} exceeds the {self._queue_max_rows}-row "
                    "budget")
            self._pending_rows += n
            # enqueue UNDER the lock (put never blocks): a close()
            # racing between the flag check and an unlocked put could
            # drain, join and leave this future unresolved forever
            self._queue.put(_Request(rows, fut, time.perf_counter(),
                                     trace))
        return fut

    def swap(self, forest) -> object:
        """Install ``forest`` as the serving model; returns the old
        one. In-flight batches keep the model they dequeued with (the
        old forest must therefore stay alive — see
        :meth:`swap_deferred` for the donating variant)."""
        with self._lock:
            old = self._forest
            self._forest = forest
            self._swaps_total += 1
        return old

    def swap_deferred(self, build) -> Future:
        """Enqueue ``build(old_forest) -> new_forest`` to run on the
        worker thread between batches, where the old forest is
        guaranteed idle — the daemon passes a staged
        ``CompiledForest.attach`` here so the upload can donate the
        old model's device buffers field by field (transient HBM
        overhead: one field, never a second resident forest). The
        returned Future resolves to the new forest (or the build
        error; a failed build keeps the old model serving)."""
        cmd = _SwapCmd(build)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.put(cmd)    # under the lock, like submit()
        return cmd.future

    def _apply_swap(self, cmd: _SwapCmd) -> None:
        if not cmd.future.set_running_or_notify_cancel():
            return    # requester cancelled (e.g. gave up waiting): a
            #           swap that never reported must never apply late
        old = self._current_forest()
        try:
            new = cmd.build(old)
        except BaseException as e:
            cmd.future.set_exception(e)    # old keeps serving
            return
        with self._lock:
            self._forest = new
            self._swaps_total += 1
        cmd.future.set_result(new)

    def _current_forest(self):
        with self._lock:
            return self._forest

    def stats(self) -> dict:
        """Queue/latency snapshot for telemetry and the ``stats``
        protocol command."""
        with self._lock:
            lat = list(self._latencies)
            out = {
                "queue_depth_rows": self._pending_rows,
                "requests_total": self._requests_total,
                "rows_total": self._rows_total,
                "batches_total": self._batches_total,
                "swaps_total": self._swaps_total,
                "rejected_total": self._rejected_total,
                "shed_total": self._shed_total,
                "shed_rows": self._shed_rows,
            }
        if lat:
            q = np.percentile(np.asarray(lat, np.float64), [50.0, 99.0])
            out["p50_ms"] = round(float(q[0]) * 1e3, 3)
            out["p99_ms"] = round(float(q[1]) * 1e3, 3)
        else:
            out["p50_ms"] = None
            out["p99_ms"] = None
        return out

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting requests, drain everything already queued
        (FIFO: the stop marker sits behind them), and join the
        worker."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._queue.put(_STOP)
        self._worker.join(timeout=timeout)
        # a submit that raced the close flag can land behind the stop
        # marker; its future must fail, never hang a caller forever
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _STOP:     # late _Request or _SwapCmd alike
                req.future.set_exception(
                    RuntimeError("batcher closed before the request "
                                 "was served"))

    # -- worker side ---------------------------------------------------
    def _maybe_shed(self, req: _Request) -> bool:
        """Overload policy at dequeue time: shed ``req`` (resolve its
        future with :class:`SheddingError`, True) when the pending
        backlog exceeds ``shed_queue_rows`` or the request has already
        waited past ``shed_p99_ms``. Runs on the worker thread only;
        the bookkeeping writes share the caller-side lock."""
        reason = None
        age_ms = (time.perf_counter() - req.t_submit) * 1e3
        n = req.rows.shape[0]
        with self._lock:
            # the backlog BEHIND this request decides the queue-depth
            # shed: counting the request's own rows would deterministically
            # shed any single request larger than the threshold even on
            # an idle server
            backlog = self._pending_rows - n
            if 0 < self._shed_queue_rows < backlog:
                reason = (f"{backlog} rows queued behind this request, "
                          f"over the {self._shed_queue_rows}-row shed "
                          "threshold; oldest requests are dropped so "
                          "fresh ones keep bounded latency")
            elif 0 < self._shed_p99_ms < age_ms:
                reason = (f"request waited {age_ms:.1f} ms, past the "
                          f"{self._shed_p99_ms:g} ms latency budget")
            if reason is None:
                return False
            self._pending_rows -= n
            self._shed_total += 1
            self._shed_rows += n
        req.future.set_exception(SheddingError(
            f"request shed under load: {reason} (retry later or "
            "against another replica)"))
        return True

    def _worker_loop(self) -> None:
        while True:
            req = self._queue.get()
            if req is _STOP:
                return
            if isinstance(req, _SwapCmd):
                self._apply_swap(req)
                continue
            if self._maybe_shed(req):
                continue
            if req.trace is not None:
                req.t_dequeue = time.perf_counter()
            batch: List[_Request] = [req]
            n = req.rows.shape[0]
            deadline = time.perf_counter() + self._window_s
            stop_after = False
            pending_swap: Optional[_SwapCmd] = None
            while n < self._max_batch_rows:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop_after = True
                    break
                if isinstance(nxt, _SwapCmd):
                    pending_swap = nxt   # close the batch, swap after
                    break
                if self._maybe_shed(nxt):
                    continue
                if nxt.trace is not None:
                    nxt.t_dequeue = time.perf_counter()
                batch.append(nxt)
                n += nxt.rows.shape[0]
            self._run_batch(batch)
            if pending_swap is not None:
                self._apply_swap(pending_swap)
            if stop_after:
                return

    def _run_batch(self, batch: List[_Request]) -> None:
        forest = self._current_forest()
        X = batch[0].rows if len(batch) == 1 else \
            np.concatenate([r.rows for r in batch])
        err: Optional[BaseException] = None
        t_dispatch = time.perf_counter()
        try:
            # device dispatch OUTSIDE the lock: a slow batch must not
            # block submit()/stats() on other threads
            out = forest.predict_raw(X)
        except BaseException as e:
            err = e
            out = None
        done = time.perf_counter()
        with self._lock:
            self._pending_rows -= X.shape[0]
            self._requests_total += len(batch)
            self._rows_total += X.shape[0]
            self._batches_total += 1
            if err is None:
                for r in batch:
                    self._latencies.append(done - r.t_submit)
        off = 0
        for r in batch:
            k = r.rows.shape[0]
            if err is not None:
                r.future.set_exception(err)
            else:
                # stamp WHICH forest produced the scores before
                # resolving (the future's internal condition orders
                # this write before result() returns): a consumer that
                # finalizes raw scores across a hot swap must use the
                # producing model's transform, not the current one
                r.future.serving_forest = forest
                if r.trace is not None:
                    # perf_counter checkpoints for the daemon's spans:
                    # queue wait = dequeue - submit, batch window =
                    # dispatch - dequeue, device = done - dispatch
                    r.future.trace = r.trace
                    r.future.trace_times = (
                        r.t_submit, r.t_dequeue or t_dispatch,
                        t_dispatch, done)
                r.future.set_result(out[off:off + k])
            off += k
