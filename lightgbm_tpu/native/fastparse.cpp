// Fast delimited-text parser — the native data-loader component.
//
// Re-design of the reference's C++ parsing stack
// (/root/reference/src/io/parser.cpp CSVParser/TSVParser +
// include/LightGBM/utils/text_reader.h + the vendored
// fast_double_parser): one OpenMP pass over an mmap-style buffer,
// line ranges split per thread, std::from_chars for float decoding.
// Exposed through plain C symbols consumed via ctypes
// (lightgbm_tpu/utils/native.py) — no pybind11 dependency.
//
// Layout contract: the caller allocates out[n_rows * n_cols] float64;
// unparseable / empty cells become NaN (the reference's missing-value
// convention for dense text loads).

#include <algorithm>
#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <limits>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Count data rows and detect the column count + delimiter.
// Returns 0 on success. delim_out: ',', '\t' or ' '.
int ltpu_sniff(const char* buf, int64_t len, int skip_header,
               int64_t* rows_out, int64_t* cols_out, char* delim_out) {
  int64_t pos = 0;
  if (skip_header) {
    while (pos < len && buf[pos] != '\n') pos++;
    if (pos < len) pos++;
  }
  // find first non-empty line for delimiter + column sniffing
  int64_t line_start = pos;
  while (line_start < len) {
    int64_t line_end = line_start;
    while (line_end < len && buf[line_end] != '\n') line_end++;
    if (line_end > line_start + 1) break;
    line_start = line_end + 1;
  }
  if (line_start >= len) return 1;
  int64_t line_end = line_start;
  char delim = ' ';
  while (line_end < len && buf[line_end] != '\n') {
    if (buf[line_end] == '\t') delim = '\t';
    else if (buf[line_end] == ',' && delim != '\t') delim = ',';
    line_end++;
  }
  int64_t cols = 1;
  for (int64_t i = line_start; i < line_end; ++i) {
    if (delim == ' ' ? (buf[i] == ' ' || buf[i] == '\t')
                     : buf[i] == delim) {
      cols++;
      if (delim == ' ')  // collapse runs of whitespace
        while (i + 1 < line_end &&
               (buf[i + 1] == ' ' || buf[i + 1] == '\t')) i++;
    }
  }
  int64_t rows = 0;
  for (int64_t i = pos; i < len; ++i)
    if (buf[i] == '\n' && i > pos && buf[i - 1] != '\n') rows++;
  if (len > pos && buf[len - 1] != '\n') rows++;  // unterminated last line
  *rows_out = rows;
  *cols_out = cols;
  *delim_out = delim;
  return 0;
}

static inline double parse_cell(const char* s, const char* e) {
  while (s < e && (*s == ' ' || *s == '\t')) s++;
  while (e > s && (*(e - 1) == ' ' || *(e - 1) == '\r')) e--;
  if (s >= e) return std::numeric_limits<double>::quiet_NaN();
  double v;
  auto res = std::from_chars(s, e, v);
  if (res.ec != std::errc()) {
    // from_chars rejects leading '+' and inf/nan spellings; fall back
    if ((e - s) >= 3 && (s[0] == 'n' || s[0] == 'N'))
      return std::numeric_limits<double>::quiet_NaN();
    char tmp[64];
    size_t m = static_cast<size_t>(e - s);
    if (m >= sizeof(tmp)) m = sizeof(tmp) - 1;
    std::memcpy(tmp, s, m);
    tmp[m] = 0;
    char* endp = nullptr;
    v = std::strtod(tmp, &endp);
    if (endp == tmp) return std::numeric_limits<double>::quiet_NaN();
  }
  return v;
}

// Parse the whole buffer into out[rows * cols] (row-major). Rows with
// fewer cells get NaN tails; extra cells are ignored.
// Returns the number of parsed rows.
int64_t ltpu_parse_dense(const char* buf, int64_t len, int skip_header,
                         char delim, int64_t rows, int64_t cols,
                         double* out) {
  int64_t pos = 0;
  if (skip_header) {
    while (pos < len && buf[pos] != '\n') pos++;
    if (pos < len) pos++;
  }
  // collect line offsets (serial, cheap) then parse cells in parallel
  std::vector<int64_t> starts;
  starts.reserve(static_cast<size_t>(rows) + 1);
  int64_t i = pos;
  while (i < len && static_cast<int64_t>(starts.size()) < rows) {
    int64_t le = i;
    while (le < len && buf[le] != '\n') le++;
    if (le > i) starts.push_back(i);
    i = le + 1;
  }
  const int64_t n = static_cast<int64_t>(starts.size());
  const bool ws = (delim == ' ');
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t r = 0; r < n; ++r) {
    int64_t s = starts[static_cast<size_t>(r)];
    int64_t e = s;
    while (e < len && buf[e] != '\n') e++;
    double* row = out + r * cols;
    int64_t c = 0;
    int64_t cs = s;
    for (int64_t k = s; k <= e && c < cols; ++k) {
      bool is_delim = (k == e) ||
          (ws ? (buf[k] == ' ' || buf[k] == '\t') : buf[k] == delim);
      if (!is_delim) continue;
      row[c++] = parse_cell(buf + cs, buf + k);
      if (ws)  // collapse whitespace runs
        while (k + 1 <= e && k + 1 < len &&
               (buf[k + 1] == ' ' || buf[k + 1] == '\t')) k++;
      cs = k + 1;
    }
    for (; c < cols; ++c)
      row[c] = std::numeric_limits<double>::quiet_NaN();
  }
  return n;
}

// Bin numerical columns of a row-major [n, F] matrix — the native
// BinMapper::ValueToBin loop (the reference bins with compiled C++ in
// dataset_loader.cpp ConstructBinMappers + bin.h ValueToBin; the numpy
// path pays ~100-160 ns/value in per-call dispatch, measured round 5,
// which at Allstate width (4228 columns) made Dataset.construct the
// wall-clock bottleneck).
//
//   X        row-major values, float32 (is_f64=0) or float64 (=1)
//   cols     [C] source column indices into X
//   bounds   concatenated per-column upper bounds (float64, ascending)
//   bnd_off  [C+1] offsets into bounds
//   nan_to   [C] bin NaN maps to (num_bins-1 for MissingType::NAN,
//            else the precomputed bin of 0.0 — identical to the numpy
//            path's where(nan -> 0.0) + searchsorted)
//   out      row-major [n, C], uint8 (out_is_u16=0) or uint16 (=1)
//
// searchsorted(side="left") == std::lower_bound; the result is clamped
// to the last bound like the numpy path.
void ltpu_bin_columns(const void* X, int is_f64, int64_t n, int64_t F,
                      const int32_t* cols, int64_t C,
                      const double* bounds, const int64_t* bnd_off,
                      const int32_t* nan_to,
                      void* out, int out_is_u16) {
  const float* xf = static_cast<const float*>(X);
  const double* xd = static_cast<const double*>(X);
  uint8_t* o8 = static_cast<uint8_t*>(out);
  uint16_t* o16 = static_cast<uint16_t*>(out);
  // column blocks keep the active bounds L2-resident; row tiles keep
  // reads row-major-contiguous and give threads false-sharing-free
  // output segments
  const int64_t CB = 64, RB = 4096;
  for (int64_t c0 = 0; c0 < C; c0 += CB) {
    const int64_t c1 = (c0 + CB < C) ? c0 + CB : C;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
    for (int64_t r0 = 0; r0 < n; r0 += RB) {
      const int64_t r1 = (r0 + RB < n) ? r0 + RB : n;
      for (int64_t r = r0; r < r1; ++r) {
        for (int64_t c = c0; c < c1; ++c) {
          const int64_t src = r * F + cols[c];
          const double v = is_f64 ? xd[src]
                                  : static_cast<double>(xf[src]);
          const double* lo = bounds + bnd_off[c];
          const int64_t nb = bnd_off[c + 1] - bnd_off[c];
          int64_t b;
          if (std::isnan(v)) {
            b = nan_to[c];
          } else {
            b = std::lower_bound(lo, lo + nb, v) - lo;
            if (b >= nb) b = nb - 1;
          }
          if (out_is_u16)
            o16[r * C + c] = static_cast<uint16_t>(b);
          else
            o8[r * C + c] = static_cast<uint8_t>(b);
        }
      }
    }
  }
}

}  // extern "C"
