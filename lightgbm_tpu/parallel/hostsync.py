"""Host-level collective transport with watchdog deadlines.

Every cross-host sync this package performs outside the jitted training
step is a *host* collective: small numpy vectors (step-consistency
checks, phase-skew snapshots) or byte blobs (serialized BinMappers,
binned row shards) exchanged between processes. Two transports provide
them:

``device``
    ``jax.experimental.multihost_utils`` — the payload rides the
    accelerator interconnect as a jitted allgather. The right choice on
    TPU/GPU pods, where it is by far the fastest path for large blobs.

``kv``
    The coordination-service key-value store that
    ``jax.distributed.initialize`` already stands up (plain gRPC to the
    rank-0 coordinator). Works on every backend — including CPU, whose
    XLA backend (jaxlib <= 0.4.x) refuses multiprocess computations
    outright — and gives *per-rank* visibility: each rank publishes
    under its own key, so a stalled peer is named exactly ("heard from
    ranks 0,2; rank 1 silent"), which a device allgather can never
    attribute.

``auto`` (default) picks ``device`` when the backend can actually run
multiprocess computations and ``kv`` otherwise;
``LIGHTGBM_TPU_HOSTSYNC=kv|device`` overrides.

Every operation runs under the collective watchdog
(:mod:`~lightgbm_tpu.resilience.watchdog`): a hang or transport error
becomes a ``LightGBMError`` naming the collective, the iteration, and
the last rank heard from, instead of blocking forever.
"""

from __future__ import annotations

import io
import itertools
import os
import threading
import time
from typing import List, Optional

import numpy as np

from ..resilience import watchdog

__all__ = ["host_allgather", "host_broadcast_bytes", "transport"]

#: per-process collective sequence number. SPMD processes execute the
#: identical sequence of host collectives (that contract is what
#: verify_step_consistency enforces), so the counter agrees across
#: ranks and makes every collective's key set unique within a run.
_SEQ = itertools.count()

#: payloads above this size get their kv keys deleted after a
#: completion barrier; smaller keys are deleted lazily (below) so the
#: coordinator's store stays bounded without a barrier per collective.
_KV_CLEANUP_BYTES = 1 << 16

#: this process's published small keys awaiting deletion. Safe to
#: delete once a LATER gather completes: completing gather epoch E
#: required reading every rank's epoch-E key, hence every rank had
#: already finished every epoch < E (and with it, every read of our
#: older keys). Every ``_kv_exchange`` runs on a FRESH watchdog worker
#: thread (and concurrent trainers on separate host threads share this
#: module), so mutations go through ``_pending_lock`` — copy under the
#: lock, talk to the kv store outside it (tpulint TPL008 proves this
#: on the lock-acquisition CFG).
_pending_delete: List[str] = []
_pending_lock = threading.Lock()


def _kv_client():
    from jax._src import distributed
    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "host collective requested before jax.distributed was "
            "initialized (call init_distributed first)")
    return client


def transport() -> str:
    """The effective transport: ``device`` or ``kv``."""
    mode = os.environ.get("LIGHTGBM_TPU_HOSTSYNC", "auto").lower()
    if mode in ("kv", "device"):
        return mode
    if mode != "auto":
        from ..utils.log import log_warning
        log_warning(f"LIGHTGBM_TPU_HOSTSYNC={mode!r} is not auto|kv|"
                    "device; using auto")
    import jax

    # jaxlib's CPU backend (<= 0.4.x) rejects multiprocess computations
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"), which rules the device transport out for CPU meshes
    return "kv" if jax.default_backend() == "cpu" else "device"


class _StalledRank(RuntimeError):
    """A peer did not publish within the deadline (kv transport). The
    watchdog classifies this as a timeout via ``is_timeout``."""

    is_timeout = True


def _deadline_ms() -> int:
    limit = watchdog.deadline_seconds()
    if limit <= 0:
        # watchdog explicitly disabled: honor it on the kv transport
        # too — block essentially forever rather than smuggling the
        # default deadline back in
        return 7 * 24 * 3600 * 1000
    return max(1000, int(limit * 1000))


def _outer_deadline() -> Optional[float]:
    """Watchdog deadline for the thread wrapping a kv collective: the
    kv gets time out at the configured deadline themselves (with exact
    per-rank attribution — "rank 1 never published"), so the outer
    thread deadline only backstops a hung gRPC client and must not
    race the inner one. None keeps guarded()'s own resolution."""
    limit = watchdog.deadline_seconds()
    if limit <= 0:
        return limit     # watchdog disabled: pass the 0 through
    return limit * 1.5 + 10.0


def _array_to_bytes(a: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return buf.getvalue()


def _array_from_bytes(b: bytes) -> np.ndarray:
    return np.load(io.BytesIO(b), allow_pickle=False)


def _kv_exchange(name: str, payload: Optional[bytes],
                 gather: bool) -> List[Optional[bytes]]:
    """One kv collective: every rank publishes (``gather``) or only
    rank 0 does (broadcast), then every rank reads the expected keys.
    Per-rank blocking gets share one overall deadline, so the first
    silent peer is named with the ranks already heard from."""
    import jax

    client = _kv_client()
    me, nproc = jax.process_index(), jax.process_count()
    seq = next(_SEQ)
    prefix = f"lgbm_hostsync/{seq}/{name}"
    deadline_ms = _deadline_ms()
    if payload is not None:
        client.key_value_set_bytes(f"{prefix}/{me}", payload)
    readers = range(nproc) if gather else (0,)
    out: List[Optional[bytes]] = [None] * nproc
    heard: List[int] = []
    t0 = time.monotonic()
    for r in readers:
        if r == me and payload is not None:
            out[r] = payload
            heard.append(r)
            continue
        left_ms = deadline_ms - int((time.monotonic() - t0) * 1000)
        try:
            out[r] = client.blocking_key_value_get_bytes(
                f"{prefix}/{r}", max(1, left_ms))
        except Exception as e:
            if "DEADLINE_EXCEEDED" not in str(e):
                raise
            raise _StalledRank(
                f"rank {r} never published its '{name}' payload "
                f"(heard from ranks {heard or 'none'}; "
                f"{nproc} expected)") from e
        heard.append(r)
    size = max((len(b) for b in out if b is not None), default=0)
    if size > _KV_CLEANUP_BYTES:
        left_ms = deadline_ms - int((time.monotonic() - t0) * 1000)
        client.wait_at_barrier(f"{prefix}/done", max(1, left_ms))
        if payload is not None:
            client.key_value_delete(f"{prefix}/{me}")
    elif payload is not None:
        doomed: List[str] = []
        if gather:
            # completing a gather proves every rank finished all
            # earlier epochs, so our previously published keys are
            # dead — snapshot-and-clear under the lock, delete outside
            # it (kv deletes are gRPC round trips; never hold the lock
            # across them)
            with _pending_lock:
                doomed, _pending_delete[:] = list(_pending_delete), []
        for key in doomed:
            try:
                client.key_value_delete(key)
            except Exception:
                pass
        with _pending_lock:
            _pending_delete.append(f"{prefix}/{me}")
    return out


def host_allgather(arr: np.ndarray, name: str,
                   iteration: Optional[int] = None) -> np.ndarray:
    """Allgather one equal-shaped host array: returns ``[P, *shape]``.
    Watchdog-guarded; single-process returns ``arr[None]``."""
    import jax

    nproc = jax.process_count()
    arr = np.asarray(arr)
    if nproc <= 1:
        return arr[None]

    if transport() == "device":
        def _run():
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(arr))

        return watchdog.guarded(name, _run, iteration=iteration,
                                world=nproc)

    def _run():
        parts = _kv_exchange(name, _array_to_bytes(arr), gather=True)
        return np.stack([_array_from_bytes(p) for p in parts])

    return watchdog.guarded(name, _run, iteration=iteration,
                            world=nproc, deadline=_outer_deadline())


def host_broadcast_bytes(payload: Optional[bytes], name: str,
                         iteration: Optional[int] = None) -> bytes:
    """Broadcast rank 0's byte blob to every process (rank 0 passes the
    payload, others pass None). Watchdog-guarded; single-process
    returns the payload unchanged."""
    import jax

    nproc = jax.process_count()
    if nproc <= 1:
        return payload if payload is not None else b""

    if transport() == "device":
        def _run():
            from jax.experimental import multihost_utils

            # length-prefix so every process allocates the same buffer;
            # only rank 0's bytes matter (other ranks' payloads, if
            # passed, may differ in size)
            n = np.asarray([len(payload or b"")], np.int32)
            n = multihost_utils.broadcast_one_to_all(n)
            buf = np.zeros(int(n[0]), np.uint8)
            if jax.process_index() == 0:
                buf[: len(payload)] = np.frombuffer(payload, np.uint8)
            buf = multihost_utils.broadcast_one_to_all(buf)
            return bytes(buf.tobytes())

        return watchdog.guarded(name, _run, iteration=iteration,
                                world=nproc)

    def _run():
        me = jax.process_index()
        parts = _kv_exchange(
            name, payload if me == 0 else None, gather=False)
        return parts[0]

    return watchdog.guarded(name, _run, iteration=iteration,
                            world=nproc, deadline=_outer_deadline())
