"""Device mesh construction and sharding helpers.

Replaces the reference's entire network layer
(/root/reference/src/network/: Linkers socket/MPI mesh construction,
BruckMap/RecursiveHalvingMap topologies, network.cpp collectives): on TPU
there is no linker handshake — the mesh IS the topology, and XLA emits
the collectives (SURVEY.md §2.6 TPU mapping). Multi-host is reached via
``jax.distributed.initialize`` + the same mesh spanning all processes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "shard_rows", "replicate", "DATA_AXIS",
           "pad_rows"]

DATA_AXIS = "data"


def make_mesh(num_devices: int = 0, axis_name: str = DATA_AXIS,
              devices: Optional[Sequence] = None) -> Mesh:
    """1-D data-parallel mesh over available devices.

    The reference analog is Network::Init (rank/num_machines from the
    socket or MPI world); here the 'world' is jax.devices() — spanning
    hosts automatically under jax.distributed.
    """
    if devices is None:
        devices = jax.devices()
        if jax.process_count() > 1 and jax.default_backend() == "cpu":
            # jaxlib <= 0.4.x's CPU backend refuses multiprocess XLA
            # computations outright, so a global mesh could never run
            # a jitted collective. In a kv-transport world
            # (parallel/hostsync.py picks kv on CPU for the same
            # reason) every process runs the identical replicated
            # program over its OWN local devices; the cross-rank
            # surface is exactly the host-level sync points.
            devices = jax.local_devices()
    if num_devices and num_devices > 0:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def pad_rows(n: int, num_devices: int) -> int:
    """Rows of padding needed so every device holds an equal shard."""
    return (-n) % num_devices


def shard_rows(mesh: Mesh, arr, row_axis: int = 0):
    """Place an array with rows sharded over the mesh's data axis."""
    spec = [None] * arr.ndim
    spec[row_axis] = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(*spec))
    return jax.device_put(arr, sharding)


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P()))
