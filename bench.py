"""Benchmark: boosting iterations/sec + held-out AUC on a Higgs-shaped
synthetic dataset.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...}.

Baseline (BASELINE.md): reference LightGBM trains Higgs-10M (10.5M x 28,
255 bins, 255 leaves) at 500 iters / 130.094 s = 3.843 iters/sec on a
28-thread 2x E5-2670v2 (docs/Experiments.rst:111-123). ``vs_baseline`` is
our iters/sec divided by that number, linearly rescaled to the 10.5M-row
workload when BENCH_ROWS is smaller (histogram work is O(rows); the
rescale factor is 1 at the full shape).

Accuracy: ``auc`` is the held-out AUC after BENCH_AUC_ITERS boosting
rounds, and ``auc_ref`` is the reference implementation's AUC trained on
the byte-identical dataset/params (measured once with an oracle build of
/root/reference at v4.6.0.99, 50 rounds, lr 0.1, 255 leaves/bins; the
synthetic task is separable so both sit near 0.97 — parity, not the
absolute Higgs 0.8457, is the check).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BASELINE_ITERS_PER_SEC = 500.0 / 130.094
HIGGS_ROWS = 10_500_000

# Resilience: the driver runs this through a TPU tunnel that has died
# mid-round twice (BENCH_r01/r03 captured stack traces, not numbers).
# Probe the backend with retry/backoff before committing to the big
# run, and on hard failure still emit the ONE json line — with an
# "error" field and the last builder-measured number — so the round
# record is data, not a traceback.
PROBE_RETRIES = int(os.environ.get("BENCH_PROBE_RETRIES", 10))
PROBE_BACKOFF_S = float(os.environ.get("BENCH_PROBE_BACKOFF", 30.0))
# a half-dead tunnel can make backend init HANG rather than raise;
# each probe attempt runs in a subprocess bounded by this timeout
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 180))
# last full-scale number measured by the builder on a real chip
# (10.5M x 28, 255 leaves/bins; see benchmarks/PROFILE.md)
LAST_MEASURED = {"value": 1.12, "unit": "iters/sec",
                 "vs_baseline": 0.293, "commit": "3cef1da"}


def _git_head():
    try:
        return subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip()
    except Exception:
        return "unknown"


def _probe_backend():
    """Wait for a usable JAX backend; returns jax or raises last error.

    The probe runs in a SUBPROCESS with a hard timeout: a dead tunnel
    can make backend init either raise (caught) or HANG in native code
    holding the GIL (where in-process SIGALRM never fires — observed
    round 4). The parent only imports jax once a probe succeeded."""
    last = None
    for attempt in range(PROBE_RETRIES):
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; jax.devices(); print('BENCH_PROBE_OK')"],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S)
            if r.returncode == 0 and "BENCH_PROBE_OK" in r.stdout:
                try:
                    import jax
                    jax.devices()
                    return jax
                except Exception as e:
                    # the tunnel died in the probe->init window; jax
                    # caches the failed backend init in-process, so a
                    # retry needs a fresh interpreter: re-exec with a
                    # decremented budget
                    sys.stderr.write(
                        f"bench: parent backend init failed after a "
                        f"successful probe: {e}\n")
                    if attempt + 1 < PROBE_RETRIES:
                        time.sleep(PROBE_BACKOFF_S)
                        env = dict(os.environ)
                        env["BENCH_PROBE_RETRIES"] = str(
                            PROBE_RETRIES - attempt - 1)
                        os.execve(sys.executable,
                                  [sys.executable] + sys.argv, env)
                    raise
            tail = (r.stderr or r.stdout).strip().splitlines()
            last = RuntimeError(tail[-1] if tail else
                                f"probe rc={r.returncode}")
        except subprocess.TimeoutExpired:
            last = TimeoutError(
                f"backend init hung > {PROBE_TIMEOUT_S}s "
                "(tunnel half-dead)")
        except Exception as e:
            last = e
        sys.stderr.write(
            f"bench: backend probe {attempt + 1}/{PROBE_RETRIES} "
            f"failed: {last}\n")
        if attempt + 1 < PROBE_RETRIES:
            time.sleep(PROBE_BACKOFF_S)
    raise last


def _emit_failure(err):
    """One JSON line recording the failure + the last known number."""
    shape = "Allstate-shaped" if _ALLSTATE else "Higgs-shaped"
    result = {
        "metric": f"boosting iters/sec, {shape} "
                  f"{N_ROWS}x{N_FEATURES}, {NUM_LEAVES} leaves, "
                  f"{MAX_BIN} bins (BENCH FAILED - last measured value "
                  "reported)",
        "value": LAST_MEASURED["value"],
        "unit": LAST_MEASURED["unit"],
        "vs_baseline": LAST_MEASURED["vs_baseline"],
        "error": f"{type(err).__name__}: {err}"[:500],
        "measured_at_commit": LAST_MEASURED["commit"],
        "failed_at_commit": _git_head(),
    }
    print(json.dumps(result))

# BENCH_PRESET=allstate: the wide-sparse EFB path (13.2M x 4228
# one-hot-ish features w/ NaN, docs/Experiments.rst:121 Allstate shape;
# reference trains it in 148.231 s / 500 iters = 3.373 iters/sec).
# Default preset: the REAL Higgs shape — measured, not extrapolated.
PRESET = os.environ.get("BENCH_PRESET", "higgs")
_ALLSTATE = PRESET == "allstate"
ALLSTATE_ROWS = 13_184_290
ALLSTATE_BASELINE_ITERS_PER_SEC = 500.0 / 148.231
N_ROWS = int(os.environ.get(
    "BENCH_ROWS", ALLSTATE_ROWS if _ALLSTATE else HIGGS_ROWS))
N_FEATURES = int(os.environ.get("BENCH_FEATURES",
                                4228 if _ALLSTATE else 28))
NUM_LEAVES = int(os.environ.get("BENCH_LEAVES", 255))
MAX_BIN = int(os.environ.get("BENCH_BINS", 255))
WARMUP = int(os.environ.get("BENCH_WARMUP", 1))
ITERS = int(os.environ.get("BENCH_ITERS", 5))
AUC_ITERS = int(os.environ.get("BENCH_AUC_ITERS", 50))
N_VALID = int(os.environ.get("BENCH_VALID", 524_288))

# oracle (reference build, v4.6.0.99) held-out AUC on the identical
# seed-0 dataset, 50 rounds: measured via /tmp oracle runs of
# /root/reference with the same make_higgs_like generator
ORACLE_AUC = {1_048_576: 0.967940, 10_500_000: 0.967607}


def make_higgs_like(n, f, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f).astype(np.float32)
    coef = rs.randn(f).astype(np.float32)
    logits = X @ coef * 0.5 + 0.5 * rs.randn(n).astype(np.float32)
    y = (logits > 0).astype(np.float32)
    return X.astype(np.float64), y.astype(np.float64)


def make_allstate_like(n, f, seed=0, per_group=128):
    """Wide sparse one-hot blocks + NaN (the Allstate/Bosch shape EFB
    exists for): f features in blocks of ``per_group``, one nonzero
    per row per block, ~10% of nonzeros NaN-ified. Generated in row
    chunks so the [n, f] float64 matrix is the only big allocation."""
    rs = np.random.RandomState(seed)
    groups = f // per_group
    X = np.zeros((n, f), np.float32)
    signal = np.zeros(n, np.float32)
    vals = rs.rand(groups, per_group).astype(np.float32) * 2
    for g in range(groups):
        pick = rs.randint(0, per_group, n)
        rows = np.arange(n)
        X[rows, g * per_group + pick] = vals[g, pick]
        signal += vals[g, pick]
    nanmask = rs.rand(n) < 0.1
    X[nanmask, 0] = np.nan
    y = (signal > np.median(signal)).astype(np.float32)
    return X.astype(np.float64), y.astype(np.float64)


def auc(y, p):
    o = np.argsort(p)
    r = np.empty(len(p))
    r[o] = np.arange(1, len(p) + 1)
    npos = y.sum()
    nneg = len(y) - npos
    return (r[y == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg)


def main():
    # persistent XLA compilation cache: the grower compiles once per
    # (shape, config); repeated bench runs skip the 20-40s TPU compile
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.expanduser("~/.cache/lightgbm_tpu/xla"))
    jax = _probe_backend()
    import lightgbm_tpu as lgb

    gen = make_allstate_like if _ALLSTATE else make_higgs_like
    X, y = gen(N_ROWS + N_VALID, N_FEATURES)
    # slice-copies so `del X` actually frees the big base array
    Xv, yv = X[N_ROWS:].copy(), y[N_ROWS:].copy()
    Xtr = X[:N_ROWS].copy()
    del X
    ds = lgb.Dataset(Xtr, label=y[:N_ROWS], params={"max_bin": MAX_BIN})
    ds.construct()
    del Xtr

    bst = lgb.Booster(
        params={
            "objective": "binary",
            "num_leaves": NUM_LEAVES,
            "max_bin": MAX_BIN,
            "learning_rate": 0.1,
            "verbosity": -1,
        },
        train_set=ds)

    for _ in range(WARMUP):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()

    t0 = time.time()
    for _ in range(ITERS):
        bst._engine.train_one_iter()
    bst._engine.score.block_until_ready()
    dt = time.time() - t0

    # accuracy leg: continue to AUC_ITERS rounds, then held-out AUC
    result_auc = None
    trained = WARMUP + ITERS
    if AUC_ITERS > trained:
        for _ in range(AUC_ITERS - trained):
            bst._engine.train_one_iter()
        result_auc = float(auc(yv, bst.predict(Xv)))

    iters_per_sec = ITERS / dt
    # linear rescale to the preset's full row count (histogram work is
    # O(rows); the factor is 1 at the default shape, so normally this
    # is a direct measurement)
    full_rows = ALLSTATE_ROWS if _ALLSTATE else HIGGS_ROWS
    base = ALLSTATE_BASELINE_ITERS_PER_SEC if _ALLSTATE \
        else BASELINE_ITERS_PER_SEC
    iters_per_sec_full = iters_per_sec * (N_ROWS / full_rows)
    scale_note = "" if N_ROWS == full_rows \
        else f" (rescaled to {full_rows} rows)"
    shape_name = "Allstate-shaped" if _ALLSTATE else "Higgs-shaped"
    result = {
        "metric": f"boosting iters/sec, {shape_name} "
                  f"{N_ROWS}x{N_FEATURES}"
                  f"{scale_note}, {NUM_LEAVES} leaves, "
                  f"{MAX_BIN} bins, backend={jax.default_backend()}",
        "value": round(iters_per_sec_full, 4),
        "unit": "iters/sec",
        "vs_baseline": round(iters_per_sec_full / base, 4),
    }
    if result_auc is not None:
        result["auc"] = round(result_auc, 6)
        oracle_config = (N_FEATURES == 28 and NUM_LEAVES == 255
                         and MAX_BIN == 255 and N_VALID == 524_288
                         and AUC_ITERS == 50)
        if oracle_config and N_ROWS in ORACLE_AUC:
            result["auc_ref"] = ORACLE_AUC[N_ROWS]
    print(json.dumps(result))


def _supervise():
    """Run the real bench in a child process under a hard timeout.

    The parent holds no jax state, so it can ALWAYS emit the one-line
    JSON record even when the child hangs in native backend-init code
    (the half-dead-tunnel mode where no in-process mechanism fires)."""
    hard = int(os.environ.get("BENCH_HARD_TIMEOUT", 5400))
    env = dict(os.environ, BENCH_WORKER="1")
    try:
        r = subprocess.run([sys.executable] + sys.argv,
                           env=env, timeout=hard)
        if r.returncode != 0:
            _emit_failure(RuntimeError(
                f"bench worker exited rc={r.returncode}"))
    except subprocess.TimeoutExpired:
        _emit_failure(TimeoutError(
            f"bench worker exceeded BENCH_HARD_TIMEOUT={hard}s "
            "(hung backend init or run)"))


if __name__ == "__main__":
    if os.environ.get("BENCH_WORKER") != "1":
        _supervise()
    else:
        try:
            main()
        except Exception as err:  # emit data, never a bare stack trace
            import traceback
            traceback.print_exc(file=sys.stderr)
            _emit_failure(err)
