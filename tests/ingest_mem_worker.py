"""Memory-budget proof for streaming ingestion (run as a subprocess by
tests/test_data_ingest.py::test_peak_rss_bounded_by_chunk_footprint...).

Constructs a Dataset from a generator source whose total size is >= 10x
the chunk size, with NO jax import anywhere (the data/ path is
jax-lazy), and reports ru_maxrss deltas as one JSON line:

- ``delta_mb``   — peak-RSS growth across the construct
- ``raw_mb``     — what the dense float64 matrix alone would cost
- ``budget_mb``  — binned product + sample + label + chunk slack

The assertion (made by the test) is delta < raw/2 and delta < budget:
peak memory scales with the chunk footprint and the binned product,
never with the raw dataset.
"""

import json
import os
import resource
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

from lightgbm_tpu.basic import Dataset
from lightgbm_tpu.data import GeneratorChunkSource

N = 1 << 20          # 1,048,576 rows
F = 64
CHUNK = 16384        # 64 chunks: dataset is 64x the chunk size
SAMPLE = 20000


def chunks():
    start = 0
    while start < N:
        c = min(CHUNK, N - start)
        rs = np.random.RandomState(start % (2 ** 31 - 1))
        X = rs.randn(c, F).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        yield X, y
        start += c


def rss_mb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main():
    # warm numpy + the generator once so the baseline includes every
    # fixed cost (interpreter, numpy pools, one chunk buffer)
    for Xc, yc in chunks():
        del Xc, yc
        break
    base = rss_mb()

    src = GeneratorChunkSource(chunks, num_rows=N, num_features=F)
    ds = Dataset(src, params={"verbosity": -1, "max_bin": 63,
                              "bin_construct_sample_cnt": SAMPLE,
                              "ingest_chunk_rows": CHUNK})
    ds.construct()
    assert ds.num_data() == N
    delta = rss_mb() - base

    bins_mb = ds.host_bins().nbytes / 2 ** 20
    raw_mb = N * F * 8 / 2 ** 20                      # float64 matrix
    sample_mb = SAMPLE * F * 8 / 2 ** 20
    label_mb = N * 8 / 2 ** 20
    chunk_mb = CHUNK * F * 8 / 2 ** 20
    # generous slack for allocator overhead / transient copies, still
    # far below the raw matrix
    budget_mb = bins_mb + sample_mb + label_mb + 12 * chunk_mb + 64
    print(json.dumps({
        "delta_mb": round(delta, 1),
        "raw_mb": round(raw_mb, 1),
        "bins_mb": round(bins_mb, 1),
        "budget_mb": round(budget_mb, 1),
        "base_mb": round(base, 1),
    }))


if __name__ == "__main__":
    main()
